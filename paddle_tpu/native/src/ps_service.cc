// Networked parameter-server service over the sharded sparse table.
//
// TPU-native rebuild of the reference's brpc PS service layer
// (paddle/fluid/distributed/ps/service/brpc_ps_server.cc request dispatch,
// brpc_ps_client.cc client stubs, ps_client.h PSClient API): a plain-TCP
// length-prefixed binary protocol instead of brpc/protobuf — the payloads
// are dense numpy buffers, so there is nothing for an IDL to describe, and
// zero-copy in/out of the table is the whole game. Each server process owns
// ONE table instance (a shard of the global key space); clients partition
// keys by hash across servers (HeterComm shard-by-hash restated host-side).
//
// Frame format (little-endian, x86/ARM hosts):
//   request:  [u32 body_len][u8 op][body ...]
//   reply:    [i32 status][u32 body_len][body ...]   status<0 => error
//
// Ops: PULL keys->rows, PUSH keys+grads, SIZE, KEYS, SAVE, LOAD(merge flag),
// SHRINK, SET_LR, BARRIER(world) — the worker-sync primitive the reference
// routes through its Gloo/brpc barrier — and STOP.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {
// table C API (ps_table.cc)
void pt_table_pull(void* h, const int64_t* keys, int64_t n, float* out);
void pt_table_push(void* h, const int64_t* keys, const float* grads, int64_t n);
int64_t pt_table_size(void* h);
int64_t pt_table_keys(void* h, int64_t* out, int64_t cap);
int64_t pt_table_shrink(void* h, float threshold);
int32_t pt_table_save(void* h, const char* path);
int32_t pt_table_load(void* h, const char* path);
int32_t pt_table_load_merge(void* h, const char* path);
void pt_table_set_lr(void* h, float lr);
int32_t pt_table_dim(void* h);
}

namespace {

// Largest body we will buffer for one request. Bounds the allocation a
// single malformed/hostile frame can force (a bogus u32 length of ~4 GiB
// would otherwise be handed straight to resize() and bad_alloc the server).
// 256 MiB covers any sane batch: push of n keys costs n*(8 + 4*dim) bytes,
// so even dim=512 allows ~130k keys per request.
constexpr uint32_t kMaxFrameLen = 256u << 20;

enum Op : uint8_t {
  kPull = 1,
  kPush = 2,
  kSize = 3,
  kSave = 4,
  kLoad = 5,
  kShrink = 6,
  kSetLr = 7,
  kBarrier = 8,
  kKeys = 9,
  kStop = 10,
};

bool ReadFull(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool SendReply(int fd, int32_t status, const void* body, uint32_t len) {
  char hdr[8];
  std::memcpy(hdr, &status, 4);
  std::memcpy(hdr + 4, &len, 4);
  if (!WriteFull(fd, hdr, 8)) return false;
  return len == 0 || WriteFull(fd, body, len);
}

class PsServer {
 public:
  PsServer(void* table, int listen_fd, int port)
      : table_(table), listen_fd_(listen_fd), port_(port) {
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  int port() const { return port_; }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) {
      // another thread (e.g. the detached kStop handler) is stopping; wait
      // for it so stop-then-destroy can't free the server under its feet
      Wait();
      return;
    }
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      // only fds of still-running workers: a finished worker has already
      // closed its fd and the number may have been recycled by the OS
      std::lock_guard<std::mutex> g(conn_mu_);
      for (auto& w : workers_) {
        if (!w->done.load()) ::shutdown(w->fd, SHUT_RDWR);
      }
    }
    // release any barrier waiters so their threads can exit
    {
      std::lock_guard<std::mutex> g(barrier_mu_);
      barrier_gen_++;
      barrier_count_ = 0;
    }
    barrier_cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    std::vector<std::unique_ptr<Worker>> workers;
    {
      std::lock_guard<std::mutex> g(conn_mu_);
      workers.swap(workers_);
    }
    for (auto& w : workers) {
      if (w->thread.joinable()) w->thread.join();
    }
    std::lock_guard<std::mutex> g(stopped_mu_);
    stopped_ = true;
    stopped_cv_.notify_all();
  }

  void Wait() {
    std::unique_lock<std::mutex> l(stopped_mu_);
    stopped_cv_.wait(l, [this] { return stopped_; });
  }

  ~PsServer() { Stop(); }

 private:
  struct Worker {
    std::thread thread;
    std::atomic<bool> done{false};
    int fd = -1;
  };

  void AcceptLoop() {
    while (!stopping_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(conn_mu_);
      // reap finished workers so short-lived connections (barriers) don't
      // accumulate dead thread objects for the life of the server
      for (auto it = workers_.begin(); it != workers_.end();) {
        if ((*it)->done.load()) {
          if ((*it)->thread.joinable()) (*it)->thread.join();
          it = workers_.erase(it);
        } else {
          ++it;
        }
      }
      workers_.emplace_back(new Worker);
      Worker* w = workers_.back().get();
      w->fd = fd;
      w->thread = std::thread([this, w] { Serve(w); });
    }
  }

  void Serve(Worker* w) {
    const int fd = w->fd;
    std::vector<char> body;
    while (!stopping_.load()) {
      char hdr[5];
      if (!ReadFull(fd, hdr, 5)) break;
      uint32_t len;
      std::memcpy(&len, hdr, 4);
      uint8_t op = static_cast<uint8_t>(hdr[4]);
      if (len > kMaxFrameLen) {
        // reply, then close: the oversized body is still in flight, so the
        // stream cannot be re-synchronized without reading it all
        SendReply(fd, -11, nullptr, 0);
        break;
      }
      body.resize(len);
      if (len && !ReadFull(fd, body.data(), len)) break;
      if (!Dispatch(fd, op, body.data(), len)) break;
    }
    // done BEFORE close: Stop() only shutdown()s fds of workers with
    // done == false, so it can never hit a recycled fd number
    w->done.store(true);
    ::close(fd);
  }

  bool Dispatch(int fd, uint8_t op, const char* body, uint32_t len) {
    const int32_t dim = pt_table_dim(table_);
    // All size arithmetic in uint64 and every fixed-width field checked
    // against len BEFORE the memcpy: a malformed or hostile frame must get
    // an error reply, never an out-of-bounds read.
    switch (op) {
      case kPull: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0);
        uint32_t n;
        std::memcpy(&n, body, 4);
        if (static_cast<uint64_t>(len) != 4 + static_cast<uint64_t>(n) * 8)
          return SendReply(fd, -10, nullptr, 0);
        const int64_t* keys = reinterpret_cast<const int64_t*>(body + 4);
        std::vector<float> rows(static_cast<size_t>(n) * dim);
        pt_table_pull(table_, keys, n, rows.data());
        return SendReply(fd, 0, rows.data(),
                         static_cast<uint32_t>(rows.size() * 4));
      }
      case kPush: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0);
        uint32_t n;
        std::memcpy(&n, body, 4);
        if (static_cast<uint64_t>(len) !=
            4 + static_cast<uint64_t>(n) * 8 +
                static_cast<uint64_t>(n) * dim * 4)
          return SendReply(fd, -10, nullptr, 0);
        const int64_t* keys = reinterpret_cast<const int64_t*>(body + 4);
        const float* grads = reinterpret_cast<const float*>(body + 4 + n * 8);
        pt_table_push(table_, keys, grads, n);
        return SendReply(fd, 0, nullptr, 0);
      }
      case kSize: {
        int64_t sz = pt_table_size(table_);
        return SendReply(fd, 0, &sz, 8);
      }
      case kKeys: {
        int64_t cap = pt_table_size(table_);
        std::vector<int64_t> keys(static_cast<size_t>(cap));
        int64_t w = pt_table_keys(table_, keys.data(), cap);
        return SendReply(fd, 0, keys.data(), static_cast<uint32_t>(w * 8));
      }
      case kSave: {
        std::string path(body, len);
        int32_t rc = pt_table_save(table_, path.c_str());
        return SendReply(fd, rc, nullptr, 0);
      }
      case kLoad: {
        if (len < 1) return SendReply(fd, -10, nullptr, 0);
        bool merge = body[0] != 0;
        std::string path(body + 1, len - 1);
        int32_t rc = merge ? pt_table_load_merge(table_, path.c_str())
                           : pt_table_load(table_, path.c_str());
        return SendReply(fd, rc, nullptr, 0);
      }
      case kShrink: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0);
        float thr;
        std::memcpy(&thr, body, 4);
        int64_t dropped = pt_table_shrink(table_, thr);
        return SendReply(fd, 0, &dropped, 8);
      }
      case kSetLr: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0);
        float lr;
        std::memcpy(&lr, body, 4);
        pt_table_set_lr(table_, lr);
        return SendReply(fd, 0, nullptr, 0);
      }
      case kBarrier: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0);
        uint32_t world;
        std::memcpy(&world, body, 4);
        {
          std::unique_lock<std::mutex> l(barrier_mu_);
          uint64_t my_gen = barrier_gen_;
          if (++barrier_count_ >= world) {
            barrier_count_ = 0;
            barrier_gen_++;
            barrier_cv_.notify_all();
          } else {
            barrier_cv_.wait(l, [&] {
              return barrier_gen_ != my_gen || stopping_.load();
            });
          }
        }
        return SendReply(fd, stopping_.load() ? -1 : 0, nullptr, 0);
      }
      case kStop: {
        SendReply(fd, 0, nullptr, 0);
        // detach: Stop() joins worker threads; calling it from a worker
        // would self-join, so hand off.
        std::thread([this] { Stop(); }).detach();
        return false;
      }
      default:
        return SendReply(fd, -127, nullptr, 0);
    }
  }

  void* table_;
  int listen_fd_;
  int port_;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::mutex conn_mu_;
  std::vector<std::unique_ptr<Worker>> workers_;
  std::mutex barrier_mu_;
  std::condition_variable barrier_cv_;
  uint64_t barrier_gen_ = 0;
  uint32_t barrier_count_ = 0;
  std::mutex stopped_mu_;
  std::condition_variable stopped_cv_;
  bool stopped_ = false;
};

}  // namespace

extern "C" {

// Start serving `table` on `port` (0 = ephemeral). Returns handle or null.
void* pt_ps_server_start(void* table, int32_t port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  return new PsServer(table, fd, ntohs(addr.sin_port));
}

int32_t pt_ps_server_port(void* h) { return static_cast<PsServer*>(h)->port(); }

void pt_ps_server_stop(void* h) { static_cast<PsServer*>(h)->Stop(); }

// Block until the server stops (subprocess entrypoint main loop).
void pt_ps_server_wait(void* h) { static_cast<PsServer*>(h)->Wait(); }

void pt_ps_server_destroy(void* h) { delete static_cast<PsServer*>(h); }
}
