// Networked parameter-server service over the sharded sparse table.
//
// TPU-native rebuild of the reference's brpc PS service layer
// (paddle/fluid/distributed/ps/service/brpc_ps_server.cc request dispatch,
// brpc_ps_client.cc client stubs, ps_client.h PSClient API): a plain-TCP
// length-prefixed binary protocol instead of brpc/protobuf — the payloads
// are dense numpy buffers, so there is nothing for an IDL to describe, and
// zero-copy in/out of the table is the whole game. Each server process owns
// ONE table instance (a shard of the global key space); clients partition
// keys by hash across servers (HeterComm shard-by-hash restated host-side).
//
// Framing and connection lifecycle live in net.h (shared with the graph
// service, graph_service.cc).
//
// Ops: PULL keys->rows, PUSH keys+grads, SIZE, KEYS, SAVE, LOAD(merge flag),
// SHRINK, SET_LR, BARRIER(world) — the worker-sync primitive the reference
// routes through its Gloo/brpc barrier — and STOP.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "net.h"

extern "C" {
// table C API (ps_table.cc)
void pt_table_pull(void* h, const int64_t* keys, int64_t n, float* out);
void pt_table_push(void* h, const int64_t* keys, const float* grads, int64_t n);
void pt_table_push_raw(void* h, const int64_t* keys, const float* deltas,
                       int64_t n);
void pt_table_push_show_click(void* h, const int64_t* keys, const float* sc,
                              int64_t n);
void* pt_dense_create(int64_t len, int32_t optimizer, float lr, float eps);
void* pt_dense_create_from_file(const char* path);
int32_t pt_dense_optimizer(void* h);
void pt_dense_destroy(void* h);
int64_t pt_dense_len(void* h);
void pt_dense_set_lr(void* h, float lr);
int32_t pt_dense_get(void* h, int64_t off, int64_t n, float* out);
int32_t pt_dense_set(void* h, int64_t off, int64_t n, const float* vals);
int32_t pt_dense_push(void* h, int64_t off, int64_t n, const float* grad);
int32_t pt_dense_save(void* h, const char* path);
int32_t pt_dense_load(void* h, const char* path);
int64_t pt_table_size(void* h);
int64_t pt_table_keys(void* h, int64_t* out, int64_t cap);
int64_t pt_table_shrink(void* h, float threshold);
int32_t pt_table_save(void* h, const char* path);
int32_t pt_table_load(void* h, const char* path);
int32_t pt_table_load_merge(void* h, const char* path);
void pt_table_set_lr(void* h, float lr);
int32_t pt_table_dim(void* h);
}

namespace {

enum Op : uint8_t {
  kPull = 1,
  kPush = 2,
  kSize = 3,
  kSave = 4,
  kLoad = 5,
  kShrink = 6,
  kSetLr = 7,
  kBarrier = 8,
  kKeys = 9,
  kStop = 10,
  kPushRaw = 11,        // add deltas bypassing the rule (geo delta merge)
  kPushShowClick = 12,  // accumulate CTR usage stats
  kDenseInit = 13,      // [i64 len][i32 opt][f32 lr] — lazy dense table
  kDensePull = 14,      // [i64 off][i64 n] -> floats
  kDensePush = 15,      // [i64 off][i64 n][grads]
  kDenseSet = 16,       // [i64 off][i64 n][vals]
};

// The PS server = a FramedServer dispatching into one table, plus barrier
// state (the only op needing cross-connection coordination).
struct PsServer {
  void* table = nullptr;
  // Lazy MemoryDenseTable block (kDenseInit / snapshot restore). Atomic:
  // connection threads read it unlocked; dense_mu serializes creation.
  // Once set it is never swapped (resize -> error), so a loaded pointer
  // stays valid for the server's lifetime.
  std::atomic<void*> dense{nullptr};
  std::mutex dense_mu;

  void* DenseOrNull() { return dense.load(std::memory_order_acquire); }
  ptn::FramedServer* srv = nullptr;
  // own stopping flag (not srv->stopping()): the dispatch lambda can run
  // before Start() returns and assigns srv
  std::atomic<bool> stopping{false};
  std::mutex barrier_mu;
  std::condition_variable barrier_cv;
  uint64_t barrier_gen = 0;
  uint32_t barrier_count = 0;

  // Restore (or refresh) the dense block from `<path>.dense`. Creates the
  // table from the sidecar's own header when none exists yet (server
  // restart before any client dense_init). Absent sidecar is fine.
  int32_t LoadDenseSidecar(const std::string& path) {
    const std::string side = path + ".dense";
    std::lock_guard<std::mutex> g(dense_mu);
    void* d = dense.load(std::memory_order_relaxed);
    if (d) {
      int32_t drc = pt_dense_load(d, side.c_str());
      return (drc == 0 || drc == -1) ? 0 : drc;  // -1 = file absent
    }
    FILE* probe = std::fopen(side.c_str(), "rb");
    if (!probe) return 0;
    std::fclose(probe);
    void* fresh = pt_dense_create_from_file(side.c_str());
    if (!fresh) return -16;
    dense.store(fresh, std::memory_order_release);
    return 0;
  }

  int Dispatch(int fd, uint8_t op, const char* body, uint32_t len) {
    using ptn::SendReply;
    const int32_t dim = pt_table_dim(table);
    // All size arithmetic in uint64 and every fixed-width field checked
    // against len BEFORE the memcpy; replies larger than the frame cap are
    // rejected up front (their u32 length field would otherwise truncate
    // and desync the stream).
    switch (op) {
      case kPull: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        uint32_t n;
        std::memcpy(&n, body, 4);
        if (static_cast<uint64_t>(len) != 4 + static_cast<uint64_t>(n) * 8 ||
            static_cast<uint64_t>(n) * dim * 4 > ptn::kMaxFrameLen)
          return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        const int64_t* keys = reinterpret_cast<const int64_t*>(body + 4);
        std::vector<float> rows(static_cast<size_t>(n) * dim);
        pt_table_pull(table, keys, n, rows.data());
        return SendReply(fd, 0, rows.data(),
                         static_cast<uint32_t>(rows.size() * 4))
                   ? 0
                   : 1;
      }
      case kPush: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        uint32_t n;
        std::memcpy(&n, body, 4);
        if (static_cast<uint64_t>(len) !=
            4 + static_cast<uint64_t>(n) * 8 +
                static_cast<uint64_t>(n) * dim * 4)
          return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        const int64_t* keys = reinterpret_cast<const int64_t*>(body + 4);
        const float* grads = reinterpret_cast<const float*>(body + 4 + n * 8);
        pt_table_push(table, keys, grads, n);
        return SendReply(fd, 0, nullptr, 0) ? 0 : 1;
      }
      case kSize: {
        int64_t sz = pt_table_size(table);
        return SendReply(fd, 0, &sz, 8) ? 0 : 1;
      }
      case kKeys: {
        int64_t cap = pt_table_size(table);
        if (static_cast<uint64_t>(cap) * 8 > ptn::kMaxFrameLen)
          return SendReply(fd, -11, nullptr, 0) ? 0 : 1;
        std::vector<int64_t> keys(static_cast<size_t>(cap));
        int64_t w = pt_table_keys(table, keys.data(), cap);
        return SendReply(fd, 0, keys.data(), static_cast<uint32_t>(w * 8))
                   ? 0
                   : 1;
      }
      case kSave: {
        std::string path(body, len);
        int32_t rc = pt_table_save(table, path.c_str());
        void* d = DenseOrNull();
        if (rc == 0 && d) rc = pt_dense_save(d, (path + ".dense").c_str());
        return SendReply(fd, rc, nullptr, 0) ? 0 : 1;
      }
      case kLoad: {
        if (len < 1) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        bool merge = body[0] != 0;
        std::string path(body + 1, len - 1);
        int32_t rc = merge ? pt_table_load_merge(table, path.c_str())
                           : pt_table_load(table, path.c_str());
        if (rc == 0) rc = LoadDenseSidecar(path);
        return SendReply(fd, rc, nullptr, 0) ? 0 : 1;
      }
      case kShrink: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        float thr;
        std::memcpy(&thr, body, 4);
        int64_t dropped = pt_table_shrink(table, thr);
        return SendReply(fd, 0, &dropped, 8) ? 0 : 1;
      }
      case kSetLr: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        float lr;
        std::memcpy(&lr, body, 4);
        pt_table_set_lr(table, lr);
        return SendReply(fd, 0, nullptr, 0) ? 0 : 1;
      }
      case kBarrier: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        uint32_t world;
        std::memcpy(&world, body, 4);
        {
          std::unique_lock<std::mutex> l(barrier_mu);
          uint64_t my_gen = barrier_gen;
          if (++barrier_count >= world) {
            barrier_count = 0;
            barrier_gen++;
            barrier_cv.notify_all();
          } else {
            barrier_cv.wait(l, [&] {
              return barrier_gen != my_gen || stopping.load();
            });
          }
        }
        return SendReply(fd, stopping.load() ? -1 : 0, nullptr, 0) ? 0 : 1;
      }
      case kPushRaw: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        uint32_t n;
        std::memcpy(&n, body, 4);
        if (static_cast<uint64_t>(len) !=
            4 + static_cast<uint64_t>(n) * 8 +
                static_cast<uint64_t>(n) * dim * 4)
          return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        const int64_t* keys = reinterpret_cast<const int64_t*>(body + 4);
        const float* deltas = reinterpret_cast<const float*>(body + 4 + n * 8);
        pt_table_push_raw(table, keys, deltas, n);
        return SendReply(fd, 0, nullptr, 0) ? 0 : 1;
      }
      case kPushShowClick: {
        if (len < 4) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        uint32_t n;
        std::memcpy(&n, body, 4);
        if (static_cast<uint64_t>(len) !=
            4 + static_cast<uint64_t>(n) * 8 + static_cast<uint64_t>(n) * 8)
          return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        const int64_t* keys = reinterpret_cast<const int64_t*>(body + 4);
        const float* sc = reinterpret_cast<const float*>(body + 4 + n * 8);
        pt_table_push_show_click(table, keys, sc, n);
        return SendReply(fd, 0, nullptr, 0) ? 0 : 1;
      }
      case kDenseInit: {
        if (len < 16) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        int64_t dlen;
        int32_t opt;
        float lr;
        std::memcpy(&dlen, body, 8);
        std::memcpy(&opt, body + 8, 4);
        std::memcpy(&lr, body + 12, 4);
        if (dlen < 0) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        int32_t rc = 0;
        {
          std::lock_guard<std::mutex> g(dense_mu);
          void* d = dense.load(std::memory_order_relaxed);
          if (!d) {
            dense.store(pt_dense_create(dlen, opt, lr, 1e-8f),
                        std::memory_order_release);
          } else if (pt_dense_len(d) != dlen) {
            // never swap a live table under concurrent dense ops; a
            // resize needs a fresh server
            rc = -14;
          } else if (pt_dense_optimizer(d) != opt) {
            // a misconfigured worker must hear about the divergence, not
            // have its grads silently applied under another rule
            rc = -15;
          }
          // matching re-init (reconnecting client) keeps existing values
        }
        return SendReply(fd, rc, nullptr, 0) ? 0 : 1;
      }
      case kDensePull: {
        if (len < 16) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        int64_t off, n;
        std::memcpy(&off, body, 8);
        std::memcpy(&n, body + 8, 8);
        void* d = DenseOrNull();
        if (n < 0 || static_cast<uint64_t>(n) * 4 > ptn::kMaxFrameLen || !d)
          return SendReply(fd, -12, nullptr, 0) ? 0 : 1;
        std::vector<float> out(static_cast<size_t>(n));
        if (pt_dense_get(d, off, n, out.data()) != 0)
          return SendReply(fd, -13, nullptr, 0) ? 0 : 1;
        return SendReply(fd, 0, out.data(), static_cast<uint32_t>(n * 4))
                   ? 0
                   : 1;
      }
      case kDensePush: {
        if (len < 16) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        int64_t off, n;
        std::memcpy(&off, body, 8);
        std::memcpy(&n, body + 8, 8);
        void* d = DenseOrNull();
        if (n < 0 ||
            static_cast<uint64_t>(len) != 16 + static_cast<uint64_t>(n) * 4 ||
            !d)
          return SendReply(fd, -12, nullptr, 0) ? 0 : 1;
        const float* g = reinterpret_cast<const float*>(body + 16);
        int32_t rc = pt_dense_push(d, off, n, g);
        return SendReply(fd, rc, nullptr, 0) ? 0 : 1;
      }
      case kDenseSet: {
        if (len < 16) return SendReply(fd, -10, nullptr, 0) ? 0 : 1;
        int64_t off, n;
        std::memcpy(&off, body, 8);
        std::memcpy(&n, body + 8, 8);
        void* d = DenseOrNull();
        if (n < 0 ||
            static_cast<uint64_t>(len) != 16 + static_cast<uint64_t>(n) * 4 ||
            !d)
          return SendReply(fd, -12, nullptr, 0) ? 0 : 1;
        const float* vals = reinterpret_cast<const float*>(body + 16);
        int32_t rc = pt_dense_set(d, off, n, vals);
        return SendReply(fd, rc, nullptr, 0) ? 0 : 1;
      }
      case kStop: {
        SendReply(fd, 0, nullptr, 0);
        return 2;  // FramedServer shuts down after this reply
      }
      default:
        return SendReply(fd, -127, nullptr, 0) ? 0 : 1;
    }
  }
};

}  // namespace

extern "C" {

// Start serving `table` on `port` (0 = ephemeral). Returns handle or null.
void* pt_ps_server_start(void* table, int32_t port) {
  auto* ps = new PsServer();
  ps->table = table;
  ps->srv = ptn::FramedServer::Start(
      port,
      [ps](int fd, uint8_t op, const char* body, uint32_t len) {
        return ps->Dispatch(fd, op, body, len);
      },
      [ps] {
        // release barrier waiters so Stop()'s worker join can't deadlock
        ps->stopping.store(true);
        std::lock_guard<std::mutex> g(ps->barrier_mu);
        ps->barrier_gen++;
        ps->barrier_count = 0;
        ps->barrier_cv.notify_all();
      });
  if (!ps->srv) {
    delete ps;
    return nullptr;
  }
  return ps;
}

int32_t pt_ps_server_port(void* h) {
  return static_cast<PsServer*>(h)->srv->port();
}

void pt_ps_server_stop(void* h) { static_cast<PsServer*>(h)->srv->Stop(); }

// Block until the server stops (subprocess entrypoint main loop).
void pt_ps_server_wait(void* h) { static_cast<PsServer*>(h)->srv->Wait(); }

void pt_ps_server_destroy(void* h) {
  auto* ps = static_cast<PsServer*>(h);
  delete ps->srv;
  if (void* d = ps->dense.load()) pt_dense_destroy(d);
  delete ps;
}

// Restore the dense sidecar for `path` (server restart with --load).
int32_t pt_ps_server_load_dense(void* h, const char* path) {
  return static_cast<PsServer*>(h)->LoadDenseSidecar(path);
}
}
