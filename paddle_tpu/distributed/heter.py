"""Heterogeneous CPU↔TPU stage pipeline.

Reference parity: ``HeterPipelineTrainer`` (``paddle/fluid/framework/
trainer.h:345``) + ``HeterSectionWorker`` (``device_worker.h:708``) — the
sparse/embedding stage of a CTR model runs on cheap CPU ranks while the
dense stage runs on accelerator ranks, stages connected by
``HeterClient``/``HeterServer`` RPC (``distributed/ps/service/
heter_client.h:83``, ``heter_server.h:578``) with section queues
pipelining micro-batches across the boundary.

TPU-native shape: the CPU stage (PS embedding pulls, slot combining,
feature preprocessing) is host python/numpy; the dense stage is one
compiled TrainStep on the chip. :class:`HeterPipelineTrainer` pipelines
them — stage boundaries are a prefetch queue, and the CPU stage executes
either on local threads (one-host deployment, the reference's in-process
section queues) or on remote *heter workers* addressed by name over the
existing RPC agent (multi-host split, the HeterClient/HeterServer role).
The TPU step for batch N overlaps the CPU stage for batches N+1..N+depth.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Optional, Sequence

__all__ = ["HeterPipelineTrainer"]


class _LocalExecutor:
    """Run the CPU stage on a local thread pool (in-process section
    workers)."""

    def __init__(self, cpu_stage: Callable, num_workers: int):
        from concurrent.futures import ThreadPoolExecutor

        self.cpu_stage = cpu_stage
        self.pool = ThreadPoolExecutor(max_workers=num_workers)

    def submit(self, batch):
        return self.pool.submit(self.cpu_stage, batch)

    def stop(self):
        self.pool.shutdown(wait=False)


class _RpcExecutor:
    """Run the CPU stage on remote heter workers via the RPC agent
    (HeterClient role): requests round-robin across worker names."""

    def __init__(self, cpu_stage: Callable, workers: Sequence[str],
                 rpc_timeout: float = 120.0):
        self.cpu_stage = cpu_stage
        self.workers = list(workers)
        # bounds every stage rpc (tpu_lint R11): a dead heter worker
        # fails the micro-batch at the trainer's deadline, not the
        # transport's — the trainer then reissues on the survivors
        self.rpc_timeout = float(rpc_timeout)
        self._next = 0
        self._lock = threading.Lock()

    def submit(self, batch):
        from .rpc import rpc_async

        with self._lock:
            w = self.workers[self._next % len(self.workers)]
            self._next += 1
        return rpc_async(w, self.cpu_stage, args=(batch,),
                         timeout=self.rpc_timeout)

    def stop(self):
        pass  # rpc lifetime belongs to init_rpc/shutdown


class HeterPipelineTrainer:
    """Two-stage pipelined trainer: ``cpu_stage(batch) -> staged`` on host
    CPU (threads or remote heter workers), ``tpu_step(staged) -> loss`` on
    the chip, overlapped with ``prefetch_depth`` batches in flight.

    ``run(batches)`` drives a whole epoch and returns the losses;
    ``train_from_iterable`` is the generator flavor. Ordering is preserved
    (results apply in submission order), so loss curves are bit-identical
    to the unpipelined loop — only wall-clock changes.

    Multi-host: start heter workers with ``init_rpc`` (each registers its
    worker name), pass their names as ``heter_workers``; the CPU stage
    then executes remotely, exactly the HeterPipelineTrainer split where
    sparse pulls live next to the PS and only dense tensors cross to the
    TPU host.
    """

    def __init__(self, cpu_stage: Callable[[Any], Any],
                 tpu_step: Callable[[Any], Any],
                 prefetch_depth: int = 2,
                 heter_workers: Optional[Sequence[str]] = None,
                 num_cpu_threads: int = 2):
        if prefetch_depth < 1:
            raise ValueError("prefetch_depth must be >= 1")
        self.cpu_stage = cpu_stage
        self.tpu_step = tpu_step
        self.prefetch_depth = int(prefetch_depth)
        if heter_workers:
            self._exec = _RpcExecutor(cpu_stage, heter_workers)
        else:
            self._exec = _LocalExecutor(cpu_stage, num_cpu_threads)

    def run(self, batches: Iterable[Any]) -> list:
        return list(self.train_from_iterable(batches))

    def train_from_iterable(self, batches: Iterable[Any]):
        """Yield ``tpu_step`` results in batch order while the CPU stage
        runs ahead."""
        it = iter(batches)
        inflight: "queue.Queue" = queue.Queue()
        exhausted = False
        # prime the pipeline
        for _ in range(self.prefetch_depth):
            try:
                inflight.put(self._exec.submit(next(it)))
            except StopIteration:
                exhausted = True
                break
        while not inflight.empty():
            fut = inflight.get()
            staged = fut.result()  # re-raises CPU-stage failures in order
            if not exhausted:
                try:
                    inflight.put(self._exec.submit(next(it)))
                except StopIteration:
                    exhausted = True
            yield self.tpu_step(staged)

    def stop(self) -> None:
        self._exec.stop()
