"""Silent-data-corruption defense: cross-replica integrity checks.

A flaky chip flips bits in params or grads and training drifts without
ever tripping the NaN/hang/crash watchdogs — the fault *lies* instead of
crashing, and a single corrupting host poisons every replica through the
gradient all-reduce. This module is the detection/attribution half of
the defense (``framework/supervisor.py`` owns the escalation ladder):

- **In-program fingerprints** — a cheap modular checksum over the
  param/opt/grad pytree: leaves are bitcast to ``uint32`` and folded with
  position-dependent weights (sum mod 2**32 — associative, so any XLA
  reduction order gives the identical value). Grad folds are grouped per
  PR 17 :class:`~paddle_tpu.distributed.overlap.GradBucket`, so a
  divergence names the bucket that carried it and the checksum rides the
  existing bucketed schedule. Fingerprints are extra LAZY outputs of the
  checked step program; the host readback batches with the numerics
  watchdog flush (one ``device_get`` per check window — R1-clean).
- **Cross-replica divergence detection** — the per-replica fingerprints
  are computed under ``shard_map`` (each replica folds its own physical
  copies: exactly what a lying chip corrupts while GSPMD still believes
  the logical value is replicated) and all-gathered over the vote axis.
  A majority vote names the minority replica as suspect. Leaves sharded
  over the vote axis itself (ZeRO over a dp-ish axis) legitimately
  differ per replica and are excluded with coverage accounting.
- **Checkpoint integrity ledger** — a per-save fingerprint record
  (``integrity.json`` next to ``metadata.json``) of host-side per-leaf
  folds, verified at restore so a corrupted or stale-divergent
  checkpoint is rejected with the rank named.
- **Injection + quarantine** — :func:`apply_bitflip` realises a seeded
  ``bitflip`` :class:`~paddle_tpu.distributed.resilience.FaultRule` by
  flipping one bit in ONE replica's physical copies of a named tensor
  (the logical array is untouched — the SDC model), and
  :func:`record_conviction` durably appends a convicted rank to the
  checkpoint root's ``quarantine.json`` (staged write + atomic replace)
  so the next incarnation can boot on surviving capacity through the
  elastic-mesh machinery.

Everything defaults off: with no :class:`IntegrityChecker` enabled the
step programs and outputs are bit-identical to before this module
existed (``tools/sdc_drill.py`` asserts it).
"""
from __future__ import annotations

import fnmatch
import json
import os
import random
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "IntegrityChecker", "IntegrityMonitor", "HostEvictionRequested",
    "fold_leaf", "host_fold_leaf", "minority_ranks", "coverage_split",
    "apply_bitflip", "flip_bit",
    "LEDGER_FILE", "build_ledger", "build_ledger_bytes", "read_ledger",
    "ledger_problem", "verify_ledger",
    "QUARANTINE_FILE", "record_conviction", "load_quarantine",
]

# fold constants: odd multiplier (Knuth) + golden-ratio offset, applied as
# position weights so swapped elements change the checksum
_MULT = 2654435761
_PHI = 0x9E3779B9
_COMBINE = 0x01000193  # FNV prime: order-sensitive leaf combine

LEDGER_FILE = "integrity.json"
LEDGER_FORMAT = "paddle_tpu.integrity.v1"
QUARANTINE_FILE = "quarantine.json"
QUARANTINE_FORMAT = "paddle_tpu.quarantine.v1"


class HostEvictionRequested(RuntimeError):
    """Control-flow signal: the escalation ladder convicted ``rank`` of
    sticky silent data corruption (it diverged again after a
    deterministic replay). The quarantine record is already durable at
    ``record_path``; the launcher/harness restarts the job on surviving
    capacity (``elastic_mesh.reshaped_mesh`` absorbs the shrink exactly
    like a preemption)."""

    def __init__(self, rank: int, step: int, record_path: str):
        super().__init__(
            f"integrity: rank {rank} convicted of sticky silent data "
            f"corruption at step {step}; quarantined in {record_path}")
        self.rank = rank
        self.step = step
        self.record_path = record_path


# ---------------------------------------------------------------------------
# the fold — traced and host mirrors (bit-exact twins)
# ---------------------------------------------------------------------------

def _key_const(key: str) -> int:
    import zlib

    return zlib.crc32(key.encode()) & 0xFFFFFFFF


def fold_leaf(x):
    """Traced uint32 checksum of one leaf: bitcast to uint32 (inexact
    dtypes go through an exact cast to float32 first, so a single flipped
    bf16 bit survives) and fold with position weights. Sum mod 2**32 is
    associative + commutative, so the value is independent of XLA's
    reduction order — comparable across replicas and topologies."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.inexact):
        u = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    else:
        u = x.astype(jnp.uint32)
    u = u.reshape(-1)
    n = int(u.shape[0])
    w = (jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(_MULT)
         + jnp.uint32(_PHI))
    return jnp.sum(u * w, dtype=jnp.uint32)


def host_fold_leaf(x) -> int:
    """Host mirror of :func:`fold_leaf` (numpy uint32 wraps mod 2**32
    exactly like XLA). The checkpoint ledger records these; restore
    recomputes them over the loaded leaves."""
    x = np.asarray(x)
    if x.dtype.kind in "fV" or x.dtype.kind not in "biu":
        u = x.astype(np.float32).view(np.uint32)
    elif x.dtype == np.bool_:
        u = x.astype(np.uint32)
    else:
        u = x.astype(np.uint32)
    u = u.reshape(-1)
    w = (np.arange(u.size, dtype=np.uint32) * np.uint32(_MULT)
         + np.uint32(_PHI))
    return int((u * w).sum(dtype=np.uint32))


def combine_folds(folds: Dict[str, int]) -> int:
    """Order-insensitive-input, deterministic combined fingerprint: each
    leaf fold is mixed with its key's crc so identical tensors under
    different names cannot cancel."""
    c = np.uint32(len(folds))
    for key in sorted(folds):
        c = c * np.uint32(_COMBINE) + (np.uint32(folds[key])
                                       ^ np.uint32(_key_const(key)))
    return int(c)


# ---------------------------------------------------------------------------
# coverage: which leaves CAN be cross-replica voted on
# ---------------------------------------------------------------------------

def _spec_mentions(spec, axis: str) -> bool:
    for s in (spec or ()):
        if isinstance(s, (tuple, list)):
            if axis in s:
                return True
        elif s == axis:
            return True
    return False


def coverage_split(specs: Dict[str, Any], vote_axis: str
                   ) -> Tuple[List[str], List[str]]:
    """``(covered, uncovered)`` keys: a leaf sharded over the vote axis
    itself holds a DIFFERENT legitimate value on every replica (ZeRO over
    a dp-ish axis) — it cannot be majority-voted and is excluded, but the
    exclusion is accounted, never silent."""
    covered, uncovered = [], []
    for key in sorted(specs):
        (uncovered if _spec_mentions(specs[key], vote_axis)
         else covered).append(key)
    return covered, uncovered


class IntegrityChecker:
    """Traced-side fingerprint builder owned by a train step.

    :meth:`fingerprints` returns a ``uint32[vote_size, 1 + n_buckets]``
    array — column 0 folds the post-update state (params + covered
    optimizer slots), columns 1.. fold each PR 17 grad bucket (one column
    for all grads on the serial path) — computed per replica under
    ``shard_map`` so each replica checksums its own physical buffers, and
    all-gathered over ``vote_axis``. Everything about WHICH leaves
    participate is decided host-side at construction (static under the
    trace): coverage is a property of the sharding specs, not the data.
    """

    def __init__(self, mesh, vote_axis: str, param_specs: Dict[str, Any],
                 opt_specs: Dict[str, Any], grad_specs: Dict[str, Any],
                 buckets: Optional[Sequence] = None):
        self.mesh = mesh
        self.vote_axis = vote_axis
        self.vote_size = int(dict(mesh.shape).get(vote_axis, 1))
        self.param_covered, self.param_uncovered = coverage_split(
            param_specs, vote_axis)
        flat_opt: Dict[str, Any] = {}
        for slot, spec in opt_specs.items():
            if isinstance(spec, dict):
                for k, s in spec.items():
                    flat_opt[f"{slot}/{k}"] = s
            elif spec is not None:
                flat_opt[slot] = spec
        self.opt_covered, self.opt_uncovered = coverage_split(
            flat_opt, vote_axis)
        self.grad_covered, self.grad_uncovered = coverage_split(
            grad_specs, vote_axis)
        self._param_specs = dict(param_specs)
        self._opt_specs = flat_opt
        self._grad_specs = dict(grad_specs)
        # grad fold groups: one column per PR 17 bucket (reverse-backward
        # order — the existing schedule), or one column for all grads
        covered = set(self.grad_covered)
        groups: List[Tuple[str, List[str]]] = []
        for b in (buckets or []):
            names = [n for n in b.names if n in covered]
            if names:
                groups.append((f"bucket{b.index}", names))
        if not groups and self.grad_covered:
            groups = [("grads", list(self.grad_covered))]
        self.grad_groups = groups

    def coverage_report(self) -> dict:
        """What the vote can and cannot see — ZeRO shards over the vote
        axis are per-replica state with no cross-replica redundancy."""
        return {
            "vote_axis": self.vote_axis,
            "vote_size": self.vote_size,
            "covered": {"params": len(self.param_covered),
                        "opt_state": len(self.opt_covered),
                        "grads": len(self.grad_covered)},
            "uncovered": {"params": list(self.param_uncovered),
                          "opt_state": list(self.opt_uncovered),
                          "grads": list(self.grad_uncovered)},
            "grad_groups": [name for name, _ in self.grad_groups],
        }

    # ------------------------------------------------------------- traced
    def fingerprints(self, params, opt_state, grads):
        """``uint32[vote_size, 1 + len(grad_groups)]`` — see class doc."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        flat_opt: Dict[str, Any] = {}
        for slot, val in opt_state.items():
            if isinstance(val, dict):
                for k, v in val.items():
                    flat_opt[f"{slot}/{k}"] = v
            elif hasattr(val, "ndim"):
                flat_opt[slot] = val

        vals, specs, labels = [], [], []
        for k in self.param_covered:
            vals.append(params[k])
            specs.append(self._param_specs[k])
            labels.append(("state", f"params/{k}"))
        for k in self.opt_covered:
            if k in flat_opt:
                vals.append(flat_opt[k])
                specs.append(self._opt_specs[k])
                labels.append(("state", f"opt_state/{k}"))
        for gname, names in self.grad_groups:
            for k in names:
                vals.append(grads[k])
                specs.append(self._grad_specs[k])
                labels.append((gname, f"grads/{k}"))

        columns = ["state"] + [g for g, _ in self.grad_groups]
        col_of = {c: i for i, c in enumerate(columns)}

        def local_folds(*leaves):
            accs = [jnp.uint32(0)] * len(columns)
            for (group, key), leaf in zip(labels, leaves):
                i = col_of[group]
                accs[i] = (accs[i] * jnp.uint32(_COMBINE)
                           + (fold_leaf(leaf) ^ jnp.uint32(_key_const(key))))
            return jnp.stack(accs)

        if self.vote_size <= 1 or self.vote_axis not in self.mesh.shape:
            # nothing to vote over: a single global fold, shape [1, F]
            return local_folds(*vals)[None, :]

        other = tuple(a for a in self.mesh.axis_names if a != self.vote_axis)

        def per_replica(*leaves):
            fp = local_folds(*leaves)
            if other:
                # fold the non-vote shards (mp/sp/... pieces of this
                # replica) into one replica-wide value: replicated over
                # every axis but the vote axis, divergent only where a
                # replica's own buffers lie
                fp = jax.lax.psum(fp, other)
            return fp[None, :]

        in_specs = tuple(P(*s) if not isinstance(s, P) else s for s in specs)
        return shard_map(per_replica, mesh=self.mesh, in_specs=in_specs,
                         out_specs=P(self.vote_axis, None),
                         check_rep=False)(*vals)


# ---------------------------------------------------------------------------
# host side: the monitor (batched readback + escalation state machine)
# ---------------------------------------------------------------------------

def minority_ranks(fps: np.ndarray) -> List[int]:
    """Ranks whose fingerprint column differs from the majority value.
    Returns every rank when no value holds a strict majority (a 50/50
    split cannot be attributed — the caller replays instead of
    convicting)."""
    arr = np.atleast_2d(np.asarray(fps))
    v = arr.shape[0]
    if v <= 1:
        return []
    bad: set = set()
    for col in arr.T:
        vals, counts = np.unique(col, return_counts=True)
        if len(vals) == 1:
            continue
        if counts.max() * 2 <= v:
            bad.update(range(v))
            continue
        maj = vals[int(np.argmax(counts))]
        bad.update(int(i) for i in range(v) if col[i] != maj)
    return sorted(bad)


class IntegrityMonitor:
    """Batches the lazy per-step fingerprint arrays and decides the
    escalation action. Mirrors ``NumericsWatchdog``'s batched-sync
    design: flags accumulate without host syncs and ONE ``device_get``
    drains the window (batched with the watchdog flush).

    The lock guards only host bookkeeping (``observe`` runs on the
    training thread while ``stats()`` may be read from a metrics scrape
    thread); the device readback always happens OUTSIDE it — a stuck
    collective must never wedge a thread that merely wants counters.

    Escalation state machine (the supervisor acts on the verdict):

    - divergence, nothing armed  -> ``replay``: arm the suspect, roll
      back to the last consistent checkpoint and deterministically
      replay (per-step RNG is ``fold_in(base_key, count)`` — the replay
      is bit-identical unless the fault recurs).
    - divergence, armed suspect diverges AGAIN -> ``convict``: the fault
      is sticky (the chip keeps lying), quarantine + evict.
    - ``forgive_after`` consecutive clean flushes -> disarm: the fault
      was transient; the rollback already discarded the poisoned steps.
    """

    def __init__(self, check_interval: int = 4, forgive_after: int = 2):
        self.check_interval = max(1, int(check_interval))
        self.forgive_after = max(1, int(forgive_after))
        self._lock = threading.Lock()
        self._pending: List[tuple] = []   # (step_no, lazy uint32[V, F])
        self.mismatches = 0
        self.replays = 0
        self.convictions = 0
        self.suspect: Optional[int] = None
        self.last_fingerprints: Optional[list] = None
        self._armed: Optional[Tuple[Optional[int], int]] = None
        self._clean_flushes = 0

    def observe(self, step_no: int, fp) -> None:
        """Record one step's fingerprint array WITHOUT forcing it to
        host."""
        with self._lock:
            self._pending.append((int(step_no), fp))

    @property
    def due(self) -> bool:
        with self._lock:
            return len(self._pending) >= self.check_interval

    @property
    def armed(self) -> Optional[Tuple[Optional[int], int]]:
        with self._lock:
            return self._armed

    def drop_pending(self) -> None:
        """Forget fingerprints of steps a rollback is about to replay —
        post-restore they would re-report pre-rollback divergence."""
        with self._lock:
            self._pending.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"mismatches": self.mismatches,
                    "replays": self.replays,
                    "convictions": self.convictions,
                    "suspect": self.suspect,
                    "armed": self._armed,
                    "pending": len(self._pending)}

    def flush(self) -> Optional[dict]:
        """Host-sync the window; returns an escalation verdict
        ``{"action": "replay"|"convict", "rank", "step",
        "fingerprints"}`` or ``None`` when every step agreed. The first
        divergent step settles the window — the escalation replays the
        rest anyway."""
        import jax

        from ..observability.registry import default_registry

        with self._lock:
            todo, self._pending = self._pending, []
        if not todo:
            return None
        # ONE device_get for the whole window, taken with no lock held —
        # per-step readbacks would serialize host round-trips and a stuck
        # device must not wedge stats() readers
        # tpu-lint: disable=R1(THE batched fingerprint sync point — one device_get per integrity check window, batched with the watchdog flush, by design)
        fetched = jax.device_get([fp for _, fp in todo])
        verdict = None
        with self._lock:
            for (step_no, _), fps in zip(todo, fetched):
                arr = np.atleast_2d(np.asarray(fps))
                self.last_fingerprints = [[int(x) for x in row]
                                          for row in arr]
                suspects = minority_ranks(arr)
                if not suspects:
                    continue
                self.mismatches += 1
                self._clean_flushes = 0
                default_registry().inc("integrity.mismatch")
                rank = suspects[0] if len(suspects) == 1 else None
                self.suspect = rank
                if (self._armed is not None and rank is not None
                        and self._armed[0] == rank):
                    self.convictions += 1
                    action = "convict"
                else:
                    self.replays += 1
                    self._armed = (rank, step_no)
                    action = "replay"
                verdict = {"action": action, "rank": rank, "step": step_no,
                           "fingerprints": self.last_fingerprints}
                break
            else:
                if self._armed is not None:
                    self._clean_flushes += 1
                    if self._clean_flushes >= self.forgive_after:
                        # transient confirmed: the replay already
                        # discarded the poisoned steps
                        self._armed = None
                        self._clean_flushes = 0
                        self.suspect = None
        return verdict


# ---------------------------------------------------------------------------
# injection: realise a seeded `bitflip` FaultRule
# ---------------------------------------------------------------------------

def flip_bit(array, mesh, vote_axis: str, rank: int, *,
             bit: Optional[int] = None, element: Optional[int] = None,
             rng: Optional[random.Random] = None):
    """Flip one bit in the physical copies of ``array`` held by devices
    whose ``vote_axis`` mesh coordinate is ``rank``.

    This is the silent-data-corruption model made concrete: the LOGICAL
    (GSPMD) value is untouched — every other replica's buffers are
    byte-identical to before — but one replica's local copies now lie.
    For float32 the default bit is drawn from the mantissa (never NaN/
    inf, so the numerics watchdog stays silent and only the fingerprint
    vote can see it). Returns ``(new_array, info)``; the choice of
    element/bit is a pure function of ``rng``, so a seeded plan replays
    identically."""
    import jax

    rng = rng or random.Random(0)
    names = list(mesh.axis_names)
    if vote_axis not in names:
        vote_axis = names[0]
    ax = names.index(vote_axis)
    coord = {dev: idx[ax]
             for idx, dev in np.ndenumerate(np.asarray(mesh.devices))}
    shards = list(array.addressable_shards)
    sample = np.asarray(shards[0].data)
    nelem = max(1, int(np.prod(sample.shape)))
    element = element if element is not None else rng.randrange(nelem)
    if sample.dtype == np.float32:
        bit = bit if bit is not None else rng.randrange(23)  # mantissa
    else:
        bit = (bit if bit is not None
               else rng.randrange(max(1, sample.dtype.itemsize * 8 - 1)))
    pieces, flipped = [], 0
    for shard in shards:
        data = np.array(shard.data, copy=True)
        if coord.get(shard.device) == rank:
            if data.dtype == np.float32:
                u = data.view(np.uint32).reshape(-1)
                u[element % u.size] ^= np.uint32(1 << bit)
            else:
                u = data.view(np.uint8).reshape(-1)
                byte = (element % nelem) * data.dtype.itemsize + bit // 8
                u[byte % u.size] ^= np.uint8(1 << (bit % 8))
            flipped += 1
        pieces.append(jax.device_put(data, shard.device))
    out = jax.make_array_from_single_device_arrays(
        array.shape, array.sharding, pieces)
    return out, {"element": int(element), "bit": int(bit),
                 "copies_flipped": flipped}


def apply_bitflip(step, fault) -> Optional[dict]:
    """Realise an :class:`~paddle_tpu.distributed.resilience.
    InjectedBitflip` against a train step: pick the target parameter by
    the rule's ``tensor`` pattern (seeded choice among matches) and flip
    one bit on the rule's rank via :func:`flip_bit`. A step without a
    device mesh (single-device ``TrainStep``) has no per-replica copies
    to corrupt — the fault degrades to the NaN poison seam so the plan
    still exercises *a* fault path."""
    from ..observability import flight as _flight
    from ..observability.registry import default_registry

    mesh = getattr(step, "mesh", None)
    params = getattr(step, "params", None)
    if mesh is None or not isinstance(params, dict):
        warnings.warn(
            "bitflip fault on a step without a device mesh; degrading to "
            "a NaN-poisoned batch", RuntimeWarning)
        step.inject_anomaly()
        return None
    pattern = fault.tensor or "*"
    names = sorted(k for k in params if fnmatch.fnmatchcase(k, pattern))
    if not names:
        warnings.warn(
            f"bitflip fault: no parameter matches {pattern!r}; fault "
            f"not applied", RuntimeWarning)
        return None
    rng = random.Random(fault.draw)
    name = names[rng.randrange(len(names))]
    vote_axis = getattr(getattr(step, "_integrity", None), "vote_axis",
                        None) or "dp"
    arr, info = flip_bit(params[name], mesh, vote_axis, fault.rank,
                         bit=fault.bit, rng=rng)
    params[name] = arr
    info.update(tensor=name, rank=int(fault.rank))
    default_registry().inc("integrity.bitflip_injected")
    _flight.note("bitflip_injected", **info)
    print(f"[integrity] injected bitflip: tensor={name} "
          f"rank={fault.rank} bit={info['bit']} "
          f"element={info['element']}", flush=True)
    return info


# ---------------------------------------------------------------------------
# durable JSON records: quarantine + checkpoint ledger
# ---------------------------------------------------------------------------

def _write_json_durable(path: str, obj) -> None:
    """Staged durable publish: write+fsync a process-unique sibling, then
    one atomic ``os.replace`` — a reader never sees a torn record. The
    staging file is removed on EVERY failure path (no orphan to leak)."""
    tmp = f"{path}.tmp-pt{os.getpid()}"
    raw = json.dumps(obj, indent=1, sort_keys=True).encode()
    try:
        f = open(tmp, "wb")
        try:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        finally:
            f.close()
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def record_conviction(root: str, record: dict) -> str:
    """Append a conviction to ``<root>/quarantine.json`` (durable,
    crash-atomic). The record is what the next incarnation needs to boot
    on surviving capacity: the convicted rank, the step, and the
    fingerprint vote that convicted it."""
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, QUARANTINE_FILE)
    data = load_quarantine(root) or {"format": QUARANTINE_FORMAT,
                                     "convicted": []}
    data["convicted"].append(record)
    _write_json_durable(path, data)
    return path


def load_quarantine(root: str) -> Optional[dict]:
    try:
        with open(os.path.join(root, QUARANTINE_FILE)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def build_ledger(state, step: int, monitor: Optional[IntegrityMonitor]
                 = None) -> dict:
    """Per-save fingerprint record written next to ``metadata.json``:
    host folds per array leaf (recomputable at load — the save path
    copies every shard to host anyway, so this is the same D2H traffic
    once more, and only when integrity is on) plus the latest
    cross-replica vote. The supervisor drains the fingerprint window
    BEFORE cutting a checkpoint, so a save over divergent state raises
    instead of writing; ``divergent`` stays in the record as the
    belt-and-braces flag restore still honours."""
    import jax

    from .checkpoint import _flatten

    flat, _ = _flatten(state)
    if jax.process_count() > 1:
        # leaves are not fully addressable here; the divergent flag and
        # vote record still travel, the content folds do not
        leaves = {}
    else:
        leaves = {k: host_fold_leaf(v) for k, v in flat.items()
                  if hasattr(v, "ndim") or isinstance(v, np.ndarray)}
    rec = {"format": LEDGER_FORMAT, "step": int(step), "leaves": leaves,
           "fingerprint": combine_folds(leaves),
           "divergent": False, "suspect": None,
           "vote_fingerprints": None}
    if monitor is not None:
        # the supervisor drains the fingerprint window before every save
        # (divergence raises instead of saving), so a divergent record
        # here means the caller saved OUTSIDE the escalation path while
        # a divergence was visible — restore honours the flag either way
        rec["vote_fingerprints"] = monitor.last_fingerprints
        if monitor.last_fingerprints is not None:
            suspects = minority_ranks(np.asarray(monitor.last_fingerprints,
                                                 dtype=np.uint32))
            if suspects:
                rec["divergent"] = True
                rec["suspect"] = (suspects[0] if len(suspects) == 1
                                  else None)
    return rec


def build_ledger_bytes(state, step: int,
                       monitor: Optional[IntegrityMonitor] = None) -> bytes:
    return json.dumps(build_ledger(state, step, monitor), indent=1,
                      sort_keys=True).encode()


def read_ledger(directory: str) -> Optional[dict]:
    try:
        with open(os.path.join(directory, LEDGER_FILE)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def ledger_problem(directory: str) -> Optional[str]:
    """Cheap pre-load check (no state needed): a checkpoint whose ledger
    says the replicas had already diverged at save time is poisoned —
    reject it with the suspect rank named, exactly like a crc failure,
    so ``latest_checkpoint(exclude=)`` falls back to an older one."""
    rec = read_ledger(directory)
    if rec is None:
        return None
    if rec.get("divergent"):
        return (f"{directory}: integrity ledger marks this checkpoint "
                f"stale-divergent (suspect rank "
                f"{rec.get('suspect')}) — written while replicas "
                f"disagreed")
    return None


def verify_ledger(directory: str, flat_state: Dict[str, Any]
                  ) -> Optional[str]:
    """Recompute host folds over the LOADED leaves and compare to the
    ledger — catches corruption the per-shard crc cannot (bits flipped in
    HBM before the save wrote consistent-but-wrong bytes would carry a
    matching crc; a ledger written from the same poisoned state matches
    too, which is why the divergent flag exists — but load-path or
    re-slicing corruption lands here). Returns a problem string naming
    the first mismatching leaf, or ``None``."""
    import jax

    rec = read_ledger(directory)
    if rec is None:
        return None
    prob = ledger_problem(directory)
    if prob is not None:
        return prob
    if jax.process_count() > 1:
        return None  # leaves are not fully addressable: skip content pass
    for key, want in rec.get("leaves", {}).items():
        v = flat_state.get(key)
        if v is None or not (hasattr(v, "ndim")
                             or isinstance(v, np.ndarray)):
            continue
        got = host_fold_leaf(np.asarray(v))
        if got != int(want):
            return (f"{directory}: integrity fingerprint mismatch for "
                    f"leaf {key!r}: loaded {got:#010x} != ledger "
                    f"{int(want):#010x} (corruption between save and "
                    f"restore)")
    return None
