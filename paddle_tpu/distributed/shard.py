"""Sharding utilities + the distributed train step.

This is the replacement for the reference's whole program-rewrite stack:
``sharding_optimizer.py`` / ``tensor_parallel_optimizer.py`` meta-optimizers
and the ``c_*`` collective insertion passes collapse into: (1) parameter
PartitionSpecs declared by layers (or by policy here), (2) one ``jax.jit``
with in/out shardings, (3) GSPMD.

ZeRO mapping (reference ``group_sharded_parallel`` levels, SURVEY §2.3):
- os   (stage 1): optimizer state sharded over "sdp"
- os_g (stage 2): + gradient reduce-scatter (weight-update sharding)
- p_g_os (stage 3): + parameters sharded over "sdp" (gathered on use)
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..nn.layer import Layer, buffer_state, functional_call, param_state
from ..framework import random as framework_random
from ..framework.jit import StepSeams
from .mesh import get_mesh, require_mesh
from .overlap import (build_buckets, bucketed_reduce, shard_first_free_dim,
                      weight_update_specs)

P = PartitionSpec


def _filter_spec(spec: tuple, mesh) -> PartitionSpec:
    """Drop axes absent from the mesh (so tp-annotated models run on a
    dp-only mesh etc.)."""
    out = []
    for s in spec:
        if s is None:
            out.append(None)
        elif isinstance(s, (tuple, list)):
            kept = [a for a in s if a in mesh.shape]
            out.append(tuple(kept) if kept else None)
        else:
            out.append(s if s in mesh.shape else None)
    return PartitionSpec(*out)


def param_specs(model: Layer, mesh=None, zero3_axis: Optional[str] = None,
                min_zero3_size: int = 2 ** 16) -> Dict[str, PartitionSpec]:
    """PartitionSpec per parameter path: layer-declared (TP) specs first,
    then optional ZeRO-3 sharding of remaining large params over
    ``zero3_axis`` (largest dim divisible by the axis size)."""
    mesh = mesh or require_mesh()
    declared = dict(model.named_param_shardings())
    specs: Dict[str, PartitionSpec] = {}
    for name, p in model.named_parameters():
        if name in declared:
            specs[name] = _filter_spec(declared[name], mesh)
            continue
        spec = [None] * p.ndim
        if zero3_axis and zero3_axis in mesh.shape and p.size >= min_zero3_size:
            ax_size = mesh.shape[zero3_axis]
            # pick the largest divisible dim
            cand = sorted(range(p.ndim), key=lambda i: -p.shape[i])
            for i in cand:
                if p.shape[i] % ax_size == 0:
                    spec[i] = zero3_axis
                    break
        specs[name] = PartitionSpec(*spec)
    return specs


def buffer_specs(model: Layer, mesh=None) -> Dict[str, PartitionSpec]:
    mesh = mesh or require_mesh()
    return {name: PartitionSpec() for name, _ in model.named_buffers()}


def put_global(x, sharding: NamedSharding):
    """Place a host value onto ``sharding``, valid on meshes spanning
    multiple processes.

    Single-process: plain ``device_put``. Multi-process: ``device_put``
    onto a non-addressable sharding first runs a broadcast to assert every
    process passed the same value — a collective per leaf, and one the CPU
    backend may not even implement — so the global array is assembled with
    ``make_array_from_callback`` instead: each process materialises only
    its addressable shards, no communication. The multi-controller data
    contract (every process passes the same global value) is assumed, the
    same contract ``device_put`` would have verified.
    """
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def shard_params(params: Dict[str, Any], specs: Dict[str, PartitionSpec], mesh=None):
    """Scatter each param to its NamedSharding (host->mesh). Goes through
    numpy so the result never aliases the input buffer (the train step
    donates its params; the source Layer must stay valid)."""
    mesh = mesh or require_mesh()
    return {name: put_global(np.asarray(p), NamedSharding(mesh, specs.get(name, PartitionSpec())))
            for name, p in params.items()}


def opt_state_specs(opt_state, params_specs: Dict[str, PartitionSpec],
                    shard_axis: Optional[str] = None, mesh=None,
                    on_fallback: Optional[Callable[[str], None]] = None):
    """Specs for optimizer state: moment slots inherit their parameter's
    spec; with ``shard_axis`` (ZeRO-1/2 weight-update sharding, cf.
    "Automatic Cross-Replica Sharding" in PAPERS.md) unsharded dims of the
    slots are additionally sharded over that axis — by the SAME dim rule
    as ``overlap.weight_update_specs`` (one shared helper), so the param
    shard and its moment shards always land on the same dim.

    A slot with no ``shard_axis``-divisible dim stays at its base spec —
    a silently REPLICATED piece of a nominally sharded update; each such
    param path is reported once through ``on_fallback`` so callers can
    count it instead of shipping a mis-sharded run invisibly."""
    mesh = mesh or require_mesh()

    def spec_for(path_key, leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return PartitionSpec()
        base = params_specs.get(path_key)
        if base is None:
            return PartitionSpec()
        if shard_axis and shard_axis in mesh.shape:
            spec, ok = shard_first_free_dim(list(base), leaf.shape,
                                            shard_axis, mesh)
            if not ok and on_fallback is not None:
                on_fallback(path_key)
            return spec
        spec = list(base) + [None] * (leaf.ndim - len(list(base)))
        return PartitionSpec(*spec)

    out = {}
    for slot, val in opt_state.items():
        if isinstance(val, dict) and slot != "step":
            out[slot] = {k: spec_for(k, v) for k, v in val.items()}
        elif hasattr(val, "ndim"):
            out[slot] = PartitionSpec()
        else:
            out[slot] = None
    return out


class DistributedTrainStep(StepSeams):
    """pjit'd hybrid-parallel train step.

    Composition by configuration (the ``DistributedStrategy`` analogue):
      - data parallel: batch sharded over ("dp", "sdp")
      - tensor parallel: layer-declared "mp" specs
      - ZeRO: ``sharding_stage`` 1/2 -> opt-state (+grad) sharded over "sdp";
        3 -> params too
      - overlap: ``overlap_grad_reduce=True`` -> bucketed gradient
        reduction in reverse-backward order (``overlap.build_buckets`` /
        ``bucketed_reduce``) + the weight update computed on each
        replica's ``sdp`` shard under ``sharding_stage >= 1`` (default
        off => the serial schedule, bit-identical to before the knob
        existed)
      - recompute: wrap blocks with paddle_tpu.distributed.recompute
      - sp/pp: see sequence_parallel.py / pipeline.py
    """

    def __init__(self, model: Layer, optimizer, loss_fn=None, inputs_fn=None,
                 mesh=None, batch_axes=("dp", "sdp"), sharding_stage: int = 0,
                 grad_transform=None, donate: bool = True,
                 grad_accum_steps: int = 1, grad_accum_avg: bool = True,
                 scaler=None, overlap_grad_reduce: bool = False,
                 bucket_size_mb: Optional[float] = None,
                 bucket_count: Optional[int] = None):
        from ..framework.jit import (DEFAULT_RNG_STREAMS, _grad_dtype,
                                     resolve_inputs_fn)

        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.inputs_fn = resolve_inputs_fn(inputs_fn, loss_fn)
        self.grad_transform = grad_transform
        self.mesh = mesh or require_mesh()
        self.batch_axes = batch_axes

        # the public ZeRO entry (group_sharded_parallel) tags the optimizer
        # with the USER's requested stage; it must not be silently
        # downgraded by a caller's heuristic default (Engine passes 2)
        sharding_stage = max(sharding_stage,
                             getattr(optimizer, "_group_sharded_stage", 0))
        self.sharding_stage = sharding_stage
        zero3 = "sdp" if sharding_stage >= 3 else None
        self.specs = param_specs(model, self.mesh, zero3_axis=zero3)
        self.params = shard_params(param_state(model), self.specs, self.mesh)
        self.buffers = {k: put_global(np.asarray(v), NamedSharding(self.mesh, P()))
                        for k, v in buffer_state(model).items()}
        opt_state = optimizer.init(self.params)
        shard_axis = "sdp" if sharding_stage >= 1 else None
        # every param whose update stays replicated because no dim divides
        # the sdp axis — counted, logged, and surfaced in statusz() so a
        # mis-sharded run is visible instead of silently replicated
        self.zero_fallback_params: list = []

        def _note_fallback(name):
            if name not in self.zero_fallback_params:
                self.zero_fallback_params.append(name)

        self.opt_specs = opt_state_specs(opt_state, self.specs, shard_axis,
                                         self.mesh,
                                         on_fallback=_note_fallback)
        self.opt_state = self._shard_opt_state(opt_state)

        # ---- overlap schedule (ROADMAP item 1): bucketed grad reduction
        # in reverse-backward order + ZeRO weight-update sharding. All of
        # it is OFF by default; the serial path below is untouched.
        self.overlap_grad_reduce = bool(overlap_grad_reduce)
        if bucket_size_mb is None:
            # ported DataParallel scripts carry their comm_buffer_size
            # (MB) — honor it as the bucket size hint
            bucket_size_mb = getattr(model, "_comm_buffer_mb", None) or 25.0
        self.bucket_size_mb = float(bucket_size_mb)
        self.update_specs = weight_update_specs(
            self.specs, {k: v.shape for k, v in self.params.items()},
            shard_axis, self.mesh, on_fallback=_note_fallback)
        self._sharded_update = bool(self.overlap_grad_reduce and shard_axis)
        self._reduce_specs = (self.update_specs if self._sharded_update
                              else self.specs)
        self._buckets = None
        if self.overlap_grad_reduce:
            sizes = {k: int(v.size) * int(jnp.dtype(v.dtype).itemsize)
                     for k, v in self.params.items()}
            self._buckets = build_buckets(
                sizes, int(self.bucket_size_mb * 2 ** 20), bucket_count)
        if self.zero_fallback_params and shard_axis:
            from ..observability.registry import default_registry

            reg = default_registry()
            reg.inc("distributed.zero_fallback_params_total",
                    len(self.zero_fallback_params))
            reg.set_gauge("distributed.zero_fallback_params",
                          len(self.zero_fallback_params),
                          step=type(model).__name__,
                          stage=str(sharding_stage))
            logging.getLogger(__name__).warning(
                "ZeRO stage %d: %d param(s) have no sdp-divisible dim; "
                "their update stays REPLICATED: %s", sharding_stage,
                len(self.zero_fallback_params),
                ", ".join(self.zero_fallback_params[:8])
                + ("..." if len(self.zero_fallback_params) > 8 else ""))

        batch_spec = PartitionSpec(tuple(a for a in batch_axes if a in self.mesh.shape) or None)
        self._batch_sharding = NamedSharding(self.mesh, batch_spec)
        # tpu-lint: disable=R1(one-time construction readback; see TrainStep.__init__ — lazy key inputs trip the tunnel slow path)
        self._base_key = jax.block_until_ready(framework_random.next_key())
        self._count = 0
        self._rng_streams = DEFAULT_RNG_STREAMS
        # gradient merge (reference gradient_merge_optimizer.py): accumulator
        # sharded like the grads it receives — the param specs on the
        # serial path, the reduce-scattered update specs under the overlap
        # schedule (so accumulation happens on each replica's shard and
        # the sdp memory win extends to the accumulator)
        self.grad_accum_steps = int(grad_accum_steps)
        self.grad_accum_avg = grad_accum_avg
        self._grad_accum = None
        if self.grad_accum_steps > 1:
            self._grad_accum = {
                k: put_global(
                    np.zeros(v.shape, _grad_dtype(v.dtype)),
                    NamedSharding(self.mesh, self._reduce_specs[k]))
                for k, v in self.params.items()}
        self._init_seams(scaler, self.grad_accum_steps)
        # scale state is replicated: every device applies the same skip/grow
        # decision, so the rolled-back state stays consistent across shards
        self.scaler_state = (
            {k: put_global(np.asarray(v), NamedSharding(self.mesh, P()))
             for k, v in dict(self.scaler.state).items()}
            if self.scaler is not None else None)
        donate_argnums = (0, 1, 2, 3) if donate else ()
        from ..framework import compile_cache

        self._cc_name = compile_cache.register_name(
            f"DistributedTrainStep:{type(model).__name__}")
        self._traced = compile_cache.instrument(self._step, self._cc_name)
        self._compiled = jax.jit(self._traced, donate_argnums=donate_argnums,
                                 static_argnames=("do_update",))
        self._donate_argnums = donate_argnums
        self._compiled_checked = None
        # silent-data-corruption defense (distributed/integrity.py):
        # None = off, and the traced programs stay bit-identical to a
        # build without the feature (with_fp is never passed)
        self._integrity = None
        self._fp_compiled = None
        self._last_fp = None

    def enable_integrity(self, vote_axis="dp"):
        """Turn on in-program cross-replica fingerprints (``None``
        disables). The checked/scaler step specializations are rebuilt so
        they emit an extra lazy ``uint32[vote_size, 1 + n_buckets]``
        output; :meth:`take_fingerprint` hands it to the supervisor's
        :class:`~paddle_tpu.distributed.integrity.IntegrityMonitor`
        without forcing a host sync. Returns the checker (or ``None``)."""
        from .integrity import IntegrityChecker

        if vote_axis is None:
            self._integrity = None
        else:
            self._integrity = IntegrityChecker(
                self.mesh, vote_axis, param_specs=self.specs,
                opt_specs=self.opt_specs, grad_specs=self._reduce_specs,
                buckets=self._buckets)
        self._compiled_checked = None
        self._fp_compiled = None
        self._last_fp = None
        return self._integrity

    def take_fingerprint(self):
        """The last checked call's lazy fingerprint array (once)."""
        fp, self._last_fp = self._last_fp, None
        return fp

    def _checked_compiled(self):
        import functools

        if self._compiled_checked is None:
            kwargs = ({"with_check": True, "with_fp": True}
                      if self._integrity is not None
                      else {"with_check": True})
            self._compiled_checked = jax.jit(
                functools.partial(self._traced, **kwargs),
                donate_argnums=self._donate_argnums)
        return self._compiled_checked

    def _scaler_compiled(self):
        import functools

        if self._integrity is None:
            return self._compiled
        if self._fp_compiled is None:
            self._fp_compiled = jax.jit(
                functools.partial(self._traced, with_fp=True),
                donate_argnums=self._donate_argnums)
        return self._fp_compiled

    def cache_stats(self) -> dict:
        from ..framework import compile_cache

        return compile_cache.cache_stats(self._cc_name)

    def collective_schedule(self) -> list:
        """The bucketed reduction schedule as plain dicts (``[]`` on the
        serial path) — what ``bench_profile --overlap`` names its
        per-bucket collective spans after."""
        return [b.to_dict() for b in (self._buckets or [])]

    def statusz(self) -> dict:
        """Introspection snapshot of the sharding/overlap configuration —
        the training-side ``/statusz`` handle. A nonzero
        ``zero_fallback_params`` under ``sharding_stage >= 1`` means that
        many updates silently run replicated (no sdp-divisible dim)."""
        return {
            "sharding_stage": self.sharding_stage,
            "overlap_grad_reduce": self.overlap_grad_reduce,
            "bucket_size_mb": self.bucket_size_mb,
            "buckets": self.collective_schedule(),
            "params": len(self.params),
            "zero_fallback_params": list(self.zero_fallback_params),
            "grad_accum_steps": self.grad_accum_steps,
        }

    def _shard_opt_state(self, opt_state):
        out = {}
        for slot, val in opt_state.items():
            spec = self.opt_specs.get(slot)
            if isinstance(val, dict) and isinstance(spec, dict):
                out[slot] = {k: put_global(v, NamedSharding(self.mesh, spec[k]))
                             for k, v in val.items()}
            elif hasattr(val, "ndim"):
                out[slot] = put_global(val, NamedSharding(self.mesh, P()))
            else:
                out[slot] = val
        return out

    def _step(self, params, buffers, opt_state, accum, scaler_state, batch,
              key, count, poison, with_check=False, do_update=True,
              with_fp=False):
        from ..framework.jit import (accumulate_grads, finite_guard,
                                     merge_accumulated, split_rng_streams)

        # fold_in inside the program: a lazy key input trips the
        # TPU-tunnel slow path (see framework/jit.py _step)
        rngs = split_rng_streams(jax.random.fold_in(key, count),
                                 self._rng_streams)
        use_scaler = scaler_state is not None

        def compute_loss(p):
            # keep params at their declared shardings inside the traced fn
            p = {k: jax.lax.with_sharding_constraint(v, NamedSharding(self.mesh, self.specs[k]))
                 for k, v in p.items()}
            inputs = self.inputs_fn(batch)
            if not isinstance(inputs, (tuple, list)):
                inputs = (inputs,)
            out, new_buf = functional_call(self.model, p, buffers, *inputs, rngs=rngs)
            raw = out if self.loss_fn is None else self.loss_fn(out, batch)
            loss = jnp.asarray(raw, jnp.float32) * poison
            scaled = loss * scaler_state["scale"] if use_scaler else loss
            return scaled, (new_buf, loss)

        (_, (new_buffers, loss)), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        if self._buckets:
            # overlap schedule: pin each reverse-backward-ordered bucket
            # of grads to its reduction placement (reduce-scattered over
            # sdp under sharding_stage >= 1) as its own schedulable unit,
            # so XLA's latency-hiding scheduler issues bucket k's
            # collective while bucket k+1's grads are still being
            # computed. Placement only — values are untouched.
            grads = bucketed_reduce(grads, self._buckets,
                                    self._reduce_specs, self.mesh)
        accum = accumulate_grads(accum, grads)
        if not do_update:
            return loss, params, new_buffers, opt_state, accum, scaler_state
        grads, accum = merge_accumulated(accum, grads, self.grad_accum_steps,
                                         self.grad_accum_avg)
        if self.grad_transform is not None:
            grads = self.grad_transform(grads)
        if use_scaler:
            from ..amp.grad_scaler import unscale_and_check

            grads, found = unscale_and_check(grads, scaler_state)
        upd_params = params
        if self._sharded_update:
            # ZeRO weight-update sharding (arXiv:2004.13336): constrain
            # the update's param input to the sdp-sharded update specs so
            # the whole optimizer computation runs on each replica's
            # shard (grads and moments already live there); the param
            # constraint right below is the all-gather back.
            upd_params = {k: jax.lax.with_sharding_constraint(
                v, NamedSharding(self.mesh, self.update_specs[k]))
                for k, v in params.items()}
        new_params, new_opt_state = self.optimizer.update(grads, opt_state,
                                                          upd_params)
        new_params = {k: jax.lax.with_sharding_constraint(
            v, NamedSharding(self.mesh, self.specs[k])) for k, v in new_params.items()}
        if use_scaler:
            from ..framework.jit import scaler_guard

            # the skip/grow decision is a replicated scalar, so every shard
            # of the GSPMD state takes the same branch — rollback-consistent
            (new_params, new_buffers, new_opt_state), new_scaler_state, \
                ok, found_inf = scaler_guard(
                    loss, found, scaler_state,
                    (new_params, new_buffers, new_opt_state),
                    (params, buffers, opt_state))
            out = (loss, new_params, new_buffers, new_opt_state, accum,
                   new_scaler_state, ok, found_inf)
            if with_fp:
                # fingerprint the GUARDED state the step actually keeps
                out += (self._integrity.fingerprints(
                    new_params, new_opt_state, grads),)
            return out
        if with_check:
            ok, (new_params, new_buffers, new_opt_state) = finite_guard(
                grads, (new_params, new_buffers, new_opt_state),
                (params, buffers, opt_state), extra_ok=jnp.isfinite(loss))
            out = (loss, new_params, new_buffers, new_opt_state, accum,
                   scaler_state, ok, jnp.zeros((), jnp.bool_))
            if with_fp:
                out += (self._integrity.fingerprints(
                    new_params, new_opt_state, grads),)
            return out
        return loss, new_params, new_buffers, new_opt_state, accum, scaler_state

    def _put_batch(self, batch):
        def put(x):
            if not (hasattr(x, "ndim") or isinstance(x, (np.ndarray, list))):
                return x
            if isinstance(x, jax.Array):
                # already on device (prefetch pipeline): reshard in place —
                # np.asarray here would block on a D2H copy (and raise
                # outright for non-addressable multi-process batches)
                return jax.device_put(x, self._batch_sharding)
            return put_global(np.asarray(x), self._batch_sharding)

        return jax.tree.map(put, batch)

    def _checked_call(self, batch, count, poison):
        if self.scaler_state is not None:
            out = self._scaler_compiled()(
                self.params, self.buffers, self.opt_state,
                self._grad_accum, self.scaler_state, batch,
                self._base_key, count, poison)
            if self._integrity is not None:
                *out, self._last_fp = out
            (loss, self.params, self.buffers, self.opt_state,
             self._grad_accum, self.scaler_state, ok, found) = out
            if self.scaler is not None:
                self.scaler._note_step(found)
                self.scaler.state = dict(self.scaler_state)
            return loss, ok, found
        out = self._checked_compiled()(self.params, self.buffers,
                                       self.opt_state, self._grad_accum,
                                       None, batch, self._base_key, count,
                                       poison)
        if self._integrity is not None:
            *out, self._last_fp = out
        (loss, self.params, self.buffers, self.opt_state, self._grad_accum,
         _, ok, found) = out
        return loss, ok, found

    def watchdog_call(self, batch):
        """``(loss, ok, found_inf)``, flags LAZY (no host sync); ``None``
        flags on accumulate-only calls. See TrainStep.watchdog_call."""
        from ..framework import compile_cache

        batch = self._put_batch(batch)
        count, do_update = self._next_count()
        compile_cache.record_call(self._cc_name)
        poison = self._take_poison()
        with self.mesh, self._step_span():
            if not do_update:
                loss, self.params, self.buffers, self.opt_state, \
                    self._grad_accum, _ = \
                    self._compiled(self.params, self.buffers, self.opt_state,
                                   self._grad_accum, None, batch,
                                   self._base_key, count, poison,
                                   do_update=False)
                return loss, None, None
            return self._checked_call(batch, count, poison)

    def __call__(self, batch):
        from ..framework import compile_cache, flags
        from ..framework.jit import raise_if_bad_step

        batch = self._put_batch(batch)
        count, do_update = self._next_count()
        compile_cache.record_call(self._cc_name)
        poison = self._take_poison()
        with self.mesh, self._step_span():
            if do_update and (self.scaler_state is not None
                              or flags.flag("FLAGS_check_nan_inf")):
                loss, ok, found = self._checked_call(batch, count, poison)
                if flags.flag("FLAGS_check_nan_inf"):
                    raise_if_bad_step(ok, loss)
                return loss
            loss, self.params, self.buffers, self.opt_state, \
                self._grad_accum, _ = \
                self._compiled(self.params, self.buffers, self.opt_state,
                               self._grad_accum, None, batch, self._base_key,
                               count, poison, do_update=do_update)
        return loss

    def sync_to_model(self):
        for name, v in self.params.items():
            self.model._set_by_path(name, v)
        for name, v in self.buffers.items():
            self.model._set_by_path(name, v)
        return self.model

    def state_dict(self):
        sd = {"params": self.params, "buffers": self.buffers,
              "opt_state": self.opt_state, "count": self._count,
              "base_key": np.asarray(jax.random.key_data(self._base_key))}
        if self._grad_accum is not None:
            sd["grad_accum"] = self._grad_accum
        if self.scaler_state is not None:
            sd["scaler_state"] = self.scaler_state
        return sd

    def state_shardings(self):
        """Flat ``{checkpoint key: NamedSharding}`` matching
        :meth:`state_dict`'s layout, for
        ``distributed.checkpoint.load_state(shardings=...)`` — each process
        materialises only its addressable shards, the multi-host resume
        path (reference: fleet ``load_persistables`` +
        ``python/paddle/distributed/fleet/utils/fs.py`` shard merge)."""
        out = {}
        for k, spec in self.specs.items():
            out[f"params/{k}"] = NamedSharding(self.mesh, spec)
        for k in self.buffers:
            out[f"buffers/{k}"] = NamedSharding(self.mesh, P())
        for slot, spec in self.opt_specs.items():
            if isinstance(spec, dict):
                for k, s in spec.items():
                    out[f"opt_state/{slot}/{k}"] = NamedSharding(self.mesh, s)
            elif spec is not None:
                out[f"opt_state/{slot}"] = NamedSharding(self.mesh, P())
        if self._grad_accum is not None:
            for k, spec in self._reduce_specs.items():
                out[f"grad_accum/{k}"] = NamedSharding(self.mesh, spec)
        out["base_key"] = NamedSharding(self.mesh, P())
        if self.scaler_state is not None:
            for k in self.scaler_state:
                out[f"scaler_state/{k}"] = NamedSharding(self.mesh, P())
        return out

    def set_state_dict(self, state):
        """Restore from a state tree (plain numpy from ``load_state``, or
        global arrays from a sharded load): every leaf is placed onto this
        step's declared sharding, so a checkpoint resumes correctly on a
        different topology too."""
        def put(v, sharding):
            if isinstance(v, jax.Array) and v.sharding == sharding:
                return v
            if isinstance(v, jax.Array) and not v.is_fully_addressable:
                # already a global array on another sharding: reshard
                return jax.device_put(v, sharding)
            return put_global(np.asarray(v), sharding)

        self.params = {k: put(state["params"][k],
                              NamedSharding(self.mesh, self.specs[k]))
                       for k in self.params}
        self.buffers = {k: put(state["buffers"][k],
                               NamedSharding(self.mesh, P()))
                        for k in self.buffers}
        new_opt = {}
        for slot, val in self.opt_state.items():
            spec = self.opt_specs.get(slot)
            sval = state["opt_state"][slot]
            if isinstance(val, dict) and isinstance(spec, dict):
                new_opt[slot] = {k: put(sval[k],
                                        NamedSharding(self.mesh, spec[k]))
                                 for k in val}
            elif hasattr(val, "ndim"):
                new_opt[slot] = put(sval, NamedSharding(self.mesh, P()))
            else:
                new_opt[slot] = sval
        self.opt_state = new_opt
        self._count = int(state.get("count", self._count))
        if state.get("base_key") is not None:
            self._base_key = jax.random.wrap_key_data(
                jnp.asarray(np.asarray(state["base_key"]), jnp.uint32))
        if self._grad_accum is not None and "grad_accum" in state:
            self._grad_accum = {
                k: put(state["grad_accum"][k],
                       NamedSharding(self.mesh, self._reduce_specs[k]))
                for k in self._grad_accum}
        if self.scaler_state is not None and "scaler_state" in state:
            self.scaler_state = {
                k: put(state["scaler_state"][k], NamedSharding(self.mesh, P()))
                for k in self.scaler_state}
