"""RPC agent.

Reference parity: ``python/paddle/distributed/rpc/rpc.py`` — ``init_rpc``
rendezvous through a master store, every worker runs a service that
executes submitted python callables, ``rpc_sync``/``rpc_async`` address
workers by NAME, and ``shutdown`` barriers before teardown.

TPU-native shape: the master store is the launch KV server
(``kv_server.py``, the TCPStore analogue) and the per-worker service is a
small threaded TCP server executing pickled ``(fn, args, kwargs)``. As in
the reference (which pickles python functions over brpc), this trusts the
cluster: only run it on networks where every peer is trusted.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, Optional

from ..launch.kv_server import KVClient, KVServer
from ..resilience import Deadline, RetryPolicy, fault_point, with_timeout


class RpcTransportError(ConnectionError):
    """A transport-level failure talking to a named peer: connect retries
    exhausted, a connection dropped mid-request, or a truncated/garbled
    frame. Subclasses ``ConnectionError`` so every caller's
    ``resilience.RetryPolicy`` classifies it as retryable, and carries
    ``peer`` so failure detectors can attribute the miss WITHOUT parsing
    the message. Application exceptions raised by the remote fn are
    re-raised as themselves, never wrapped — only the transport is ours
    to classify."""

    def __init__(self, peer: str, message: str):
        super().__init__(f"rpc peer {peer!r}: {message}")
        self.peer = peer


_DEFAULT_RPC_TIMEOUT = 120.0
# transport-level retries for connection establishment to a peer service
# (the peer may be mid-restart); the request itself is never re-sent — an
# rpc'd fn is arbitrary python and re-execution is not ours to decide
_CONNECT_RETRY = RetryPolicy(deadline=5.0, base_delay=0.1, max_delay=1.0,
                             retryable=(ConnectionError, OSError))
# rendezvous/barrier keys are leased: a crashed incarnation's stale entries
# must not satisfy the next rendezvous on a long-lived KV store forever
_KEY_TTL = 600.0


def _namespace() -> str:
    """KV namespace scoped by job, pod incarnation (PADDLE_MASTER is unique
    per pod generation and identical across its ranks — same trick as
    fleet.metrics), and the in-process init/shutdown cycle."""
    job = os.environ.get("PADDLE_JOB_ID", "default")
    gen = os.environ.get("PADDLE_MASTER", "0")
    gen = gen.replace("/", "_").replace(":", "_")
    return f"rpc/{job}/{gen}/c{_cycle}"


@dataclass(frozen=True)
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


_state: Dict[str, object] = {
    "server": None, "workers": None, "self": None, "kv": None,
    "kv_server": None, "pool": None, "world": 0,
}
# namespaces each incarnation's KV keys so a fast re-init never sees the
# previous cycle's rendezvous/barrier keys; advanced in shutdown() so a
# retry after a FAILED init stays in the same namespace as its peers
_cycle = 0


def _kv_retry(fn, deadline, what):
    """Run a KV-store operation, retrying transport failures (server not up
    yet / transient refusal) until ``deadline`` (an absolute time.time())."""
    remaining = max(0.01, deadline - time.time())
    policy = RetryPolicy(deadline=remaining, base_delay=0.2, multiplier=1.0,
                         max_delay=0.2)
    try:
        return policy.call(fn, what=f"rpc {what}")
    except TimeoutError as e:
        raise TimeoutError(
            f"rpc {what}: master store unreachable: {e.__cause__}") from e


def _read_full(sock, n):
    buf = b""
    while len(buf) < n:
        c = sock.recv(n - len(buf))
        if not c:
            raise ConnectionError("rpc peer closed")
        buf += c
    return buf


class _Service(threading.Thread):
    """Executes incoming ``(fn, args, kwargs)``; one thread per request.

    The socket binds (fixing the advertised port) at construction, but the
    accept loop only runs once ``start()`` is called — init_rpc starts it
    AFTER the worker registry is populated, so a remote fn can never
    observe half-initialized rpc state (early connects sit in the listen
    backlog)."""

    def __init__(self):
        super().__init__(daemon=True)
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("0.0.0.0", 0))
        self.sock.listen(64)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()

    def run(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                break
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            with conn:
                (size,) = struct.unpack("<Q", _read_full(conn, 8))
                fn, args, kwargs = pickle.loads(_read_full(conn, size))
                try:
                    result = (True, fn(*args, **kwargs))
                except BaseException as e:  # ship the failure back
                    result = (False, e)
                try:
                    payload = pickle.dumps(result)
                except Exception as e:  # unpicklable result/exception
                    payload = pickle.dumps((False, RuntimeError(repr(e))))
                conn.sendall(struct.pack("<Q", len(payload)) + payload)
        except (ConnectionError, OSError):
            pass

    def stop(self):
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None) -> None:
    """Start this worker's service and rendezvous with the others.

    Reference ``init_rpc``: rank/world/master default from the launch env
    (``PADDLE_TRAINER_ID``/``PADDLE_TRAINERS_NUM``/``PADDLE_MASTER``);
    rank 0 hosts the master store.
    """
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None else rank
    world_size = (int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
                  if world_size is None else world_size)
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT", os.environ.get("PADDLE_KV_ENDPOINT"))
    if master_endpoint is None:
        raise ValueError("init_rpc needs master_endpoint (host:port)")

    if rank == 0:
        host, port = master_endpoint.rsplit(":", 1)
        try:
            _state["kv_server"] = KVServer(int(port)).start()
        except OSError:
            _state["kv_server"] = None  # an external store already serves
    kv = KVClient(master_endpoint)
    service = _Service()  # bound (port known) but NOT accepting yet
    ip = socket.gethostbyname(socket.gethostname())
    ns = _namespace()
    deadline = time.time() + _DEFAULT_RPC_TIMEOUT
    # non-zero ranks commonly start BEFORE rank 0 has its store up (the
    # launch CLI spawns all pods at once), so every KV touch during
    # rendezvous retries connection failures until the shared deadline —
    # the TCPStore-client behavior of the reference
    workers: Dict[str, WorkerInfo] = {}
    try:
        _kv_retry(lambda: kv.put(
            f"{ns}/worker/{rank}",
            pickle.dumps(WorkerInfo(name, rank, ip, service.port)).hex(),
            ttl=_KEY_TTL), deadline, "register")
        for r in range(world_size):
            raw = None
            while raw is None:
                raw = _kv_retry(lambda: kv.get(f"{ns}/worker/{r}"),
                                deadline, f"rendezvous rank {r}")
                if raw is None:
                    if time.time() > deadline:
                        raise TimeoutError(
                            f"rpc rendezvous: rank {r} missing")
                    time.sleep(0.1)
            info = pickle.loads(bytes.fromhex(raw))
            workers[info.name] = info
    except Exception:
        # failed init must not leak the listening socket — nor, on rank 0,
        # the KV server this attempt started (a retry would see its own
        # orphan holding the port and mistake it for an external store)
        service.stop()
        if _state["kv_server"] is not None:
            _state["kv_server"].stop()
            _state["kv_server"] = None
        raise
    _state.update(server=service, workers=workers,
                  self=next(w for w in workers.values() if w.rank == rank),
                  kv=kv, pool=ThreadPoolExecutor(max_workers=16),
                  world=world_size)
    service.start()  # accept only now that state is fully visible


def _invoke(to: str, fn, args, kwargs, timeout, connect_deadline=None):
    workers = _state["workers"]
    if workers is None:
        raise RuntimeError("rpc not initialized; call init_rpc first")
    if to not in workers:
        raise ValueError(f"unknown rpc worker {to!r}; known: {sorted(workers)}")
    info: WorkerInfo = workers[to]
    payload = pickle.dumps((fn, tuple(args or ()), dict(kwargs or {})))

    # the per-ATTEMPT connect timeout must also respect connect_deadline:
    # the retry loop only checks elapsed time AFTER an attempt returns,
    # so a SYN-blackholed peer would otherwise hold each attempt for the
    # full request timeout and blow the caller's classification budget
    connect_timeout = timeout
    if connect_deadline is not None:
        connect_timeout = (min(timeout, connect_deadline) if timeout
                           else connect_deadline)

    def connect():
        # retried: nothing has been sent yet, so a drop/refusal here is
        # always safe to re-attempt (incl. injected rpc.connect faults)
        fault_point(f"rpc.connect.{to}")
        return socket.create_connection((info.ip, info.port),
                                        timeout=connect_timeout or None)

    retry = _CONNECT_RETRY
    if connect_deadline is not None:
        # callers with their own failure budget (health probes, bounded
        # drains) shrink the default 5s connect-retry window so a dead
        # peer is classified at THEIR deadline, not ours
        retry = RetryPolicy(deadline=max(0.05, float(connect_deadline)),
                            base_delay=0.05, max_delay=0.5,
                            retryable=(ConnectionError, OSError))
    # every failure below is a transport failure: the request either never
    # reached the peer (connect), died on the wire (send/recv), or came
    # back torn (short/garbled frame). All of them re-raise as the
    # retryable RpcTransportError carrying the peer's name; only the
    # remote fn's own exception (the ``not ok`` path) stays unwrapped.
    try:
        with retry.call(connect, what=f"rpc connect {to}") as conn:
            # connected: restore the full REQUEST timeout for the
            # send/recv phase (create_connection left the tighter
            # connect budget installed on the socket)
            conn.settimeout(timeout or None)
            conn.sendall(struct.pack("<Q", len(payload)) + payload)
            (size,) = struct.unpack("<Q", _read_full(conn, 8))
            ok, result = pickle.loads(_read_full(conn, size))
    except (TimeoutError, ConnectionError, OSError, EOFError,
            struct.error, pickle.UnpicklingError) as e:
        raise RpcTransportError(to, f"{type(e).__name__}: {e}") from e
    if not ok:
        raise result
    return result


def rpc_sync(to: str, fn, args=None, kwargs=None,
             timeout=_DEFAULT_RPC_TIMEOUT, connect_deadline=None):
    """Blocking call of ``fn(*args, **kwargs)`` on worker ``to``.

    Transport failures raise :class:`RpcTransportError` (a retryable
    ``ConnectionError`` naming the peer); exceptions raised by ``fn``
    itself propagate unwrapped. ``connect_deadline`` bounds the
    connection-establishment retry window (default: the module's 5s
    policy) — failure detectors pass a sub-second budget here."""
    return _invoke(to, fn, args, kwargs, timeout, connect_deadline)


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout=_DEFAULT_RPC_TIMEOUT) -> Future:
    """Non-blocking flavor; returns a Future (reference returns a
    ``FutureWrapper`` with ``wait()`` — ``Future.result`` is the analogue,
    and a ``wait`` alias is attached for ported scripts)."""
    if _state["pool"] is None:
        raise RuntimeError("rpc not initialized; call init_rpc first")
    fut = _state["pool"].submit(_invoke, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # paddle API compat
    return fut


def _wait_keys(kv, keys, timeout, what, deadline: Optional[Deadline] = None):
    """Poll until every key exists. The wait is bounded by ``timeout``
    AND, when given, the caller's own :class:`resilience.Deadline` —
    whichever budget runs out first ends the poll, so a caller mid-way
    through its shutdown window never re-grants a full ``timeout`` to
    each successive wait."""
    if deadline is not None:
        timeout = min(float(timeout), max(0.01, deadline.remaining()))
    local = time.monotonic() + timeout
    for key in keys:
        remaining = max(0.01, local - time.monotonic())
        policy = RetryPolicy(deadline=remaining, base_delay=0.05,
                             multiplier=1.0, max_delay=0.05)
        try:
            policy.until(lambda: kv.get(key), what=f"rpc {what}")
        except TimeoutError:
            raise TimeoutError(
                f"rpc {what} timed out waiting {key}") from None


def _barrier(timeout=_DEFAULT_RPC_TIMEOUT,
             deadline: Optional[Deadline] = None):
    kv: KVClient = _state["kv"]
    me: WorkerInfo = _state["self"]
    ns = _namespace()
    kv.put(f"{ns}/barrier/{me.rank}", "1", ttl=_KEY_TTL)
    _wait_keys(kv, [f"{ns}/barrier/{r}" for r in range(_state["world"])],
               timeout, "shutdown barrier", deadline=deadline)


def shutdown(timeout: float = _DEFAULT_RPC_TIMEOUT) -> None:
    """Barrier (so no in-flight request loses its executor), then stop.

    Idempotent (a second call is a no-op) and bounded: every phase —
    arrival barrier, executor drain, departure wait — fits inside
    ``timeout``, so a DEAD peer degrades the exit into a timed-out barrier
    plus local teardown instead of hanging this process forever.

    Two-phase: after the arrival barrier every rank posts a ``departed``
    key; the store host (rank 0) keeps the KV server alive until ALL peers
    have departed, so a peer descheduled mid-poll never sees the store
    vanish under it. Keys are leased — nothing needs deleting for the TTL
    to clean up, and deleting barrier keys early would strand slow pollers.
    """
    if _state["workers"] is None:
        return
    budget = Deadline(timeout)   # ONE budget across every phase below
    deadline = budget.expires_at
    peers_alive = True
    try:
        _barrier(timeout=max(0.1, timeout / 2), deadline=budget)
    except (TimeoutError, OSError) as e:
        # a crashed peer can't arrive; tear down locally instead of raising
        # (the caller is exiting — there is nothing better it could do)
        peers_alive = False
        print(f"[rpc] shutdown barrier abandoned: {e}", flush=True)
    if peers_alive:
        time.sleep(0.2)  # grace for requests accepted during the barrier
    _state["server"].stop()
    pool = _state["pool"]
    try:
        with_timeout(lambda: pool.shutdown(wait=True),
                     max(0.1, deadline - time.monotonic()),
                     "rpc executor drain")
    except TimeoutError:
        pool.shutdown(wait=False)  # in-flight calls to dead peers: abandon
    kv: KVClient = _state["kv"]
    me: WorkerInfo = _state["self"]
    ns = _namespace()
    try:
        kv.put(f"{ns}/departed/{me.rank}", "1", ttl=_KEY_TTL)
        kv.delete(f"{ns}/worker/{me.rank}")
    except OSError:
        pass
    if _state["kv_server"] is not None:
        if peers_alive:
            try:
                _wait_keys(kv, [f"{ns}/departed/{r}"
                                for r in range(_state["world"])],
                           max(0.1, deadline - time.monotonic()),
                           "departure", deadline=budget)
            except TimeoutError:
                pass  # a crashed peer shouldn't wedge the host's exit
        _state["kv_server"].stop()
    _state.update(server=None, workers=None, self=None, kv=None,
                  kv_server=None, pool=None, world=0)
    # bump the cycle only on a COMPLETED shutdown: a rank retrying a failed
    # init must land in the same namespace as its peers, and shutdown is
    # collective (barriered), so all ranks advance together
    global _cycle
    _cycle += 1


def get_worker_info(name: str) -> WorkerInfo:
    return _state["workers"][name]


def get_all_worker_infos():
    return sorted(_state["workers"].values(), key=lambda w: w.rank)


def get_current_worker_info() -> WorkerInfo:
    return _state["self"]
