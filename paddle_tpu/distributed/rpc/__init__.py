"""paddle_tpu.distributed.rpc — worker-to-worker remote procedure calls.

Reference parity: ``python/paddle/distributed/rpc/rpc.py`` (``init_rpc``
over a TCP master store, ``rpc_sync``/``rpc_async`` executing pickled
python callables on named workers, ``WorkerInfo`` registry, barriered
``shutdown``).
"""
from .rpc import (RpcTransportError, WorkerInfo, get_all_worker_infos,
                  get_current_worker_info, get_worker_info, init_rpc,
                  rpc_async, rpc_sync, shutdown)

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown", "get_worker_info",
           "get_all_worker_infos", "get_current_worker_info", "WorkerInfo",
           "RpcTransportError"]
