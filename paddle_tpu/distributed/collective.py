"""Collective communication API.

Reference parity: ``python/paddle/distributed/communication/`` (all_reduce /
all_gather / alltoall / reduce_scatter / broadcast / send / recv over
ProcessGroupNCCL) and the 160-file ``c_*`` op zoo
(``paddle/fluid/operators/collective/``). TPU-native: a "group" is a mesh
axis name; collectives are ``jax.lax`` primitives that XLA lowers onto
ICI/DCN. Two usage modes:

1. **Inside shard_map** (explicit SPMD — the PP/MoE/ring paths): these
   functions are the direct analogue of the ``c_*`` ops.
2. **Under plain pjit/GSPMD**: you rarely call these at all — sharding
   annotations make XLA insert the collectives (the whole point, see
   SURVEY §7 design stance).

``ReduceOp`` and function signatures mirror paddle for porting ease.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.jax_compat import axis_size as _axis_size


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _axis(group):
    """Accept an axis name, tuple of names, a Group (maps via its mesh
    axis), or None (-> 'dp')."""
    if group is None:
        return "dp"
    ax = getattr(group, "axis", None)  # api_compat.Group
    if ax is not None:
        return ax
    if hasattr(group, "ranks"):
        raise ValueError(
            "this Group carries no mesh-axis mapping; create it with "
            "new_group(..., axis=<mesh axis name>) to use it in "
            "collectives")
    return group


def all_reduce(tensor, op=ReduceOp.SUM, group=None):
    axis = _axis(group)
    if op == ReduceOp.SUM:
        return lax.psum(tensor, axis)
    if op == ReduceOp.AVG:
        return lax.pmean(tensor, axis)
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, axis)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, axis)
    if op == ReduceOp.PROD:
        return jnp.exp(lax.psum(jnp.log(tensor), axis))
    raise ValueError(f"unknown reduce op {op}")


def all_gather(tensor, group=None, axis=0):
    """Gather shards along ``axis`` (reference ``c_allgather``)."""
    return lax.all_gather(tensor, _axis(group), axis=axis, tiled=True)


def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, scatter_axis=0):
    axis = _axis(group)
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError("reduce_scatter supports sum/avg")
    out = lax.psum_scatter(tensor, axis, scatter_dimension=scatter_axis, tiled=True)
    if op == ReduceOp.AVG:
        out = out / lax.psum(jnp.ones((), out.dtype), axis)
    return out


def broadcast(tensor, src=0, group=None):
    """Select rank ``src``'s value on every rank (reference ``c_broadcast``)."""
    axis = _axis(group)
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return lax.psum(masked, axis)


def alltoall(tensor, group=None, split_axis=0, concat_axis=0):
    """reference ``alltoall`` / MoE ``global_scatter`` building block."""
    axis = _axis(group)
    n = _axis_size(axis)
    return lax.all_to_all(tensor, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ppermute(tensor, perm, group=None):
    """Point-to-point ring shift — the PP/ring-attention primitive
    (replaces the reference's batch_isend_irecv NCCL P2P)."""
    return lax.ppermute(tensor, _axis(group), perm=perm)


def shift_right(tensor, group=None):
    axis = _axis(group)
    n = _axis_size(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(tensor, axis, perm=perm)


def shift_left(tensor, group=None):
    axis = _axis(group)
    n = _axis_size(axis)
    perm = [(i, (i - 1) % n) for i in range(n)]
    return lax.ppermute(tensor, axis, perm=perm)


def all_reduce_buckets(tensors: Sequence, op=ReduceOp.SUM, group=None):
    """Bucketed list all-reduce — the explicit-SPMD (shard_map) analogue
    of ``overlap.bucketed_reduce``: each tensor in ``tensors`` is reduced
    as its own schedulable unit, chained with ``optimization_barrier`` so
    the collectives issue in list order (reverse-backward order when the
    caller follows ``overlap.bucket_order``) instead of fusing into one
    tail reduction. Values are identical to mapping :func:`all_reduce`
    over the list; only the schedule differs."""
    out = []
    anchor = None
    for t in tensors:
        if anchor is not None:
            t, _ = jax.lax.optimization_barrier((t, anchor))
        r = all_reduce(t, op=op, group=group)
        (r,) = jax.lax.optimization_barrier((r,))
        anchor = r
        out.append(r)
    return out


def axis_index(group=None):
    return lax.axis_index(_axis(group))


def axis_size_of(group=None):
    return _axis_size(_axis(group))


# ----------------------------------------------------------------- eager API
def eager_all_reduce(tensor, op=ReduceOp.SUM, group=None, mesh=None):
    """Paddle-style eager collective over a mesh axis: runs a tiny shard_map
    program. For testing/metric aggregation, not hot paths."""
    from ..framework.jax_compat import shard_map
    from .mesh import require_mesh, P

    m = mesh or require_mesh()
    axis = _axis(group)
    spec = P(axis)

    def body(x):
        return all_reduce(x, op=op, group=axis)

    # the tensor's leading dim is treated as the axis shard dim
    f = shard_map(body, mesh=m, in_specs=(spec,), out_specs=spec)
    return f(jnp.asarray(tensor))
