"""Distributed checkpointing.

Reference parity:
- per-shard save + re-slicing metadata: auto-parallel ``dist_saver.py``
  (``python/paddle/distributed/auto_parallel/dist_saver.py``) which dumps
  per-rank shards plus dist_attr for re-slicing on a different topology;
- ``fleet.save_persistables`` table dump (PS tables write per-shard files);
- auto-checkpoint: ``python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py``
  (periodic snapshots + resume-on-restart).

TPU-native design: a checkpoint is a directory of raw per-shard ``.npy``
files + one ``metadata.json`` describing the state tree (global shape, dtype,
and each shard's start offsets). Loading re-slices through
``jax.make_array_from_callback`` so a checkpoint written on one mesh loads
onto any other mesh/sharding, reading only the bytes each device needs.
Saving is optionally async (device->host copies happen on the caller thread,
file IO on a background thread) — the orbax pattern, dependency-free.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "save_state", "load_state", "AsyncSaver", "AutoCheckpoint",
    "latest_checkpoint",
]

_METADATA = "metadata.json"


def _flatten(tree):
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    flat = {}
    for path, leaf in leaves:
        key = "/".join(_path_elem(p) for p in path)
        flat[key] = leaf
    return flat, treedef


def _path_elem(p):
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def _safe(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", key)


def _leaf_record(key: str, arr) -> Dict[str, Any]:
    if isinstance(arr, (int, float, bool)):
        return {"kind": "scalar", "value": arr}
    if isinstance(arr, str):
        return {"kind": "str", "value": arr}
    arr_j = arr if isinstance(arr, jax.Array) else jnp.asarray(arr)
    return {
        "kind": "array",
        "shape": list(arr_j.shape),
        "dtype": str(arr_j.dtype),
    }


def save_state(state: Any, directory: str, *, async_=False,
               io_threads: int = 8) -> Optional["_PendingSave"]:
    """Save a pytree of arrays as a sharded checkpoint directory.

    Each addressable shard of each leaf becomes one ``.npy`` file (a unique
    per-leaf index prefixes the name, so distinct keys never collide after
    sanitisation); ``metadata.json`` records the tree. Multi-process: each
    process writes only shards it owns (``replica_id == 0``) and its own
    ``metadata[.<proc>].json``; :func:`load_state` merges them. With
    ``async_=True`` the device->host copies happen on the caller thread and
    the file IO on ``io_threads`` background threads; the returned handle's
    ``.wait()`` joins the IO and reports/raises any IO error.
    """
    flat, _ = _flatten(state)
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    meta: Dict[str, Any] = {"format": "paddle_tpu.ckpt.v1", "leaves": {}}
    jobs = []  # (filename, host numpy copy) — snapshotted before returning
    for leaf_i, (key, leaf) in enumerate(flat.items()):
        rec = _leaf_record(key, leaf)
        meta["leaves"][key] = rec
        if rec["kind"] != "array":
            continue
        shards = []
        prefix = f"L{leaf_i:04d}_{_safe(key)}"
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:  # replicated copies: one writer
                    continue
                start = tuple(
                    0 if idx.start is None else int(idx.start)
                    for idx in shard.index) if shard.index else ()
                data = np.asarray(shard.data)
                fname = prefix + "__" + "_".join(map(str, start)) + ".npy"
                shards.append({"file": fname, "start": list(start),
                               "shape": list(data.shape)})
                jobs.append((os.path.join(directory, fname), data))
        else:
            # copy: async IO must see a snapshot, not later in-place updates
            data = np.array(leaf, copy=True)
            fname = prefix + "__" + "_".join(["0"] * data.ndim) + ".npy"
            shards.append({"file": fname, "start": [0] * data.ndim,
                           "shape": list(data.shape)})
            jobs.append((os.path.join(directory, fname), data))
        rec["shards"] = shards

    meta_name = _METADATA if proc == 0 else f"metadata.{proc}.json"

    def do_io():
        import concurrent.futures as cf

        def write(job):
            path, data = job
            with open(path, "wb") as f:
                np.save(f, data)

        if len(jobs) > 1 and io_threads > 1:
            with cf.ThreadPoolExecutor(max_workers=io_threads) as pool:
                for _ in pool.map(write, jobs):
                    pass
        else:
            for job in jobs:
                write(job)
        # metadata written last = commit marker for this process
        with open(os.path.join(directory, meta_name), "w") as f:
            json.dump(meta, f, indent=1)

    if async_:
        pending = _PendingSave(directory)
        t = threading.Thread(target=pending._run, args=(do_io,), daemon=True)
        pending._thread = t
        t.start()
        return pending
    do_io()
    return None


class _PendingSave:
    def __init__(self, directory):
        self._thread: Optional[threading.Thread] = None
        self.directory = directory
        self.error: Optional[BaseException] = None

    def _run(self, fn):
        try:
            fn()
        except BaseException as e:  # surfaced via wait()
            self.error = e

    def wait(self, timeout=None):
        """Join the IO. Returns False on timeout; raises if the save failed."""
        self._thread.join(timeout)
        if self._thread.is_alive():
            return False
        if self.error is not None:
            raise RuntimeError(
                f"async checkpoint save to {self.directory} failed") from self.error
        return True

    @property
    def done(self):
        return not self._thread.is_alive()


class _LeafReader:
    """Assembles arbitrary slices of one leaf from its shard files."""

    def __init__(self, directory: str, rec: Dict[str, Any]):
        self.directory = directory
        self.rec = rec
        self.shape = tuple(rec["shape"])
        self._cache: Dict[str, np.ndarray] = {}

    def _shard_data(self, shard) -> np.ndarray:
        f = shard["file"]
        if f not in self._cache:
            raw = np.load(os.path.join(self.directory, f))
            want = jnp.dtype(self.rec["dtype"])
            if raw.dtype != want:
                # extended dtypes (bfloat16, fp8) round-trip npy as void
                raw = raw.view(want) if raw.dtype.itemsize == want.itemsize \
                    else raw.astype(want)
            self._cache[f] = raw
        return self._cache[f]

    def read(self, index) -> np.ndarray:
        """index: tuple of slices into the global shape."""
        want_start = tuple(0 if s.start is None else int(s.start) for s in index)
        want_stop = tuple(dim if s.stop is None else int(s.stop)
                          for s, dim in zip(index, self.shape))
        out_shape = tuple(b - a for a, b in zip(want_start, want_stop))
        out = None
        covered = 0
        want_elems = int(np.prod(out_shape)) if out_shape else 1
        for shard in self.rec["shards"]:
            s_start = tuple(shard["start"])
            s_stop = tuple(a + b for a, b in zip(s_start, shard["shape"]))
            inter_start = tuple(max(a, b) for a, b in zip(want_start, s_start))
            inter_stop = tuple(min(a, b) for a, b in zip(want_stop, s_stop))
            if any(a >= b for a, b in zip(inter_start, inter_stop)):
                continue  # no overlap (vacuously false for 0-d leaves)
            data = self._shard_data(shard)
            if out is None:
                out = np.empty(out_shape, data.dtype)
            src = tuple(slice(a - o, b - o) for a, b, o in
                        zip(inter_start, inter_stop, s_start))
            dst = tuple(slice(a - o, b - o) for a, b, o in
                        zip(inter_start, inter_stop, want_start))
            out[dst] = data[src]
            covered += int(np.prod([b - a for a, b in
                                    zip(inter_start, inter_stop)])) if out_shape else 1
        # shards never overlap each other (distinct start offsets of one
        # sharding), so covered elements == requested elements iff complete
        if out is None or covered < want_elems:
            raise ValueError(
                f"checkpoint shards cover only {covered}/{want_elems} elements "
                f"of requested slice {index} — incomplete checkpoint?")
        return out


def load_state(directory: str, shardings: Optional[Dict[str, Any]] = None,
               template: Any = None) -> Dict[str, Any]:
    """Load a checkpoint directory.

    - plain load: returns a flat ``{key: np.ndarray}`` dict (or scalars).
    - with ``shardings`` (flat ``{key: jax.sharding.Sharding}``): each leaf is
      materialised directly onto its target sharding via
      ``make_array_from_callback`` — re-slicing happens per-device, so a
      checkpoint saved on mesh A loads onto mesh B without a full gather.
    - with ``template`` (a pytree): result is unflattened into that structure.
    """
    with open(os.path.join(directory, _METADATA)) as f:
        meta = json.load(f)
    # merge shard lists from other processes' metadata (multi-host save)
    for name in sorted(os.listdir(directory)):
        if name != _METADATA and re.match(r"^metadata\.\d+\.json$", name):
            with open(os.path.join(directory, name)) as f:
                other = json.load(f)
            for key, rec in other.get("leaves", {}).items():
                mine = meta["leaves"].setdefault(key, rec)
                if rec.get("kind") == "array" and mine is not rec:
                    mine.setdefault("shards", []).extend(rec.get("shards", []))
    flat_out: Dict[str, Any] = {}
    for key, rec in meta["leaves"].items():
        if rec["kind"] == "scalar":
            flat_out[key] = rec["value"]
            continue
        if rec["kind"] == "str":
            flat_out[key] = rec["value"]
            continue
        reader = _LeafReader(directory, rec)
        shape = tuple(rec["shape"])
        sharding = (shardings or {}).get(key)
        if sharding is not None:
            flat_out[key] = jax.make_array_from_callback(
                shape, sharding, reader.read)
        else:
            flat_out[key] = reader.read(tuple(slice(0, d) for d in shape))
    if template is not None:
        flat_t, treedef = _flatten(template)
        ordered = [flat_out[k] for k in flat_t]
        return jax.tree_util.tree_unflatten(treedef, ordered)
    return flat_out


# --------------------------------------------------------------------------
# auto checkpoint: periodic snapshots + resume (reference auto_checkpoint.py)
# --------------------------------------------------------------------------

_STEP_DIR = re.compile(r"^step_(\d+)$")


def latest_checkpoint(root: str) -> Optional[str]:
    if not os.path.isdir(root):
        return None
    best, best_step = None, -1
    for name in os.listdir(root):
        m = _STEP_DIR.match(name)
        if m and os.path.exists(os.path.join(root, name, _METADATA)):
            step = int(m.group(1))
            if step > best_step:
                best, best_step = os.path.join(root, name), step
    return best


class AutoCheckpoint:
    """Periodic snapshot + resume-on-restart manager.

    ``maybe_save(step, state)`` saves every ``save_interval_steps`` (or
    seconds); completed saves rotate down to ``keep_max`` directories.
    ``restore()`` returns ``(step, state_dict)`` of the newest complete
    snapshot, or ``(0, None)``.
    """

    def __init__(self, root: str, save_interval_steps: int = 100,
                 save_interval_seconds: Optional[float] = None,
                 keep_max: int = 3, async_save: bool = True):
        self.root = root
        self.save_interval_steps = save_interval_steps
        self.save_interval_seconds = save_interval_seconds
        self.keep_max = keep_max
        self.async_save = async_save
        self._last_save_time = time.monotonic()
        self._last_step = -1
        self._pending: Optional[_PendingSave] = None
        os.makedirs(root, exist_ok=True)

    def _due(self, step):
        if self.save_interval_seconds is not None:
            return time.monotonic() - self._last_save_time >= self.save_interval_seconds
        return step % self.save_interval_steps == 0 and step != self._last_step

    def maybe_save(self, step: int, state: Any) -> bool:
        if not self._due(step):
            return False
        self.save(step, state)
        return True

    def save(self, step: int, state: Any):
        if self._pending is not None:
            self._pending.wait()
        directory = os.path.join(self.root, f"step_{step}")
        tmp = directory + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        pending = save_state(state, tmp, async_=self.async_save)

        def finalize():
            if os.path.exists(directory):
                shutil.rmtree(directory)
            os.rename(tmp, directory)
            self._gc()

        if pending is None:
            finalize()
        else:
            orig_wait = pending.wait

            def wait_and_finalize(timeout=None):
                ok = orig_wait(timeout)
                if ok and os.path.exists(tmp):
                    finalize()
                return ok
            pending.wait = wait_and_finalize
            self._pending = pending
        self._last_save_time = time.monotonic()
        self._last_step = step

    def wait(self):
        if self._pending is not None:
            self._pending.wait()
            self._pending = None

    def _gc(self):
        steps = sorted(
            (int(m.group(1)) for m in map(_STEP_DIR.match, os.listdir(self.root)) if m),
            reverse=True)
        for step in steps[self.keep_max:]:
            shutil.rmtree(os.path.join(self.root, f"step_{step}"), ignore_errors=True)

    def restore(self, shardings=None, template=None):
        self.wait()
        path = latest_checkpoint(self.root)
        if path is None:
            return 0, None
        step = int(_STEP_DIR.match(os.path.basename(path)).group(1))
        return step, load_state(path, shardings=shardings, template=template)


class AsyncSaver:
    """Fire-and-forget async saver with at-most-one outstanding save."""

    def __init__(self):
        self._pending: Optional[_PendingSave] = None

    def save(self, state, directory):
        if self._pending is not None:
            self._pending.wait()
        self._pending = save_state(state, directory, async_=True)
        return self._pending

    def wait(self):
        if self._pending is not None:
            self._pending.wait()
            self._pending = None
