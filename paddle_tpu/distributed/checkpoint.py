"""Distributed checkpointing.

Reference parity:
- per-shard save + re-slicing metadata: auto-parallel ``dist_saver.py``
  (``python/paddle/distributed/auto_parallel/dist_saver.py``) which dumps
  per-rank shards plus dist_attr for re-slicing on a different topology;
- ``fleet.save_persistables`` table dump (PS tables write per-shard files);
- auto-checkpoint: ``python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py``
  (periodic snapshots + resume-on-restart).

TPU-native design: a checkpoint is a directory of raw per-shard ``.npy``
files + one ``metadata.json`` describing the state tree (global shape, dtype,
and each shard's start offsets). Loading re-slices through
``jax.make_array_from_callback`` so a checkpoint written on one mesh loads
onto any other mesh/sharding, reading only the bytes each device needs.
Saving is optionally async (device->host copies happen on the caller thread,
file IO on a background thread) — the orbax pattern, dependency-free.

Crash safety (the resilience layer's contract): a checkpoint directory is
either COMPLETE or INVISIBLE. Single-process saves stage everything in a
``<dir>.tmp-pt*`` sibling — shards fsync'd, every shard crc32-checksummed
into ``metadata.json``, the metadata fsync'd last — and publish with one
``os.replace``; a SIGKILL at any point leaves only the staging dir, which
:class:`AutoCheckpoint` sweeps on startup. Multi-process saves share the
target directory, so publish is per-file (tmp + fsync + ``os.replace``)
with each process's metadata written last as its commit marker.
:func:`load_state` verifies checksums (raising
:class:`CheckpointCorruptError` on torn/corrupt data) and
:func:`latest_checkpoint` validates candidates, silently skipping
incomplete or corrupt step dirs so restore falls back to the newest GOOD
checkpoint.
"""
from __future__ import annotations

import io
import json
import os
import re
import shutil
import tempfile
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from .resilience import fault_point

__all__ = [
    "save_state", "load_state", "AsyncSaver", "AutoCheckpoint",
    "latest_checkpoint", "validate_checkpoint", "CheckpointCorruptError",
    "mesh_info", "last_load_stats",
]

_METADATA = "metadata.json"
_TMP_MARK = ".tmp-pt"  # staging dirs: <target>.tmp-pt<pid>


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification (missing metadata,
    missing shard file, size mismatch, or crc32 mismatch). The message
    names the offending file and what differed."""


def _flatten(tree):
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    flat = {}
    for path, leaf in leaves:
        key = "/".join(_path_elem(p) for p in path)
        flat[key] = leaf
    return flat, treedef


def _path_elem(p):
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def _safe(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", key)


def _leaf_record(key: str, arr) -> Dict[str, Any]:
    if isinstance(arr, (int, float, bool)):
        return {"kind": "scalar", "value": arr}
    if isinstance(arr, str):
        return {"kind": "str", "value": arr}
    arr_j = arr if isinstance(arr, jax.Array) else jnp.asarray(arr)
    rec = {
        "kind": "array",
        "shape": list(arr_j.shape),
        "dtype": str(arr_j.dtype),
    }
    spec = _spec_of(arr)
    if spec is not None:
        rec["spec"] = spec
    return rec


def _spec_of(arr) -> Optional[list]:
    """JSON-serializable PartitionSpec of a NamedSharding-ed array (None
    for host values / non-named shardings). Axis tuples become lists."""
    sharding = getattr(arr, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    return [list(s) if isinstance(s, (tuple, list)) else s for s in spec]


def _mesh_of(state_leaves) -> Optional[Dict[str, Any]]:
    """Mesh axes/device-count of the first NamedSharding-ed leaf — the
    topology this checkpoint was WRITTEN on, recorded so a restore onto a
    different mesh can report/plan the re-slice (elastic shrink/grow)."""
    for leaf in state_leaves:
        mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
        if mesh is not None and hasattr(mesh, "shape"):
            try:
                return {"axes": {str(k): int(v)
                                 for k, v in dict(mesh.shape).items()},
                        "devices": int(np.prod(list(mesh.shape.values())))}
            except Exception:
                return None
    return None


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; best effort
    finally:
        os.close(fd)


def _write_file_durable(path: str, raw: bytes, atomic: bool) -> None:
    """Write+fsync ``raw``; with ``atomic``, stage at a process-unique
    ``path + ".tmp<pid>"`` and ``os.replace`` so a concurrent reader never
    sees a torn file. The pid suffix matters in multi-process saves: a
    REPLICATED host leaf (e.g. ``base_key``) is written by every process
    to the same target, and a shared ``.tmp`` name would let one writer's
    rename steal another's staging file mid-flight."""
    target = f"{path}.tmp{os.getpid()}" if atomic else path
    with open(target, "wb") as f:
        f.write(raw)
        f.flush()
        os.fsync(f.fileno())
    if atomic:
        os.replace(target, path)


def save_state(state: Any, directory: str, *, async_=False,
               io_threads: int = 8,
               extra_meta: Optional[Dict[str, Any]] = None,
               extra_files: Optional[Dict[str, bytes]] = None,
               ) -> Optional["_PendingSave"]:
    """Save a pytree of arrays as a sharded checkpoint directory.

    Each addressable shard of each leaf becomes one ``.npy`` file (a unique
    per-leaf index prefixes the name, so distinct keys never collide after
    sanitisation); ``metadata.json`` records the tree plus each shard's
    byte length and crc32. Multi-process: each process writes only shards
    it owns (``replica_id == 0``) and its own ``metadata[.<proc>].json``;
    :func:`load_state` merges them. With ``async_=True`` the device->host
    copies happen on the caller thread and the file IO on ``io_threads``
    background threads; the returned handle's ``.wait()`` joins the IO and
    reports/raises any IO error.

    Publication is crash-safe: single-process saves stage in a
    ``.tmp-pt<pid>`` sibling directory and appear atomically via
    ``os.replace``; multi-process saves write each file atomically into
    the shared directory with metadata last as the commit marker. A
    process killed mid-save never leaves a directory that
    :func:`latest_checkpoint`/:func:`load_state` would accept.

    ``extra_meta`` merges additional records into ``metadata.json`` —
    including overriding ``format`` (the LoRA adapter registry stamps
    ``format: "lora_adapter"`` so :func:`load_state` can refuse to
    restore an adapter as a full model). The structural keys
    (``leaves``/``process_count``/``mesh``) cannot be overridden.

    ``extra_files`` are sidecar records (name -> raw bytes) written by
    process 0 INSIDE the publish barrier — before metadata, so they
    appear atomically with the checkpoint (the integrity ledger
    ``integrity.json`` rides here). Names must not collide with
    ``metadata*.json`` or shard files.
    """
    flat, _ = _flatten(state)
    proc = jax.process_index()
    nprocs = jax.process_count()
    multiproc = nprocs > 1
    directory = directory.rstrip(os.sep)
    # single-writer: stage EVERYTHING in a sibling dir, publish by rename;
    # multi-writer: processes share the target dir, so publish per-file
    stage_dir = (directory if multiproc
                 else f"{directory}{_TMP_MARK}{os.getpid()}")
    if not multiproc and os.path.exists(stage_dir):
        shutil.rmtree(stage_dir)
    os.makedirs(stage_dir, exist_ok=True)
    # process_count lets validators detect a MISSING peer metadata file
    # (a peer killed pre-commit) instead of silently loading partial state
    meta: Dict[str, Any] = {"format": "paddle_tpu.ckpt.v1",
                            "process_count": nprocs, "leaves": {}}
    if extra_meta:
        reserved = {"leaves", "process_count", "mesh"}
        bad = reserved & set(extra_meta)
        if bad:
            raise ValueError(
                f"extra_meta may not override structural metadata keys "
                f"{sorted(bad)}")
        meta.update(extra_meta)
    # the mesh this checkpoint was written on (axes + device count): enough
    # for a restore onto a DIFFERENT topology to plan/report the re-slice
    # (elastic shrink/grow). Absent for host-only state; old checkpoints
    # without it restore through the same-topology path unchanged.
    written_mesh = _mesh_of(flat.values())
    if written_mesh is not None:
        meta["mesh"] = written_mesh
    jobs = []  # (filename, host numpy copy, shard record to patch)
    for leaf_i, (key, leaf) in enumerate(flat.items()):
        rec = _leaf_record(key, leaf)
        meta["leaves"][key] = rec
        if rec["kind"] != "array":
            continue
        shards = []
        prefix = f"L{leaf_i:04d}_{_safe(key)}"
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:  # replicated copies: one writer
                    continue
                start = tuple(
                    0 if idx.start is None else int(idx.start)
                    for idx in shard.index) if shard.index else ()
                data = np.asarray(shard.data)
                fname = prefix + "__" + "_".join(map(str, start)) + ".npy"
                # "process" = writer rank: after a host loss, validators
                # can say exactly WHOSE shards are gone
                srec = {"file": fname, "start": list(start),
                        "shape": list(data.shape), "process": proc}
                shards.append(srec)
                jobs.append((fname, data, srec))
        else:
            # copy: async IO must see a snapshot, not later in-place updates
            data = np.array(leaf, copy=True)
            fname = prefix + "__" + "_".join(["0"] * data.ndim) + ".npy"
            srec = {"file": fname, "start": [0] * data.ndim,
                    "shape": list(data.shape), "process": proc}
            shards.append(srec)
            jobs.append((fname, data, srec))
        rec["shards"] = shards

    meta_name = _METADATA if proc == 0 else f"metadata.{proc}.json"

    def do_io():
        import concurrent.futures as cf

        def write(job):
            fname, data, srec = job
            buf = io.BytesIO()
            np.save(buf, data)
            raw = buf.getvalue()
            srec["bytes"] = len(raw)
            srec["crc32"] = zlib.crc32(raw) & 0xFFFFFFFF
            fault_point("ckpt.shard_write")
            _write_file_durable(os.path.join(stage_dir, fname), raw,
                                atomic=multiproc)

        if len(jobs) > 1 and io_threads > 1:
            with cf.ThreadPoolExecutor(max_workers=io_threads) as pool:
                for _ in pool.map(write, jobs):
                    pass
        else:
            for job in jobs:
                write(job)
        if extra_files and proc == 0:
            for name, raw in extra_files.items():
                _write_file_durable(os.path.join(stage_dir, name),
                                    bytes(raw), atomic=multiproc)
        # metadata written last = this process's commit marker (and, via
        # the dir rename below, the single-process publish barrier)
        fault_point("ckpt.publish")
        _write_file_durable(os.path.join(stage_dir, meta_name),
                            json.dumps(meta, indent=1).encode(),
                            atomic=multiproc)
        _fsync_dir(stage_dir)
        if not multiproc:
            trash = None
            if os.path.exists(directory):
                # same-name overwrite: POSIX replaces only EMPTY target
                # dirs, so move the old checkpoint ASIDE first — a crash
                # between the two renames leaves the old data recoverable
                # under .old-pt rather than a window with nothing at all
                trash = f"{directory}.old-pt{os.getpid()}"
                if os.path.exists(trash):
                    shutil.rmtree(trash)
                os.replace(directory, trash)
            os.replace(stage_dir, directory)
            parent = os.path.dirname(os.path.abspath(directory))
            _fsync_dir(parent)
            if trash is not None:
                shutil.rmtree(trash, ignore_errors=True)

    if async_:
        pending = _PendingSave(directory)
        t = threading.Thread(target=pending._run, args=(do_io,), daemon=True)
        pending._thread = t
        t.start()
        return pending
    do_io()
    return None


class _PendingSave:
    def __init__(self, directory):
        self._thread: Optional[threading.Thread] = None
        self.directory = directory
        self.error: Optional[BaseException] = None

    def _run(self, fn):
        try:
            fn()
        except BaseException as e:  # surfaced via wait()
            self.error = e

    def wait(self, timeout=None):
        """Join the IO. Returns False on timeout; raises if the save failed."""
        self._thread.join(timeout)
        if self._thread.is_alive():
            return False
        if self.error is not None:
            raise RuntimeError(
                f"async checkpoint save to {self.directory} failed") from self.error
        return True

    @property
    def done(self):
        return not self._thread.is_alive()


def _read_shard_file(directory: str, shard: Dict[str, Any],
                     verify: bool = True) -> np.ndarray:
    """Read one shard ``.npy``, verifying recorded size/crc32 when present
    (older checkpoints without checksums load unverified). Verification
    streams the file (1 MB chunks) so peak memory stays ~1x the decoded
    array, not raw-bytes + array."""
    path = os.path.join(directory, shard["file"])
    rank = shard.get("process")
    whose = f" (written by rank {rank})" if rank is not None else ""
    try:
        if verify:
            want_len = shard.get("bytes")
            if want_len is not None:
                size = os.path.getsize(path)
                if size != want_len:
                    raise CheckpointCorruptError(
                        f"checkpoint shard {path}: {size} bytes on disk, "
                        f"metadata records {want_len} (truncated/torn "
                        f"write)")
            want_crc = shard.get("crc32")
            if want_crc is not None:
                got = _file_crc32(path)
                if got != want_crc:
                    raise CheckpointCorruptError(
                        f"checkpoint shard {path}: crc32 {got:#010x} != "
                        f"recorded {want_crc:#010x} (bit rot or torn write)")
        return np.load(path)
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"checkpoint shard missing: {path}{whose} (torn save or lost "
            f"host?)") from None
    except ValueError as e:
        raise CheckpointCorruptError(
            f"checkpoint shard {path}: undecodable npy: {e}") from e


# default per-leaf shard-cache bound for streaming (re-sliced) loads: big
# enough that small/medium checkpoints never evict, small enough that a
# multi-GB param tree restored onto a reshaped mesh stays bounded on host
DEFAULT_SHARD_CACHE_BYTES = 1 << 28  # 256 MiB

# accounting for the most recent load_state call (single-threaded loads;
# see last_load_stats)
_LOAD_STATS = {"peak_resident_bytes": 0, "bytes_read": 0,
               "shard_reads": 0, "evictions": 0, "leaves": 0}


def last_load_stats() -> Dict[str, int]:
    """Host-memory accounting of the most recent :func:`load_state`:
    ``peak_resident_bytes`` is the maximum decoded shard bytes any single
    leaf's reader held at once — the restore path's working set, which a
    bounded-memory (elastic reshard) restore asserts stays far below the
    full tree size. ``bytes_read``/``shard_reads`` count shard file
    decodes (a shard evicted under the cache bound and needed again is
    re-read — memory is the bounded resource, IO the price)."""
    return dict(_LOAD_STATS)


def _reset_load_stats() -> None:
    for k in _LOAD_STATS:
        _LOAD_STATS[k] = 0


class _LeafReader:
    """Assembles arbitrary slices of one leaf from its shard files,
    holding at most ``max_cache_bytes`` of decoded shards at a time (LRU;
    the shard being served is never evicted)."""

    def __init__(self, directory: str, rec: Dict[str, Any],
                 verify: bool = True,
                 max_cache_bytes: Optional[int] = DEFAULT_SHARD_CACHE_BYTES):
        self.directory = directory
        self.rec = rec
        self.verify = verify
        self.shape = tuple(rec["shape"])
        self.max_cache_bytes = max_cache_bytes
        self._cache: Dict[str, np.ndarray] = {}
        self._resident = 0

    def _shard_data(self, shard) -> np.ndarray:
        f = shard["file"]
        if f in self._cache:
            self._cache[f] = self._cache.pop(f)  # LRU: move to back
            return self._cache[f]
        raw = _read_shard_file(self.directory, shard, self.verify)
        want = jnp.dtype(self.rec["dtype"])
        if raw.dtype != want:
            # extended dtypes (bfloat16, fp8) round-trip npy as void
            raw = raw.view(want) if raw.dtype.itemsize == want.itemsize \
                else raw.astype(want)
        self._cache[f] = raw
        self._resident += raw.nbytes
        _LOAD_STATS["shard_reads"] += 1
        _LOAD_STATS["bytes_read"] += raw.nbytes
        # peak is taken BEFORE eviction: at the decode moment the new
        # shard and the full cache coexist — that transient is the true
        # working set the bound must be judged against
        _LOAD_STATS["peak_resident_bytes"] = max(
            _LOAD_STATS["peak_resident_bytes"], self._resident)
        while (self.max_cache_bytes is not None
               and self._resident > self.max_cache_bytes
               and len(self._cache) > 1):
            oldest = next(iter(self._cache))
            self._resident -= self._cache.pop(oldest).nbytes
            _LOAD_STATS["evictions"] += 1
        return self._cache[f]

    def read(self, index) -> np.ndarray:
        """index: tuple of slices into the global shape."""
        want_start = tuple(0 if s.start is None else int(s.start) for s in index)
        want_stop = tuple(dim if s.stop is None else int(s.stop)
                          for s, dim in zip(index, self.shape))
        out_shape = tuple(b - a for a, b in zip(want_start, want_stop))
        out = None
        covered = 0
        want_elems = int(np.prod(out_shape)) if out_shape else 1
        for shard in self.rec["shards"]:
            s_start = tuple(shard["start"])
            s_stop = tuple(a + b for a, b in zip(s_start, shard["shape"]))
            inter_start = tuple(max(a, b) for a, b in zip(want_start, s_start))
            inter_stop = tuple(min(a, b) for a, b in zip(want_stop, s_stop))
            if any(a >= b for a, b in zip(inter_start, inter_stop)):
                continue  # no overlap (vacuously false for 0-d leaves)
            data = self._shard_data(shard)
            if out is None:
                out = np.empty(out_shape, data.dtype)
            src = tuple(slice(a - o, b - o) for a, b, o in
                        zip(inter_start, inter_stop, s_start))
            dst = tuple(slice(a - o, b - o) for a, b, o in
                        zip(inter_start, inter_stop, want_start))
            out[dst] = data[src]
            covered += int(np.prod([b - a for a, b in
                                    zip(inter_start, inter_stop)])) if out_shape else 1
        # shards never overlap each other (distinct start offsets of one
        # sharding), so covered elements == requested elements iff complete
        if out is None or covered < want_elems:
            raise ValueError(
                f"checkpoint shards cover only {covered}/{want_elems} elements "
                f"of requested slice {index} — incomplete checkpoint?")
        return out


def load_state(directory: str, shardings: Optional[Dict[str, Any]] = None,
               template: Any = None,
               verify: Union[bool, str] = True,
               max_shard_cache_bytes: Optional[int] =
               DEFAULT_SHARD_CACHE_BYTES) -> Dict[str, Any]:
    """Load a checkpoint directory.

    - plain load: returns a flat ``{key: np.ndarray}`` dict (or scalars).
    - with ``shardings`` (flat ``{key: jax.sharding.Sharding}``): each leaf is
      materialised directly onto its target sharding via
      ``make_array_from_callback`` — re-slicing happens per-device, so a
      checkpoint saved on mesh A loads onto mesh B (different shape, axis
      layout, or host count — the elastic shrink/grow restore) without ever
      assembling a full global array on one host. Peak host memory per leaf
      is bounded by ``max_shard_cache_bytes`` of decoded source shards
      (LRU; an evicted shard needed again is re-read — see
      :func:`last_load_stats`). ``None`` disables the bound.
    - with ``template`` (a pytree): result is unflattened into that structure.

    With ``verify`` (default), every shard file read is checked against the
    byte length and crc32 recorded at save time; a missing/truncated/
    corrupted shard or missing metadata raises
    :class:`CheckpointCorruptError` naming the file, the writer rank, and
    the mismatch. The sharded-load path reads LAZILY per device, so with
    plain ``verify=True`` a shard no device asks for is never
    content-checked; ``verify="proactive"`` closes that hole by running a
    full :func:`validate_checkpoint` crc pass over EVERY recorded shard
    up front, before any leaf is materialised — the mode supervisor
    restores use. Each byte is still read+checked exactly once (per-read
    re-verification is skipped after the proactive pass).
    """
    _reset_load_stats()
    try:
        with open(os.path.join(directory, _METADATA)) as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"{directory}: no {_METADATA} — not a (complete) checkpoint "
            "directory; the save may have been killed before publishing"
        ) from None
    except json.JSONDecodeError as e:
        raise CheckpointCorruptError(
            f"{directory}/{_METADATA}: undecodable metadata: {e}") from e
    # a LoRA adapter checkpoint holds ONLY lora_A/lora_B leaves: fed to a
    # full-model restore (a template expecting base weights) it would
    # otherwise die on a confusing missing-leaves error deep below —
    # name the real problem instead
    if meta.get("format") == "lora_adapter" and template is not None:
        flat_t, _ = _flatten(template)
        non_lora = [k for k in flat_t
                    if k.rsplit("/", 1)[-1].rsplit(".", 1)[-1]
                    not in ("lora_A", "lora_B")]
        if non_lora:
            raise ValueError(
                f"{directory} is a LoRA ADAPTER checkpoint (format="
                f"'lora_adapter'): it carries only adapter leaves and "
                f"cannot restore a full model (template expects e.g. "
                f"{non_lora[0]!r}). Load the base model first, then "
                f"attach the adapter via paddle_tpu.lora.load_adapter / "
                f"AdapterStore.load")
    # merge shard lists from other processes' metadata (multi-host save);
    # files at or beyond process_count are STALE leftovers from an earlier
    # larger-world save into the same path — merging them would mix shards
    # from a different training trajectory into the restored state
    nprocs = meta.get("process_count")
    seen_procs = {0}
    for name in sorted(os.listdir(directory)):
        proc_i = _meta_proc(name)
        if proc_i is not None and (nprocs is None or proc_i < nprocs):
            seen_procs.add(proc_i)
            with open(os.path.join(directory, name)) as f:
                other = json.load(f)
            for key, rec in other.get("leaves", {}).items():
                mine = meta["leaves"].setdefault(key, rec)
                if rec.get("kind") == "array" and mine is not rec:
                    mine.setdefault("shards", []).extend(rec.get("shards", []))
    if verify and nprocs is not None:
        absent = set(range(nprocs)) - seen_procs
        if absent:
            raise CheckpointCorruptError(
                f"{directory}: metadata missing for process(es) "
                f"{sorted(absent)} — a peer was killed before committing; "
                f"its shards are not recoverable from this directory")
    read_verify = bool(verify)
    if verify == "proactive":
        problem = validate_checkpoint(directory, checksums=True)
        if problem is not None:
            raise CheckpointCorruptError(problem)
        read_verify = False  # every shard just passed a full crc pass
    flat_out: Dict[str, Any] = {}
    for key, rec in meta["leaves"].items():
        if rec["kind"] == "scalar":
            flat_out[key] = rec["value"]
            continue
        if rec["kind"] == "str":
            flat_out[key] = rec["value"]
            continue
        reader = _LeafReader(directory, rec, verify=read_verify,
                             max_cache_bytes=max_shard_cache_bytes)
        _LOAD_STATS["leaves"] += 1
        shape = tuple(rec["shape"])
        sharding = (shardings or {}).get(key)
        if sharding is not None:
            flat_out[key] = jax.make_array_from_callback(
                shape, sharding, reader.read)
        else:
            flat_out[key] = reader.read(tuple(slice(0, d) for d in shape))
    if template is not None:
        flat_t, treedef = _flatten(template)
        missing = [k for k in flat_t if k not in flat_out]
        if missing:
            raise CheckpointCorruptError(
                f"{directory}: checkpoint lacks {len(missing)} leaf/leaves "
                f"the template expects (first: {missing[0]!r}). A checkpoint "
                f"written before the state tree gained new leaves (e.g. "
                f"base_key/scaler_state) loads fine WITHOUT a template, or "
                f"through TrainingSupervisor.restore(), which treats those "
                f"leaves as optional.")
        ordered = [flat_out[k] for k in flat_t]
        return jax.tree_util.tree_unflatten(treedef, ordered)
    return flat_out


# --------------------------------------------------------------------------
# auto checkpoint: periodic snapshots + resume (reference auto_checkpoint.py)
# --------------------------------------------------------------------------

_STEP_DIR = re.compile(r"^step_(\d+)$")


def _meta_proc(name: str) -> Optional[int]:
    """Process index of a ``metadata.N.json`` file name (None for the
    primary ``metadata.json``)."""
    m = re.match(r"^metadata\.(\d+)\.json$", name)
    return int(m.group(1)) if m else None


def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    """Streaming crc32 — never materialises the file (crc32 is
    incremental), so validating multi-GB shards costs one buffer."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def validate_checkpoint(directory: str,
                        checksums: bool = True) -> Optional[str]:
    """Integrity-check a checkpoint directory WITHOUT materialising arrays.

    Returns ``None`` when every metadata file parses and every recorded
    shard exists with matching byte length and (with ``checksums``) crc32;
    otherwise a string describing the problem. Missing shard files and
    missing per-process commit markers are AGGREGATED and attributed to
    writer ranks — after a host loss the report says exactly which ranks'
    shards are gone (and names example keys) rather than the first missing
    file. ``checksums=False`` is the cheap stat-only mode for housekeeping
    paths (retention GC) that must not re-read every shard byte.
    Pre-checksum checkpoints (no recorded crc) validate on existence/size
    only. Completeness is topology-agnostic: a directory that validates
    restores onto ANY target mesh (the re-slice plans itself from the
    recorded offsets), so ``latest_checkpoint`` falling back to the newest
    valid candidate is exactly "newest complete for the target topology".
    """
    metas: List[str] = []
    try:
        for name in sorted(os.listdir(directory)):
            if name == _METADATA or re.match(r"^metadata\.\d+\.json$", name):
                metas.append(name)
    except OSError as e:
        return f"{directory}: unreadable: {e}"
    if _METADATA not in metas:
        return f"{directory}: no {_METADATA} (unpublished/torn save)"
    try:
        with open(os.path.join(directory, _METADATA)) as f:
            nprocs = json.load(f).get("process_count")
    except (OSError, json.JSONDecodeError) as e:
        return f"{directory}/{_METADATA}: undecodable metadata: {e}"
    if nprocs is not None:
        # every process's commit marker must exist — a peer killed before
        # its metadata write means its shards are silently absent
        lost = [p for p in range(1, nprocs)
                if f"metadata.{p}.json" not in metas]
        if lost:
            names = ", ".join(f"metadata.{p}.json" for p in lost)
            return (f"{directory}: missing {names} — rank(s) {lost} of a "
                    f"{nprocs}-process save never committed (killed "
                    f"pre-commit or host lost); their shards are not "
                    f"recoverable from this directory")
        # ...and markers BEYOND process_count are stale leftovers from an
        # earlier larger-world save into this path: skip them, exactly as
        # load_state does (pre-process_count checkpoints check everything)
        metas = [n for n in metas
                 if _meta_proc(n) is None or _meta_proc(n) < nprocs]
    gone: List[tuple] = []  # (rank-or-None, key, file) of missing shards
    bad: List[str] = []     # size/crc/readability problems
    for name in metas:
        try:
            with open(os.path.join(directory, name)) as f:
                meta = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            return f"{directory}/{name}: undecodable metadata: {e}"
        for key, rec in meta.get("leaves", {}).items():
            for shard in rec.get("shards", []):
                path = os.path.join(directory, shard["file"])
                try:
                    size = os.path.getsize(path)
                except OSError:
                    gone.append((shard.get("process"), key, shard["file"]))
                    continue
                want_len = shard.get("bytes")
                if want_len is not None and size != want_len:
                    bad.append(f"{path}: {size} bytes, metadata records "
                               f"{want_len}")
                    continue
                want_crc = shard.get("crc32")
                # the first corruption settles the verdict — keep scanning
                # for MISSING files (cheap stats, they drive the rank
                # postmortem) but don't re-read further shard bytes
                if checksums and want_crc is not None and not bad:
                    try:
                        got = _file_crc32(path)
                    except OSError:
                        bad.append(f"{path}: shard unreadable "
                                   f"(leaf {key!r})")
                        continue
                    if got != want_crc:
                        bad.append(f"{path}: crc32 mismatch")
    if gone:
        # dedup by FILE: a replicated leaf is recorded by every rank's
        # metadata under the same filename, and one lost file must not
        # read as "every host died"
        by_file: Dict[str, set] = {}
        keys_set = set()
        for r, k, f in gone:
            by_file.setdefault(f, set()).add(r)
            keys_set.add(k)
        # attribute a rank only when the file belongs to exactly one
        # (a multi-rank file is replicated — no single host to blame)
        ranks = sorted({next(iter(rs)) for rs in by_file.values()
                        if len(rs) == 1 and None not in rs})
        keys = sorted(keys_set)
        return (f"{directory}: {len(by_file)} shard file(s) missing"
                + (f" from rank(s) {ranks}" if ranks else "")
                + f" — lost host? affected leaves: "
                + ", ".join(repr(k) for k in keys[:4])
                + (f" (+{len(keys) - 4} more)" if len(keys) > 4 else "")
                + (f"; also {bad[0]}" if bad else ""))
    if bad:
        return bad[0]
    return None


def mesh_info(directory: str) -> Optional[Dict[str, Any]]:
    """Topology a checkpoint was WRITTEN on: ``{"axes": {name: size},
    "devices": N, "process_count": M}``. ``None`` for unreadable
    directories, host-only state, or checkpoints predating the elastic
    metadata (which restore through the same-topology path unchanged).
    Restores never REQUIRE this — re-slicing plans itself from per-shard
    offsets — it exists so an elastic restore can report the shrink/grow
    (``saved 8 devices -> restoring onto 4``) and so
    :func:`paddle_tpu.distributed.elastic_mesh.reshaped_mesh` can rebuild
    a compatible mesh on surviving capacity."""
    if directory is None:
        # empty checkpoint root (no checkpoint yet) — the fresh-start path
        return None
    try:
        with open(os.path.join(directory, _METADATA)) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    mesh = meta.get("mesh")
    if mesh is None:
        return None
    out = dict(mesh)
    if meta.get("process_count") is not None:
        out["process_count"] = int(meta["process_count"])
    return out


def latest_checkpoint(root: str, verify: bool = True,
                      exclude: Sequence[str] = (),
                      on_invalid: Optional[Callable[[str], None]] = None,
                      ) -> Optional[str]:
    """Newest VALID ``step_*`` checkpoint under ``root`` (or ``None``).

    With ``verify`` (default), candidates failing
    :func:`validate_checkpoint` — torn saves, truncated or bit-flipped
    shards, a lost host's missing rank shards — are skipped, so restore
    falls back to the newest checkpoint that is complete (and completeness
    is topology-agnostic: a complete directory restores onto any target
    mesh). This reads every shard of the chosen candidate once (crc32); a
    subsequent :func:`load_state` reads them again — the double pass is
    deliberate: fallback must reject a bit-rotted-but-right-sized newest
    checkpoint BEFORE restore commits to it. Pass ``verify=False`` to pick
    by metadata presence only. ``exclude`` paths are skipped outright —
    the restore loop's "this one failed to LOAD, give me the next" hook.
    ``on_invalid`` is called with each path that FAILED validation; a
    retry loop that feeds those back into ``exclude`` avoids re-reading
    every shard byte of already-rejected candidates on each iteration.
    """
    if not os.path.isdir(root):
        return None
    excluded = {os.path.abspath(p) for p in exclude}
    steps = sorted(
        ((int(m.group(1)), name) for m, name in
         ((_STEP_DIR.match(n), n) for n in os.listdir(root)) if m),
        reverse=True)
    for step, name in steps:
        path = os.path.join(root, name)
        if os.path.abspath(path) in excluded:
            continue
        if not os.path.exists(os.path.join(path, _METADATA)):
            continue
        if verify:
            problem = validate_checkpoint(path)
            if problem is not None:
                print(f"[checkpoint] skipping {path}: {problem}",
                      flush=True)
                if on_invalid is not None:
                    on_invalid(path)
                continue
        return path
    return None


class AutoCheckpoint:
    """Periodic snapshot + resume-on-restart manager.

    ``maybe_save(step, state)`` saves every ``save_interval_steps`` (or
    seconds); completed saves rotate down to ``keep_max`` directories.
    ``restore()`` returns ``(step, state_dict)`` of the newest complete
    snapshot, or ``(0, None)``.
    """

    def __init__(self, root: str, save_interval_steps: int = 100,
                 save_interval_seconds: Optional[float] = None,
                 keep_max: int = 3, async_save: bool = True,
                 staging_ttl_seconds: float = 3600.0):
        self.root = root
        self.save_interval_steps = save_interval_steps
        self.save_interval_seconds = save_interval_seconds
        self.keep_max = keep_max
        self.async_save = async_save
        self.staging_ttl_seconds = float(staging_ttl_seconds)
        self._last_save_time = time.monotonic()
        self._last_step = -1
        self._pending: Optional[_PendingSave] = None
        os.makedirs(root, exist_ok=True)
        self._sweep_orphans()

    _ORPHAN = re.compile(r"^step_\d+\.tmp(-pt\d+)?$")
    _TRASH = re.compile(r"^(step_\d+)\.old-pt\d+$")
    _TMPFILE = re.compile(r"\.tmp\d+$")

    def _sweep_orphans(self, ttl: float = 0.0) -> None:
        """Clean up after a killed process: ``step_N.tmp*`` staging dirs are
        never valid restore targets (publish renames them away before they
        count) and are deleted; a ``step_N.old-pt<pid>`` overwrite trash
        copy whose ``step_N`` is MISSING is the old checkpoint caught
        between save_state's two renames — restore it rather than lose the
        only copy. Inside step dirs, ``*.tmp<pid>`` FILES are a
        multi-process writer SIGKILLed between staging a shard and its
        ``os.replace`` publish — each crashed incarnation leaves a
        uniquely-named file that no later save overwrites, so they are
        reaped here too.

        ``ttl`` > 0 reaps only staging dirs whose mtime is older than that
        many seconds. The startup sweep runs with ttl=0 (the restarting
        process owns the root); the PERIODIC sweep (from ``_gc``, so a
        long-lived trainer also heals) uses ``staging_ttl_seconds`` — a
        sibling process SIGKILLed mid-save must not leak its staging dir
        until the next restart, while a live peer's in-flight save (fresh
        mtime) is left alone."""
        now = time.time()

        def fresh(path: str, ttl: float = ttl) -> bool:
            # under a TTL, anything recently touched may belong to a LIVE
            # sibling mid-save (including the window between save_state's
            # two overwrite renames) — leave it alone
            if ttl <= 0.0:
                return False
            try:
                return now - os.path.getmtime(path) < ttl
            except OSError:
                return True  # raced with its publish rename: not stale

        # in-step-dir staging FILES sit in a root SHARED with multi-process
        # peers, and peers do not restart atomically: a straggler rank's
        # startup sweep (ttl=0) must not reap an earlier-restarted peer's
        # in-flight shard, so the FILE reap keeps the staging TTL whenever
        # other writer processes may be live.
        file_ttl = ttl
        if jax.process_count() > 1:
            file_ttl = max(ttl, self.staging_ttl_seconds)

        for name in os.listdir(self.root):
            path = os.path.join(self.root, name)
            m = self._TRASH.match(name)
            if m:
                if fresh(path):
                    continue
                target = os.path.join(self.root, m.group(1))
                if not os.path.exists(target):
                    os.replace(path, target)
                else:
                    shutil.rmtree(path, ignore_errors=True)
            elif self._ORPHAN.match(name):
                if fresh(path):
                    continue
                shutil.rmtree(path, ignore_errors=True)
            elif _STEP_DIR.match(name) and os.path.isdir(path):
                try:
                    members = os.listdir(path)
                except OSError:
                    continue  # raced with retention GC
                for fn in members:
                    if not self._TMPFILE.search(fn):
                        continue
                    fpath = os.path.join(path, fn)
                    if fresh(fpath, file_ttl):
                        continue
                    try:
                        os.remove(fpath)
                    except OSError:
                        pass  # raced with its publish rename

    def _due(self, step):
        if self.save_interval_seconds is not None:
            return time.monotonic() - self._last_save_time >= self.save_interval_seconds
        return step % self.save_interval_steps == 0 and step != self._last_step

    def maybe_save(self, step: int, state: Any) -> bool:
        if not self._due(step):
            return False
        self.save(step, state)
        return True

    def save(self, step: int, state: Any,
             extra_files: Optional[Dict[str, bytes]] = None):
        if self._pending is not None:
            self._pending.wait()
        directory = os.path.join(self.root, f"step_{step}")
        # save_state publishes atomically (staging dir + os.replace), so a
        # kill mid-save leaves only a .tmp-pt orphan — never a half dir
        pending = save_state(state, directory, async_=self.async_save,
                             extra_files=extra_files)

        if pending is None:
            self._gc()
        else:
            orig_wait = pending.wait

            def wait_and_gc(timeout=None):
                ok = orig_wait(timeout)
                if ok:
                    self._gc()
                return ok
            pending.wait = wait_and_gc
            self._pending = pending
        self._last_save_time = time.monotonic()
        self._last_step = step

    def wait(self):
        if self._pending is not None:
            self._pending.wait()
            self._pending = None

    def _gc(self):
        """Retain the newest ``keep_max`` VALID checkpoints. Invalid dirs
        (torn multi-host saves, corruption) never count toward the quota —
        else they could push out the only loadable fallback — and invalid
        dirs NEWER than the kept set are left alone (a peer may still be
        committing its metadata). Cheap stat-only validation: _gc runs
        after every save, so it must not re-read every shard byte."""
        steps = sorted(
            (int(m.group(1)) for m in map(_STEP_DIR.match, os.listdir(self.root)) if m),
            reverse=True)
        kept_valid = 0
        for step in steps:
            path = os.path.join(self.root, f"step_{step}")
            if kept_valid < self.keep_max:
                if validate_checkpoint(path, checksums=False) is None:
                    kept_valid += 1
                continue
            shutil.rmtree(path, ignore_errors=True)
        # periodic staging sweep: a SIGKILLed sibling's .tmp-pt dir would
        # otherwise leak until the next process restart
        self._sweep_orphans(ttl=self.staging_ttl_seconds)

    def restore(self, shardings=None, template=None):
        self.wait()
        path = latest_checkpoint(self.root)
        if path is None:
            return 0, None
        step = int(_STEP_DIR.match(os.path.basename(path)).group(1))
        return step, load_state(path, shardings=shardings, template=template)


class AsyncSaver:
    """Fire-and-forget async saver with at-most-one outstanding save."""

    def __init__(self):
        self._pending: Optional[_PendingSave] = None

    def save(self, state, directory):
        if self._pending is not None:
            self._pending.wait()
        self._pending = save_state(state, directory, async_=True)
        return self._pending

    def wait(self):
        if self._pending is not None:
            self._pending.wait()
            self._pending = None
