"""paddle_tpu.distributed — SPMD distributed training over device meshes.

Reference: ``python/paddle/distributed/`` (fleet, collective API, launch,
auto_parallel). Design per SURVEY §7: GSPMD shardings replace hand-inserted
collectives; shard_map + lax collectives replace the ``c_*`` op zoo for the
explicitly-scheduled paths (pipeline, ring attention, MoE).
"""
from . import collective  # noqa: F401
from . import env  # noqa: F401
from . import fleet  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp, all_gather, all_reduce, all_reduce_buckets, alltoall,
    broadcast, ppermute, reduce_scatter, shift_left, shift_right,
)
from . import overlap  # noqa: F401
from .overlap import (  # noqa: F401
    GradBucket, bucket_order, bucketed_reduce, build_buckets,
    weight_update_specs,
)
from .env import (  # noqa: F401
    barrier, get_rank, get_world_size, init_parallel_env, is_initialized,
)
from .mesh import (  # noqa: F401
    HybridCommunicateGroup, axis_size, get_mesh, init_mesh, mesh_scope,
    require_mesh, set_mesh, sharding,
)
from .shard import (  # noqa: F401
    DistributedTrainStep, buffer_specs, opt_state_specs, param_specs,
    put_global, shard_params,
)
from .parallel import (  # noqa: F401
    mp_layers, moe, pipeline, recompute as recompute_mod, sequence_parallel,
)
from .parallel.recompute import recompute  # noqa: F401
from . import checkpoint  # noqa: F401
from .heter import HeterPipelineTrainer  # noqa: F401
from . import passes  # noqa: F401
from . import rpc  # noqa: F401
from . import ps  # noqa: F401
from . import stream  # noqa: F401
from .api_compat import (  # noqa: F401
    CountFilterEntry, Group, P2POp, ParallelEnv, ParallelMode,
    ProbabilityEntry, ShowClickEntry, all_gather_object, alltoall_single,
    batch_isend_irecv, destroy_process_group, get_group,
    group_sharded_parallel, irecv, isend, new_group, recv, reduce,
    save_group_sharded_model, scatter, send, split, wait,
)
from .auto_parallel import shard_op, shard_tensor  # noqa: F401
from ..io.slot_dataset import BoxPSDataset, QueueDataset  # noqa: F401
from .ps.graph import GraphDataGenerator, GraphTable  # noqa: F401
from . import auto_parallel  # noqa: F401
from .checkpoint import (  # noqa: F401
    AsyncSaver, AutoCheckpoint, CheckpointCorruptError, last_load_stats,
    latest_checkpoint, load_state, mesh_info, save_state,
    validate_checkpoint,
)
from . import elastic_mesh  # noqa: F401
from .elastic_mesh import (  # noqa: F401
    plan_mesh_shape, rescale_batch, reshaped_mesh,
)
from . import resilience  # noqa: F401
from .resilience import (  # noqa: F401
    FaultPlan, FaultRule, InjectedFault, RetryPolicy, fault_point,
    with_timeout,
)
