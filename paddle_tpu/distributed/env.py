"""Process bootstrap & environment.

Reference parity: ``python/paddle/distributed/parallel.py:98``
(``init_parallel_env`` — env-var rank discovery, TCPStore rendezvous at
``parallel.py:268``, NCCL comm init). TPU-native: JAX's distributed
coordination service *is* the TCPStore+comm-init bundle — one call wires every
host into a global runtime where ``jax.devices()`` spans the whole slice.
NCCL-ring bootstrap ops (``c_gen_nccl_id``/``c_comm_init``) have no analogue:
the mesh exists as soon as the runtime is up.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None) -> None:
    """Initialize multi-host execution. Single-process (one host, N chips)
    needs no initialization — SPMD covers all local devices. Multi-host reads
    either explicit args or the env contract:

    - ``PADDLE_MASTER`` / ``MASTER_ADDR:MASTER_PORT`` -> coordinator
    - ``PADDLE_TRAINERS_NUM`` / ``WORLD_SIZE``        -> process count
    - ``PADDLE_TRAINER_ID`` / ``RANK``                -> process id
    """
    global _initialized
    if _initialized:
        return
    coord = coordinator_address or os.environ.get("PADDLE_MASTER")
    if coord is None and os.environ.get("MASTER_ADDR"):
        coord = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', '8701')}"
    nproc = num_processes or int(os.environ.get("PADDLE_TRAINERS_NUM",
                                                os.environ.get("WORLD_SIZE", "1")))
    pid = process_id if process_id is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", "0")))
    if coord is not None and nproc > 1:
        # NB: don't call jax.default_backend() here — it would initialise
        # the backends before jax.distributed.initialize gets to run
        if _cpu_platform_requested():
            # the CPU backend compiles cross-process collectives only when
            # a collectives layer is configured; without it every
            # multi-controller program (and even a replicated device_put,
            # which broadcasts to assert value equality) dies with
            # "Multiprocess computations aren't implemented on the CPU
            # backend" — the simulated-mesh test/CI path needs gloo
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except (AttributeError, ValueError):  # jaxlib without gloo
                pass
            else:
                if _backends_initialized():
                    # the config only shapes CpuClient CONSTRUCTION — a
                    # backend built before this call has no collectives
                    # layer, and the update above is silently inert
                    import warnings

                    warnings.warn(
                        "init_parallel_env: the CPU backend was already "
                        "initialized, so the gloo collectives config "
                        "cannot take effect — cross-process programs will "
                        "fail with 'Multiprocess computations aren't "
                        "implemented on the CPU backend'. Call "
                        "init_parallel_env before anything touches a jax "
                        "array.", RuntimeWarning)
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
    _initialized = True


def _backends_initialized() -> bool:
    try:
        from jax._src import xla_bridge

        return xla_bridge.backends_are_initialized()
    except Exception:
        return False


def _cpu_platform_requested() -> bool:
    """True when the process is pinned to the CPU backend (env or config)
    but no backend is live yet — ``jax.default_backend()`` would initialise
    one, so prefer the declared intent."""
    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            return jax.default_backend() == "cpu"
    except Exception:
        pass
    plats = (os.environ.get("JAX_PLATFORMS", "")
             or getattr(jax.config, "jax_platforms", None) or "")
    return "cpu" in str(plats).split(",")


def get_rank() -> int:
    """Process (host) index — the unit of data loading and checkpoint IO."""
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def device_count() -> int:
    return len(jax.devices())


def local_device_count() -> int:
    return len(jax.local_devices())


def is_initialized() -> bool:
    return _initialized or jax.process_count() > 1


def barrier(group=None):
    """Host barrier (reference: GlooWrapper barrier, ``gloo_wrapper.h:139``)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("paddle_tpu_barrier")
