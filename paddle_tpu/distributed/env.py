"""Process bootstrap & environment.

Reference parity: ``python/paddle/distributed/parallel.py:98``
(``init_parallel_env`` — env-var rank discovery, TCPStore rendezvous at
``parallel.py:268``, NCCL comm init). TPU-native: JAX's distributed
coordination service *is* the TCPStore+comm-init bundle — one call wires every
host into a global runtime where ``jax.devices()`` spans the whole slice.
NCCL-ring bootstrap ops (``c_gen_nccl_id``/``c_comm_init``) have no analogue:
the mesh exists as soon as the runtime is up.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None) -> None:
    """Initialize multi-host execution. Single-process (one host, N chips)
    needs no initialization — SPMD covers all local devices. Multi-host reads
    either explicit args or the env contract:

    - ``PADDLE_MASTER`` / ``MASTER_ADDR:MASTER_PORT`` -> coordinator
    - ``PADDLE_TRAINERS_NUM`` / ``WORLD_SIZE``        -> process count
    - ``PADDLE_TRAINER_ID`` / ``RANK``                -> process id
    """
    global _initialized
    if _initialized:
        return
    coord = coordinator_address or os.environ.get("PADDLE_MASTER")
    if coord is None and os.environ.get("MASTER_ADDR"):
        coord = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', '8701')}"
    nproc = num_processes or int(os.environ.get("PADDLE_TRAINERS_NUM",
                                                os.environ.get("WORLD_SIZE", "1")))
    pid = process_id if process_id is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", "0")))
    if coord is not None and nproc > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
    _initialized = True


def get_rank() -> int:
    """Process (host) index — the unit of data loading and checkpoint IO."""
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def device_count() -> int:
    return len(jax.devices())


def local_device_count() -> int:
    return len(jax.local_devices())


def is_initialized() -> bool:
    return _initialized or jax.process_count() > 1


def barrier(group=None):
    """Host barrier (reference: GlooWrapper barrier, ``gloo_wrapper.h:139``)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("paddle_tpu_barrier")
