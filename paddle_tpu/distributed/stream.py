"""paddle.distributed.stream — stream-variant collectives.

Reference parity: ``python/paddle/distributed/communication/stream/``
(collectives launched on a caller-chosen CUDA stream with
``sync_op``/``use_calc_stream`` control). TPU-native collapse: XLA owns
stream scheduling and overlaps collectives with compute during
fusion/latency-hiding — the knobs are accepted and ignored, the math
delegates to :mod:`.collective`.
"""
from __future__ import annotations

from . import api_compat as _a
from . import collective as _c


def _wrap(fn):
    def call(*args, sync_op=True, use_calc_stream=False, **kw):
        return fn(*args, **kw)

    call.__name__ = fn.__name__
    call.__doc__ = f"stream variant of collective.{fn.__name__} " \
                   "(sync_op/use_calc_stream collapse under XLA)"
    return call


all_reduce = _wrap(_c.all_reduce)
all_gather = _wrap(_c.all_gather)
alltoall = _wrap(_c.alltoall)
alltoall_single = _wrap(_a.alltoall_single)
broadcast = _wrap(_c.broadcast)
reduce_scatter = _wrap(_c.reduce_scatter)
scatter = _wrap(_a.scatter)
send = _wrap(_a.send)
recv = _wrap(_a.recv)

__all__ = ["all_reduce", "all_gather", "alltoall", "alltoall_single",
           "broadcast", "reduce_scatter", "scatter", "send", "recv"]
