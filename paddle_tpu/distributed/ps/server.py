"""PS server subprocess entrypoint.

``python -m paddle_tpu.distributed.ps.server --port 0 --embed-dim 8 ...``
prints ``PORT <p>`` once bound, then serves until a client sends STOP
(the reference's ``fleet.init_server(); fleet.run_server()`` loop,
``the_one_ps.py``)."""
from __future__ import annotations

import argparse


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--embed-dim", type=int, required=True)
    ap.add_argument("--optimizer", default="adagrad")
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--num-shards", type=int, default=16)
    ap.add_argument("--load", default=None, help="snapshot to preload")
    args = ap.parse_args(argv)

    from ...utils.procutil import start_ppid_watchdog
    from .service import PsServer
    from .table import SparseAccessorConfig

    start_ppid_watchdog()
    srv = PsServer(SparseAccessorConfig(
        embed_dim=args.embed_dim, optimizer=args.optimizer,
        learning_rate=args.lr, seed=args.seed, num_shards=args.num_shards),
        port=args.port)
    if args.load:
        srv.table.load(args.load)
        srv.load_dense(args.load)  # dense sidecar (absent is fine)
    print(f"PORT {srv.port}", flush=True)
    srv.wait()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
