"""Sparse embedding lookup fused into jitted steps via host callbacks.

Reference parity: ``distributed_lookup_table``/``c_embedding`` +
``PSGPUWrapper::PullSparse``/``PushSparseGrad``
(``paddle/fluid/framework/fleet/ps_gpu_wrapper.h:157,170``) and the Python
``paddle.static.nn.sparse_embedding``. TPU-native: the pull is a
``jax.pure_callback`` into the host C++ table (dense [batch, dim] rows cross
PCIe, never the full table), and the push rides the backward pass as an
``io_callback`` inside a ``custom_vjp`` — the optimizer update happens
server-side in C++, so the embedding never appears in the jitted step's
parameter pytree. This is the reference's "hide the host↔device hop behind
the step" trick (``pre_build_thread`` pipelining) restated for XLA.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...nn.layer import Layer
from .table import MemorySparseTable, SparseAccessorConfig

_callbacks_supported = None


def _tracing_active() -> bool:
    """True when called under ANY jax trace (jit or grad), even if every
    visible operand is a concrete closed-over array. Needed because a layer
    whose inputs are all closure constants still traces wrong: its host pull
    would bake stale rows into the compiled program and its push-vjp would
    be pruned."""
    try:
        from jax._src.core import trace_state_clean

        return not trace_state_clean()
    except Exception:  # API moved — fall back to operand-based detection
        return False


def callbacks_supported() -> bool:
    """Whether the active backend supports host callbacks inside jit.

    Standard CPU/TPU runtimes do; some tunneled PJRT plugins (axon) don't —
    there the staged :class:`StagedPull` path is the way to train.
    """
    global _callbacks_supported
    if _callbacks_supported is None:
        try:
            # ensure_compile_time_eval: the first call may come from inside
            # an active trace (eval-mode forward under an outer jit), where
            # a plain jit dispatch would stage into that trace, float()
            # would raise, and False would be cached forever on a
            # callback-capable backend
            with jax.ensure_compile_time_eval():
                # tpu-lint: disable=R2(one-time backend capability probe, memoized in a module global; ensure_compile_time_eval keeps it out of any enclosing trace)
                out = jax.jit(lambda x: jax.pure_callback(
                    lambda y: y, jax.ShapeDtypeStruct((), jnp.float32), x))(
                        jnp.float32(3.0))
                _callbacks_supported = float(out) == 3.0
        except Exception:
            _callbacks_supported = False
    return _callbacks_supported


def make_lookup(table: MemorySparseTable):
    """Build a differentiable ``lookup(ids, anchor) -> f32[..., dim]`` bound
    to ``table``. Works eagerly and under ``jit``; backward pushes grads into
    the table (which applies its optimizer rule).

    ``anchor`` is a throwaway *differentiable* scalar: reverse-mode AD only
    visits a node on a path from a differentiated input, and ``ids`` is
    integer, so without the anchor the vjp (and therefore the grad push)
    would be dead-code-eliminated. Thread any trainable scalar through it
    (:class:`SparseEmbedding` registers one).
    """
    dim = table.embed_dim

    def _pull_host(ids):
        return table.pull(np.asarray(ids))

    def _push_host(ids, grads):
        table.push(np.asarray(ids), np.asarray(grads))
        return np.int32(0)

    @jax.custom_vjp
    def lookup(ids, anchor):
        del anchor  # connectivity only; numerically unused
        flat = ids.reshape(-1)
        # io_callback, not pure_callback: pull is effectful (initializes
        # missing keys, bumps the show counter that drives shrink eviction),
        # so it must run exactly once per step — pure callbacks may be
        # cached, elided, or re-executed under retracing/vmap.
        out = jax.experimental.io_callback(
            _pull_host,
            jax.ShapeDtypeStruct((flat.shape[0], dim), jnp.float32),
            flat, ordered=False)
        return out.reshape(ids.shape + (dim,))

    def fwd(ids, anchor):
        return lookup(ids, anchor), ids

    def bwd(ids, g):
        flat_ids = ids.reshape(-1)
        flat_g = g.reshape(-1, dim).astype(jnp.float32)
        jax.experimental.io_callback(
            _push_host, jax.ShapeDtypeStruct((), jnp.int32),
            flat_ids, flat_g, ordered=False)
        return (np.zeros(ids.shape, dtype=jax.dtypes.float0),
                jnp.zeros(()))

    lookup.defvjp(fwd, bwd)
    return lookup


class SparseEmbedding(Layer):
    """Embedding layer backed by a PS table instead of a dense parameter.

    Unlike :class:`paddle_tpu.nn.Embedding` (dense [vocab, dim] parameter on
    device), ids here are arbitrary int64 feature hashes — no vocab bound —
    and rows live host-side, the CTR/recsys regime the reference's HeterPS
    serves. The update is applied by the table on ``push`` during backward,
    so this layer contributes no entries to ``param_state``.
    """

    def __init__(self, embed_dim: int, table: MemorySparseTable = None,
                 **accessor_kw):
        super().__init__()
        if table is None:
            table = MemorySparseTable(
                SparseAccessorConfig(embed_dim=embed_dim, **accessor_kw))
        assert table.embed_dim == embed_dim
        self.table = table
        self.embed_dim = embed_dim
        self._lookup = make_lookup(table)
        # Differentiable anchor so the push-vjp survives AD pruning (see
        # make_lookup). Always receives zero gradient; numerically unused.
        from ...nn.initializer import Constant

        self.grad_anchor = self.create_parameter(
            (), default_initializer=Constant(0.0))

    def forward(self, ids):
        ids = jnp.asarray(ids)
        anchor_traced = isinstance(self.grad_anchor, jax.core.Tracer)
        in_trace = (anchor_traced or isinstance(ids, jax.core.Tracer)
                    or _tracing_active())
        if not in_trace:
            # Eager path: plain host pull, no callback machinery (works on
            # backends without host-callback support).
            # tpu-lint: disable=R1(eager branch — the in_trace check above proved ids is not a Tracer and no trace is active)
            rows = self.table.pull(np.asarray(ids).reshape(-1))
            return jnp.asarray(rows).reshape(ids.shape + (self.embed_dim,))
        if self.training and not anchor_traced:
            # Inside a jit/grad trace but grad_anchor is a plain array: the
            # push-vjp is unreachable from the differentiated inputs and AD
            # would silently prune it — the step would run, loss would move,
            # and the embedding would never train. Fail loudly instead.
            raise RuntimeError(
                "SparseEmbedding used inside a traced step, but its "
                "grad_anchor parameter is not among the traced/differentiated "
                "values, so embedding gradients would be silently dropped. "
                "Run the layer via functional_call/TrainStep with "
                "param_state(model) (which includes grad_anchor), or call "
                ".eval() on the layer for inference.")
        if (not anchor_traced and not isinstance(ids, jax.core.Tracer)
                and not callbacks_supported()):
            # eval composition (everything concrete, just an enclosing
            # trace) on a backend without host callbacks (axon tunnel):
            # bake the rows into the compiled program at trace time —
            # frozen-table serving. The io_callback path would fail there.
            rows = self.table.pull(np.asarray(ids).reshape(-1))
            return jnp.asarray(rows).reshape(ids.shape + (self.embed_dim,))
        return self._lookup(ids, self.grad_anchor)

    def extra_repr(self):
        acc = getattr(self.table, "accessor", None)  # PsClient has none
        opt = f", optimizer={acc.optimizer}" if acc is not None else ""
        return f"embed_dim={self.embed_dim}{opt}"


class StagedPull:
    """Pull-before / push-after staging for training without in-graph
    callbacks — the reference's actual structure (``PSGPUWorker`` pulls via
    ``PullSparse`` before the program runs and pushes via ``PushSparseGrad``
    after it, ``ps_gpu_wrapper.h:157,170``), restated for XLA: the jitted
    step takes dense ``rows`` as a regular differentiable input; duplicate
    ids are deduplicated so row grads come back merged (the communicator's
    batched-merge, ``communicator.h:426``).

    Usage::

        staged = StagedPull(table)
        rows, inv, uniq = staged.pull(ids)          # host side
        loss, row_grads = step(params, rows, inv)   # jit: emb = rows[inv]
        staged.push(uniq, row_grads)                # host side, C++ update
    """

    def __init__(self, table: MemorySparseTable):
        self.table = table

    def pull(self, ids):
        ids = np.asarray(ids)
        uniq, inv = np.unique(ids.reshape(-1), return_inverse=True)
        rows = self.table.pull(uniq)
        return (jnp.asarray(rows), jnp.asarray(inv.reshape(ids.shape)),
                uniq)

    @staticmethod
    def lookup(rows, inv):
        """In-graph gather: embedding activations for the original ids."""
        return rows[inv]

    def push(self, uniq, row_grads) -> None:
        self.table.push(uniq, np.asarray(row_grads))
