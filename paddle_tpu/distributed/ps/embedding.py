"""Sparse embedding lookup fused into jitted steps via host callbacks.

Reference parity: ``distributed_lookup_table``/``c_embedding`` +
``PSGPUWrapper::PullSparse``/``PushSparseGrad``
(``paddle/fluid/framework/fleet/ps_gpu_wrapper.h:157,170``) and the Python
``paddle.static.nn.sparse_embedding``. TPU-native: the pull is a
``jax.pure_callback`` into the host C++ table (dense [batch, dim] rows cross
PCIe, never the full table), and the push rides the backward pass as an
``io_callback`` inside a ``custom_vjp`` — the optimizer update happens
server-side in C++, so the embedding never appears in the jitted step's
parameter pytree. This is the reference's "hide the host↔device hop behind
the step" trick (``pre_build_thread`` pipelining) restated for XLA.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...nn.layer import Layer
from .table import MemorySparseTable, SparseAccessorConfig


def make_lookup(table: MemorySparseTable):
    """Build a differentiable ``lookup(ids, anchor) -> f32[..., dim]`` bound
    to ``table``. Works eagerly and under ``jit``; backward pushes grads into
    the table (which applies its optimizer rule).

    ``anchor`` is a throwaway *differentiable* scalar: reverse-mode AD only
    visits a node on a path from a differentiated input, and ``ids`` is
    integer, so without the anchor the vjp (and therefore the grad push)
    would be dead-code-eliminated. Thread any trainable scalar through it
    (:class:`SparseEmbedding` registers one).
    """
    dim = table.embed_dim

    def _pull_host(ids):
        return table.pull(np.asarray(ids))

    def _push_host(ids, grads):
        table.push(np.asarray(ids), np.asarray(grads))
        return np.int32(0)

    @jax.custom_vjp
    def lookup(ids, anchor):
        del anchor  # connectivity only; numerically unused
        flat = ids.reshape(-1)
        out = jax.pure_callback(
            _pull_host,
            jax.ShapeDtypeStruct((flat.shape[0], dim), jnp.float32),
            flat)
        return out.reshape(ids.shape + (dim,))

    def fwd(ids, anchor):
        return lookup(ids, anchor), ids

    def bwd(ids, g):
        flat_ids = ids.reshape(-1)
        flat_g = g.reshape(-1, dim).astype(jnp.float32)
        jax.experimental.io_callback(
            _push_host, jax.ShapeDtypeStruct((), jnp.int32),
            flat_ids, flat_g, ordered=False)
        return (np.zeros(ids.shape, dtype=jax.dtypes.float0),
                jnp.zeros(()))

    lookup.defvjp(fwd, bwd)
    return lookup


class SparseEmbedding(Layer):
    """Embedding layer backed by a PS table instead of a dense parameter.

    Unlike :class:`paddle_tpu.nn.Embedding` (dense [vocab, dim] parameter on
    device), ids here are arbitrary int64 feature hashes — no vocab bound —
    and rows live host-side, the CTR/recsys regime the reference's HeterPS
    serves. The update is applied by the table on ``push`` during backward,
    so this layer contributes no entries to ``param_state``.
    """

    def __init__(self, embed_dim: int, table: MemorySparseTable = None,
                 **accessor_kw):
        super().__init__()
        if table is None:
            table = MemorySparseTable(
                SparseAccessorConfig(embed_dim=embed_dim, **accessor_kw))
        assert table.embed_dim == embed_dim
        self.table = table
        self.embed_dim = embed_dim
        self._lookup = make_lookup(table)
        # Differentiable anchor so the push-vjp survives AD pruning (see
        # make_lookup). Always receives zero gradient; numerically unused.
        from ...nn.initializer import Constant

        self.grad_anchor = self.create_parameter(
            (), default_initializer=Constant(0.0))

    def forward(self, ids):
        return self._lookup(jnp.asarray(ids), self.grad_anchor)

    def extra_repr(self):
        return (f"embed_dim={self.embed_dim}, "
                f"optimizer={self.table.accessor.optimizer}")
