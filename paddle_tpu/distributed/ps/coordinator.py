"""Federated-learning coordinator over the launch KV store.

Reference parity: ``python/paddle/distributed/ps/coordinator.py`` —
``Coordinator`` + ``ClientSelector`` (round-based client selection from
reported ``ClientInfoAttr`` states) and ``FLClient`` (push state, pull the
coordinator's per-client ``FLStrategy``), all brpc-transported in the
reference.

TPU-native shape: transport is the launch CLI's HTTP :class:`KVClient`
(the same rendezvous store elastic/launch already run), so an FL round is
plain KV traffic: clients PUT ``fl/state/<id>`` each round, the
coordinator reads all states, runs its selector, PUTs
``fl/strategy/<round>/<id>``, and clients WAIT on their key. No new
service process is needed — any KVServer (or the launch master) hosts it.
"""
from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional

from ..launch.kv_server import KVClient, KVServer

__all__ = ["ClientInfoAttr", "FLStrategy", "ClientSelector", "FLClient",
           "Coordinator"]


class ClientInfoAttr:
    """What a client reports each round (reference ``ClientInfoAttr``)."""

    def __init__(self, device_type: str = "cpu", compute_capacity: float = 1.0,
                 bandwidth: float = 1.0, loss: Optional[float] = None,
                 num_samples: int = 0):
        self.device_type = device_type
        self.compute_capacity = float(compute_capacity)
        self.bandwidth = float(bandwidth)
        self.loss = loss
        self.num_samples = int(num_samples)

    def to_json(self) -> str:
        return json.dumps(self.__dict__)

    @classmethod
    def from_json(cls, s: str) -> "ClientInfoAttr":
        obj = cls()
        obj.__dict__.update(json.loads(s))
        return obj


class FLStrategy:
    """Coordinator's per-client decision (reference ``FLStrategy``):
    JOIN (train this round), WAIT (sit out), FINISH (stop)."""

    JOIN = "JOIN"
    WAIT = "WAIT"
    FINISH = "FINISH"

    def __init__(self, action: str = "JOIN", params: Optional[Dict] = None):
        self.action = action
        self.params = params or {}

    def to_json(self) -> str:
        return json.dumps({"action": self.action, "params": self.params})

    @classmethod
    def from_json(cls, s: str) -> "FLStrategy":
        d = json.loads(s)
        return cls(d["action"], d.get("params"))


class ClientSelector:
    """Default round selector (reference ``ClientSelector``): every
    reporting client JOINs until ``max_rounds``, then FINISH. Subclass /
    pass ``select_fn`` for capacity- or loss-aware selection."""

    def __init__(self, max_rounds: int = 10,
                 select_fn: Optional[Callable[[int, Dict[str, ClientInfoAttr]],
                                              Dict[str, FLStrategy]]] = None):
        self.max_rounds = int(max_rounds)
        self.select_fn = select_fn

    def select(self, round_idx: int,
               states: Dict[str, ClientInfoAttr]) -> Dict[str, FLStrategy]:
        if self.select_fn is not None:
            return self.select_fn(round_idx, states)
        action = (FLStrategy.FINISH if round_idx >= self.max_rounds - 1
                  else FLStrategy.JOIN)
        return {cid: FLStrategy(action) for cid in states}


class Coordinator:
    """Round loop: gather client states -> select -> publish strategies.

    ``run_round`` blocks until ``num_clients`` states for this round are
    present, then publishes one FLStrategy per client.
    """

    def __init__(self, endpoint: Optional[str] = None,
                 selector: Optional[ClientSelector] = None,
                 strategy_ttl: float = 600.0):
        self._server = None
        self._last_strategies: Dict[str, FLStrategy] = {}
        self.strategy_ttl = float(strategy_ttl)
        if endpoint is None:
            self._server = KVServer()
            self._server.start()
            endpoint = f"127.0.0.1:{self._server.port}"
        self.endpoint = endpoint
        self.kv = KVClient(endpoint)
        self.selector = selector or ClientSelector()

    def run_round(self, round_idx: int, num_clients: int,
                  timeout: float = 300.0) -> Dict[str, ClientInfoAttr]:
        deadline = time.time() + timeout
        prefix = f"fl/state/{round_idx}/"
        while True:
            found = self.kv.list(prefix)
            if len(found) >= num_clients:
                break
            if time.time() > deadline:
                raise TimeoutError(
                    f"fl round {round_idx}: {len(found)}/{num_clients} "
                    f"client states")
            time.sleep(0.05)
        states = {k[len(prefix):]: ClientInfoAttr.from_json(v)
                  for k, v in found.items()}
        strategies = self.selector.select(round_idx, states)
        for cid, strat in strategies.items():
            # TTL so strategy keys can never satisfy a FUTURE session's
            # wait on a long-lived shared KV endpoint
            self.kv.put(f"fl/strategy/{round_idx}/{cid}", strat.to_json(),
                        ttl=self.strategy_ttl)
        # state keys are consumed: delete so a rerun can't read stale info
        for k in found:
            self.kv.delete(k)
        self._last_strategies = strategies
        return states

    def run(self, num_clients: int, max_rounds: Optional[int] = None,
            timeout: float = 300.0) -> int:
        """Drive rounds until the selector FINISHes everyone; returns the
        number of rounds run."""
        rounds = max_rounds or self.selector.max_rounds
        # NOTE: no auto-reset — clients may legitimately have pushed round-0
        # states already. Staleness is prevented structurally: state keys
        # are deleted when consumed and strategy keys carry a TTL. Call
        # reset() explicitly when recovering a crashed session on a shared
        # endpoint.
        for r in range(rounds):
            self.run_round(r, num_clients, timeout=timeout)
            # act on the SAME decisions run_round published: re-invoking a
            # stateful/stochastic selector could diverge from what clients
            # were told
            if all(s.action == FLStrategy.FINISH
                   for s in self._last_strategies.values()):
                return r + 1
        return rounds

    def reset(self) -> None:
        """Purge every fl/ key (stale states/strategies from a previous
        session sharing this KV endpoint)."""
        for k in self.kv.list("fl/"):
            self.kv.delete(k)

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop()
            self._server = None


class FLClient:
    """Client half (reference ``FLClient``): push state, wait for this
    round's strategy. State keys carry a TTL so one that the coordinator
    never consumes (late pusher, crashed session) cannot satisfy a future
    session's round on a shared endpoint."""

    def __init__(self, client_id: str, endpoint: str,
                 state_ttl: float = 600.0):
        self.client_id = str(client_id)
        self.kv = KVClient(endpoint)
        self.state_ttl = float(state_ttl)

    def push_client_info(self, round_idx: int, info: ClientInfoAttr) -> None:
        self.kv.put(f"fl/state/{round_idx}/{self.client_id}", info.to_json(),
                    ttl=self.state_ttl)

    def pull_fl_strategy(self, round_idx: int,
                         timeout: float = 300.0) -> FLStrategy:
        key = f"fl/strategy/{round_idx}/{self.client_id}"
        val = self.kv.wait(key, timeout=timeout)
        return FLStrategy.from_json(val)
