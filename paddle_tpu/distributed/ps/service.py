"""Multi-host parameter-server service: servers, client, communicator.

Reference parity: ``paddle/fluid/distributed/ps/service/brpc_ps_server.cc``
(request dispatch into tables), ``brpc_ps_client.cc`` (client stubs with
key->shard routing and request batching), and the communicator modes of
``ps/service/communicator/communicator.h`` (``AsyncCommunicator:426``
background send queue, ``HalfAsyncCommunicator:519`` barriered batches,
``GeoCommunicator:596`` delta pushes every k steps).

TPU-native shape: each server process owns one C++ :class:`MemorySparseTable`
(a shard of the global key space) behind the plain-TCP framed protocol of
``native/src/ps_service.cc``; the client partitions keys by splitmix64 hash —
the same router the C++ shards use internally — batches per-server requests,
and exposes the exact ``MemorySparseTable`` interface, so
:class:`~paddle_tpu.distributed.ps.SparseEmbedding` works over the network
unchanged (its JAX callbacks call ``client.pull``/``push``).
"""
from __future__ import annotations

import queue
import select
import socket
import struct
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ... import native
from ..resilience import RetryPolicy, fault_point
from .table import MemorySparseTable, SparseAccessorConfig

__all__ = ["PsServer", "PsClient", "Communicator", "launch_servers"]

_OP_PULL = 1
_OP_PUSH = 2
_OP_SIZE = 3
_OP_SAVE = 4
_OP_LOAD = 5
_OP_SHRINK = 6
_OP_SET_LR = 7
_OP_BARRIER = 8
_OP_KEYS = 9
_OP_STOP = 10
_OP_PUSH_RAW = 11
_OP_PUSH_SHOW_CLICK = 12
_OP_DENSE_INIT = 13
_OP_DENSE_PULL = 14
_OP_DENSE_PUSH = 15
_OP_DENSE_SET = 16


class PsRpcError(RuntimeError):
    """Server replied with an error status (application error — NOT a
    transport failure, so the client does not retry it)."""


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over uint64 — MUST match ``ptn::splitmix64``
    (native/src/common.h) bit for bit; it is the canonical key router."""
    x = x.astype(np.uint64, copy=True)
    x += np.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def shard_of(keys: np.ndarray, num_servers: int) -> np.ndarray:
    """Server index per key (client-side partitioning, brpc_ps_client.cc's
    key->shard routing).

    Routes on the UPPER 32 bits of the hash while the C++ table's internal
    16-way sharding uses the full hash mod 16 (ps_table.cc shard_of): with a
    shared low-bit router and power-of-two server counts, each server would
    only ever see keys with hash ≡ s (mod num_servers), funnelling them into
    a fraction of its internal shards and serializing behind shard mutexes.
    """
    return ((_splitmix64(np.asarray(keys, np.int64).view(np.uint64))
             >> np.uint64(32)) % np.uint64(num_servers)).astype(np.int64)


class PsServer:
    """One PS shard: a C++ table + the native TCP service.

    In-process flavor (tests, single-host multi-shard); for real deployments
    run one per host via ``python -m paddle_tpu.distributed.ps.server``.
    """

    def __init__(self, accessor: Optional[SparseAccessorConfig] = None,
                 port: int = 0, **accessor_kw):
        self.table = MemorySparseTable(accessor, **accessor_kw)
        self._lib = native.get_lib()
        self._h = self._lib.pt_ps_server_start(self.table._h, int(port))
        if not self._h:
            raise OSError(f"failed to bind PS server on port {port}")

    @property
    def port(self) -> int:
        return int(self._lib.pt_ps_server_port(self._h))

    def wait(self) -> None:
        self._lib.pt_ps_server_wait(self._h)

    def load_dense(self, path: str) -> None:
        """Restore the dense sidecar saved next to ``path`` (server
        restart flow); a missing sidecar is fine."""
        rc = self._lib.pt_ps_server_load_dense(self._h, path.encode())
        if rc != 0:
            raise IOError(f"dense sidecar restore failed ({rc}): {path}")

    def stop(self) -> None:
        h, self._h = self._h, None
        if h:
            self._lib.pt_ps_server_stop(h)
            self._lib.pt_ps_server_destroy(h)

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class _Conn:
    """One framed-protocol connection (thread-unsafe; callers lock)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def request(self, op: int, body: bytes = b"") -> bytes:
        self.send(op, body)
        return self.recv(op)

    def send(self, op: int, body: bytes = b"") -> None:
        """Write one framed request without waiting for the reply — the
        scatter half of scatter-gather; the framed protocol serves
        pipelined requests strictly in order, so N sends followed by N
        recvs on one connection are well-defined."""
        self.sock.sendall(struct.pack("<IB", len(body), op) + body)

    def recv(self, op: int = -1) -> bytes:
        hdr = self._read(8)
        status, blen = struct.unpack("<iI", hdr)
        payload = self._read(blen) if blen else b""
        if status != 0:
            raise PsRpcError(f"PS rpc op={op} failed with status {status}")
        return payload

    def _read(self, n: int) -> bytes:
        chunks = []
        while n > 0:
            c = self.sock.recv(n)
            if not c:
                raise ConnectionError("PS server closed connection")
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class PsClient:
    """Sharded-table client: same interface as :class:`MemorySparseTable`,
    keys routed to ``endpoints[shard_of(key)]``. Thread-safe (one lock per
    server connection, so concurrent requests to different shards overlap —
    the brpc client's per-channel concurrency).

    Transport failures reconnect and retry with exponential backoff (the
    reference's ``brpc_ps_client.cc`` retry loop): a server that dies and
    comes back on the same endpoint resumes serving this client without a
    restart. Semantics are at-least-once — a PUSH whose reply was lost may
    be applied twice after retry, the same tolerance the reference's async
    SGD accepts. Application errors (:class:`PsRpcError`) never retry.
    """

    def __init__(self, endpoints: Sequence[Tuple[str, int]], embed_dim: int,
                 retries: int = 4, retry_delay: float = 0.25):
        if not endpoints:
            raise ValueError("need at least one PS endpoint")
        self.endpoints = list(endpoints)
        self.embed_dim = int(embed_dim)
        self.retries = int(retries)
        self.retry_delay = float(retry_delay)
        # the brpc-client reconnect loop, expressed as the shared policy
        # (resilience.RetryPolicy): retries+1 attempts, doubling delay
        # capped at 2s — identical schedule to the previous inline loop
        self._retry_policy = RetryPolicy(
            max_attempts=self.retries + 1, base_delay=self.retry_delay,
            max_delay=2.0, multiplier=2.0,
            retryable=(ConnectionError, socket.timeout, OSError))
        self._conns: List[Optional[_Conn]] = [
            _Conn(h, p) for h, p in self.endpoints]
        self._locks = [threading.Lock() for _ in self._conns]
        # persistent fan-out pool: pull+push run every training step, so
        # per-call thread spawn/teardown would be pure hot-path overhead
        self._pool = (ThreadPoolExecutor(max_workers=len(self._conns))
                      if len(self._conns) > 1 else None)
        self._dense_len = 0
        self._dense_bounds: Optional[np.ndarray] = None

    def _request(self, s: int, op: int, body: bytes = b"",
                 retry: bool = True) -> bytes:
        """One RPC to server ``s`` with reconnect + backoff on transport
        errors (through the shared :class:`RetryPolicy`). PsRpcError
        (status<0 reply) is an application error — it is not in the
        policy's retryable set and passes through unretried.
        ``retry=False`` for non-idempotent control ops (shrink): a lost
        reply must surface instead of silently re-applying the op."""
        def attempt() -> bytes:
            # the fault point sits BEFORE any bytes hit the wire, so an
            # injected drop/delay/crash models a connect-time fault and a
            # retry is always protocol-safe
            fault_point(f"ps.request.{s}")
            try:
                with self._locks[s]:
                    if self._conns[s] is None:
                        self._conns[s] = _Conn(*self.endpoints[s])
                    return self._conns[s].request(op, body)
            except PsRpcError:
                raise
            except (ConnectionError, socket.timeout, OSError):
                with self._locks[s]:
                    if self._conns[s] is not None:
                        self._conns[s].close()
                        self._conns[s] = None
                raise
        if not retry:
            return attempt()
        return self._retry_policy.call(attempt, what=f"ps request srv{s}")

    # -- partitioned data plane -------------------------------------------
    def _scatter(self, keys: np.ndarray):
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1), np.int64)
        sid = shard_of(keys, len(self._conns))
        order = np.argsort(sid, kind="stable")
        sorted_keys = keys[order]
        counts = np.bincount(sid, minlength=len(self._conns))
        return keys, sid, order, sorted_keys, counts

    def pull(self, keys) -> np.ndarray:
        keys, sid, order, sorted_keys, counts = self._scatter(keys)
        out = np.empty((keys.size, self.embed_dim), np.float32)
        offs = np.concatenate([[0], np.cumsum(counts)])

        def one(s):
            part = sorted_keys[offs[s]:offs[s + 1]]
            if part.size == 0:
                return
            body = struct.pack("<I", part.size) + part.tobytes()
            payload = self._request(s, _OP_PULL, body)
            rows = np.frombuffer(payload, np.float32).reshape(
                part.size, self.embed_dim)
            out[order[offs[s]:offs[s + 1]]] = rows

        self._fanout(one)
        return out

    def push(self, keys, grads) -> None:
        self._push_rows(keys, grads, _OP_PUSH)

    def push_raw(self, keys, deltas) -> None:
        """Additively merge parameter deltas, bypassing the optimizer rule
        (the geo communicator's delta merge)."""
        self._push_rows(keys, deltas, _OP_PUSH_RAW)

    def _push_rows(self, keys, rows, op: int) -> None:
        keys, sid, order, sorted_keys, counts = self._scatter(keys)
        rows = np.ascontiguousarray(
            np.asarray(rows, np.float32).reshape(keys.size, self.embed_dim))
        sorted_rows = rows[order]
        offs = np.concatenate([[0], np.cumsum(counts)])

        def one(s):
            part = sorted_keys[offs[s]:offs[s + 1]]
            if part.size == 0:
                return
            g = sorted_rows[offs[s]:offs[s + 1]]
            body = struct.pack("<I", part.size) + part.tobytes() + g.tobytes()
            self._request(s, op, body)

        self._fanout(one)

    def push_show_click(self, keys, shows, clicks) -> None:
        """Accumulate CTR usage statistics on each key's owner server."""
        keys, sid, order, sorted_keys, counts = self._scatter(keys)
        sc = np.empty((keys.size, 2), np.float32)
        sc[:, 0] = np.asarray(shows, np.float32).reshape(-1)
        sc[:, 1] = np.asarray(clicks, np.float32).reshape(-1)
        sorted_sc = sc[order]
        offs = np.concatenate([[0], np.cumsum(counts)])

        def one(s):
            part = sorted_keys[offs[s]:offs[s + 1]]
            if part.size == 0:
                return
            g = np.ascontiguousarray(sorted_sc[offs[s]:offs[s + 1]])
            body = struct.pack("<I", part.size) + part.tobytes() + g.tobytes()
            self._request(s, _OP_PUSH_SHOW_CLICK, body)

        self._fanout(one)

    def _fanout(self, fn) -> None:
        n = len(self._conns)
        if n == 1:
            fn(0)
            return
        futures = [self._pool.submit(fn, s) for s in range(n)]
        for f in futures:
            f.result()  # re-raises the first shard failure

    # -- dense parameter plane (MemoryDenseTable over the wire) -----------
    def dense_init(self, length: int, optimizer: str = "sgd",
                   learning_rate: float = 0.05) -> None:
        """Create (idempotently) the dense parameter vector, split in
        contiguous blocks across servers — the reference's dense-table
        sharding. Must run before the other ``dense_*`` calls."""
        from .table import _DENSE_OPTIMIZERS

        self._dense_len = int(length)
        bounds = np.linspace(0, length, len(self._conns) + 1).astype(np.int64)
        self._dense_bounds = bounds
        opt = _DENSE_OPTIMIZERS[optimizer]
        for s in range(len(self._conns)):
            blk = int(bounds[s + 1] - bounds[s])
            body = struct.pack("<qif", blk, opt, float(learning_rate))
            self._request(s, _OP_DENSE_INIT, body)

    def _block(self, s: int):
        return int(self._dense_bounds[s]), int(self._dense_bounds[s + 1])

    def dense_pull(self) -> np.ndarray:
        out = np.empty(self._dense_len, np.float32)

        def one(s):
            lo, hi = self._block(s)
            if hi == lo:
                return
            body = struct.pack("<qq", 0, hi - lo)
            out[lo:hi] = np.frombuffer(
                self._request(s, _OP_DENSE_PULL, body), np.float32)

        self._fanout(one)
        return out

    def dense_push(self, grads: np.ndarray) -> None:
        self._dense_scatter(grads, _OP_DENSE_PUSH)

    def dense_set(self, values: np.ndarray) -> None:
        self._dense_scatter(values, _OP_DENSE_SET)

    def _dense_scatter(self, arr: np.ndarray, op: int) -> None:
        arr = np.ascontiguousarray(np.asarray(arr, np.float32).reshape(-1))
        assert arr.size == self._dense_len

        def one(s):
            lo, hi = self._block(s)
            if hi == lo:
                return
            body = struct.pack("<qq", 0, hi - lo) + \
                np.ascontiguousarray(arr[lo:hi]).tobytes()
            self._request(s, op, body)

        self._fanout(one)

    # -- control plane (all servers) --------------------------------------
    def __len__(self) -> int:
        total = 0
        for s in range(len(self._conns)):
            total += struct.unpack("<q", self._request(s, _OP_SIZE))[0]
        return total

    def keys(self) -> np.ndarray:
        parts = []
        for s in range(len(self._conns)):
            parts.append(np.frombuffer(self._request(s, _OP_KEYS), np.int64))
        return np.concatenate(parts) if parts else np.empty(0, np.int64)

    def shrink(self, threshold: float = 1.0) -> int:
        dropped = 0
        for s in range(len(self._conns)):
            body = struct.pack("<f", float(threshold))
            # no retry: shrink decays counters/evicts — re-applying on a
            # lost reply would decay twice
            dropped += struct.unpack(
                "<q", self._request(s, _OP_SHRINK, body, retry=False))[0]
        return dropped

    def set_learning_rate(self, lr: float) -> None:
        for s in range(len(self._conns)):
            self._request(s, _OP_SET_LR, struct.pack("<f", float(lr)))

    def save(self, path: str) -> None:
        """Each server snapshots its shard to ``<path>.shard<i>``."""
        for s in range(len(self._conns)):
            self._request(s, _OP_SAVE, f"{path}.shard{s}".encode())

    def load(self, path: str, merge: bool = False) -> None:
        for s in range(len(self._conns)):
            body = struct.pack("<B", 1 if merge else 0) + \
                f"{path}.shard{s}".encode()
            self._request(s, _OP_LOAD, body)

    def barrier(self, world: int, timeout: Optional[float] = 600.0) -> None:
        """Block until ``world`` clients reach the barrier (server 0
        coordinates, cf. the reference's Gloo/brpc worker barrier).

        Uses a dedicated connection: a barrier blocks server-side until the
        world arrives, and holding the shared channel's lock for that long
        would deadlock concurrent callers on this client."""
        conn = _Conn(*self.endpoints[0], timeout=timeout)
        try:
            conn.request(_OP_BARRIER, struct.pack("<I", int(world)))
        finally:
            conn.close()

    def stop_servers(self) -> None:
        for s in range(len(self._conns)):
            try:
                with self._locks[s]:
                    if self._conns[s] is None:
                        self._conns[s] = _Conn(*self.endpoints[s])
                    self._conns[s].request(_OP_STOP)
            except (PsRpcError, OSError):
                pass  # server exits as it acks; a dropped ack is fine

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        for conn in self._conns:
            if conn is not None:
                conn.close()


def _merge_grads(keys: np.ndarray, grads: np.ndarray):
    """Sum grads of duplicate keys (the communicator's merge-before-send,
    ``communicator.h`` MergeVars)."""
    uniq, inv = np.unique(keys, return_inverse=True)
    merged = np.zeros((uniq.size, grads.shape[1]), np.float32)
    np.add.at(merged, inv, grads)
    return uniq, merged


class Communicator:
    """Background gradient sender over a :class:`PsClient`.

    Modes (reference ``communicator.h``):
      - ``"sync"``: ``push`` sends immediately (blocking), one RPC per call.
      - ``"async"``: ``push`` enqueues; a background thread drains the queue,
        merging duplicate keys per batch (``AsyncCommunicator::Start``).
      - ``"geo"``: the DELTA-TRAIN trick (``GeoCommunicator``,
        ``communicator.h:596``): gradients apply to a local SGD shadow copy
        immediately (lr = ``geo_lr``); every ``k_steps`` pushes, the
        parameter deltas (shadow − base) are shipped and merged additively
        on the server (``push_raw``), then the shadow re-bases on the fresh
        server values — so other workers' deltas fold in. Training sees
        zero push latency; the cost is k steps of parameter lag.

    ``flush()`` drains everything (end of epoch / before save/eval).
    """

    def __init__(self, client: PsClient, mode: str = "async",
                 k_steps: int = 4, max_queue: int = 64, geo_lr: float = 1.0):
        if mode not in ("sync", "async", "geo"):
            raise ValueError(f"unknown communicator mode {mode!r}")
        self.client = client
        self.mode = mode
        self.k_steps = int(k_steps)
        self.geo_lr = float(geo_lr)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        # geo state: key -> [base_row, shadow_row]
        self._geo_base: Dict[int, np.ndarray] = {}
        self._geo_shadow: Dict[int, np.ndarray] = {}
        self._geo_count = 0
        self._err: Optional[BaseException] = None
        self._running = mode == "async"
        self._thread = None
        if self._running:
            self._thread = threading.Thread(target=self._drain, daemon=True)
            self._thread.start()

    def push(self, keys, grads) -> None:
        if self._err is not None:
            raise self._err
        keys = np.asarray(keys, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(
            keys.size, self.client.embed_dim)
        if self.mode == "sync":
            self.client.push(keys, grads)
        elif self.mode == "async":
            self._queue.put((keys, grads))
        else:  # geo: local apply now, deltas shipped every k steps
            self._geo_apply(keys, grads)
            self._geo_count += 1
            if self._geo_count >= self.k_steps:
                self._send_geo()

    def pull(self, keys) -> np.ndarray:
        """Geo-aware pull: in geo mode, locally-trained shadow rows win over
        (lagged) server rows, so the worker trains on its own freshest
        parameters — the reference's local-first lookup."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        rows = self.client.pull(keys)
        if self.mode == "geo":
            for i, k in enumerate(keys.tolist()):
                sh = self._geo_shadow.get(k)
                if sh is not None:
                    rows[i] = sh
        return rows

    def _geo_apply(self, keys: np.ndarray, grads: np.ndarray) -> None:
        # first touch pulls base rows from the server in one batch
        fresh = [k for k in np.unique(keys).tolist()
                 if k not in self._geo_base]
        if fresh:
            rows = self.client.pull(np.asarray(fresh, np.int64))
            for k, r in zip(fresh, rows):
                self._geo_base[k] = r.copy()
                self._geo_shadow[k] = r.copy()
        for i, k in enumerate(keys.tolist()):
            self._geo_shadow[k] -= self.geo_lr * grads[i]

    def _send_geo(self) -> None:
        """Ship deltas for the keys touched this window, then EVICT the
        whole local state: per-window cost and worker memory stay bounded
        by the window's working set, not the epoch's (a CTR epoch touches
        millions of distinct keys). The next window's first touch re-pulls
        fresh server rows — which by then include this worker's deltas and
        everyone else's."""
        self._geo_count = 0
        if not self._geo_shadow:
            return
        keys = np.asarray(list(self._geo_shadow.keys()), np.int64)
        deltas = np.stack([self._geo_shadow[k] - self._geo_base[k]
                           for k in keys.tolist()])
        moved = np.abs(deltas).max(axis=1) > 0
        if moved.any():
            self.client.push_raw(keys[moved], deltas[moved])
        self._geo_base.clear()
        self._geo_shadow.clear()

    def _drain(self) -> None:
        while self._running or not self._queue.empty():
            batch = []
            try:
                batch.append(self._queue.get(timeout=0.05))
            except queue.Empty:
                continue
            # opportunistically coalesce whatever is queued
            while True:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            try:
                keys = np.concatenate([k for k, _ in batch])
                grads = np.concatenate([g for _, g in batch])
                uniq, merged = _merge_grads(keys, grads)
                self.client.push(uniq, merged)
            except BaseException as e:
                self._err = e
                # account for everything queued so flush()'s join() can't
                # hang on items this dead thread will never process
                for _ in batch:
                    self._queue.task_done()
                while True:
                    try:
                        self._queue.get_nowait()
                        self._queue.task_done()
                    except queue.Empty:
                        break
                return
            for _ in batch:
                self._queue.task_done()

    def flush(self) -> None:
        if self._err is not None:
            raise self._err
        if self.mode == "geo":
            self._send_geo()
        elif self.mode == "async":
            # join() with an escape hatch: if the drain thread died, items
            # enqueued after its final sweep would never be task_done'd
            with self._queue.all_tasks_done:
                while self._queue.unfinished_tasks:
                    if self._err is not None:
                        raise self._err
                    self._queue.all_tasks_done.wait(timeout=0.1)
        if self._err is not None:
            raise self._err

    def stop(self) -> None:
        self.flush()
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def launch_servers(num_servers: int, embed_dim: int, optimizer: str = "adagrad",
                   learning_rate: float = 0.05, seed: int = 0,
                   timeout: float = 30.0):
    """Spawn ``num_servers`` PS server subprocesses on ephemeral ports.

    Returns ``(procs, endpoints)``; each server prints ``PORT <p>`` on stdout
    once bound (the rendezvous handshake — the reference publishes endpoints
    through gloo/etcd instead).
    """
    argv = [sys.executable, "-m", "paddle_tpu.distributed.ps.server",
            "--port", "0", "--embed-dim", str(embed_dim),
            "--optimizer", optimizer, "--lr", str(learning_rate),
            "--seed", str(seed)]
    return launch_port_subprocesses([argv] * num_servers, timeout=timeout)


def launch_port_subprocesses(argvs, timeout: float = 30.0):
    """Spawn one subprocess per argv; each must print ``PORT <p>`` on stdout
    once its server socket is bound. Returns ``(procs, endpoints)``."""
    from ...utils.procutil import pdeathsig_preexec

    procs, endpoints = [], []
    for argv in argvs:
        # servers die with the client (PDEATHSIG): an aborted test/bench
        # run must not leave shard servers running for hours
        procs.append(subprocess.Popen(argv, stdout=subprocess.PIPE,
                                      preexec_fn=pdeathsig_preexec()))
    deadline = time.time() + timeout

    def fail(exc):
        for q in procs:
            q.kill()
        raise exc

    for p in procs:
        buf = b""
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                fail(TimeoutError("PS server startup timed out"))
            # select, not readline: readline would block past the deadline
            # if the server hangs before printing its PORT line
            ready, _, _ = select.select([p.stdout], [], [], remaining)
            if not ready:
                fail(TimeoutError("PS server startup timed out"))
            chunk = p.stdout.read1(4096)
            if not chunk:
                fail(RuntimeError("PS server failed to start"))
            buf += chunk
            # only parse newline-terminated lines: read1 can split "PORT
            # 12345\n" mid-number, and a truncated int would be a wrong port
            complete, _, _ = buf.rpartition(b"\n")
            for line in complete.decode(errors="replace").splitlines():
                if line.startswith("PORT "):
                    endpoints.append(("127.0.0.1", int(line.split()[1])))
                    break
            else:
                continue
            break
    return procs, endpoints
