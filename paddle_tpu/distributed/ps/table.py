"""Sparse embedding tables over the native C++ runtime.

Reference parity: ``paddle/fluid/distributed/ps/table/memory_sparse_table.cc``
(sharded hash of embeddings), ``ssd_sparse_table.cc`` (beyond-RAM spill),
``sparse_sgd_rule.cc`` (per-table optimizer rules), and the GPU-resident
HeterPS path (``paddle/fluid/framework/fleet/heter_ps/``). TPU-native: the
table is host-RAM C++ (no device hashtable on TPU); the chip sees dense
gathered minibatch rows via JAX callbacks (:mod:`.embedding`).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ... import native

_OPTIMIZERS = {"sgd": 0, "adagrad": 1, "adam": 2}


@dataclass
class SparseAccessorConfig:
    """Accessor = value layout + update rule, cf. ``CtrCommonAccessor``
    (``table/ctr_common_accessor.h``) reduced to the functional fields."""

    embed_dim: int = 8
    optimizer: str = "adagrad"
    learning_rate: float = 0.05
    initial_range: float = 0.01
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    seed: int = 0
    num_shards: int = 16
    # ShowClickScore coefficients: shrink evicts keys whose decayed
    # show_coeff*show + click_coeff*click falls below threshold
    # (CtrCommonAccessor show_coeff/click_coeff).
    show_coeff: float = 1.0
    click_coeff: float = 1.0

    def __post_init__(self):
        if self.optimizer not in _OPTIMIZERS:
            raise ValueError(
                f"optimizer must be one of {sorted(_OPTIMIZERS)}, "
                f"got {self.optimizer!r}")


class MemorySparseTable:
    """Thread-sharded in-memory embedding table with C++ update rules.

    ``pull`` auto-initializes missing keys (deterministic per (seed, key));
    ``push`` applies the accessor's optimizer rule server-side — gradients
    never materialize as a dense [vocab, dim] array, which is the whole
    point of the PS design for >HBM vocabularies.
    """

    def __init__(self, accessor: Optional[SparseAccessorConfig] = None, **kw):
        self.accessor = accessor or SparseAccessorConfig(**kw)
        a = self.accessor
        self._lib = native.get_lib()
        self._h = self._lib.pt_table_create(
            a.embed_dim, _OPTIMIZERS[a.optimizer], a.learning_rate,
            a.initial_range, a.beta1, a.beta2, a.epsilon, a.seed,
            a.num_shards)
        if (a.show_coeff, a.click_coeff) != (1.0, 1.0):
            self._lib.pt_table_set_score_coeffs(
                self._h, a.show_coeff, a.click_coeff)

    @property
    def embed_dim(self) -> int:
        return self.accessor.embed_dim

    def pull(self, keys) -> np.ndarray:
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1), np.int64)
        out = np.empty((keys.size, self.embed_dim), np.float32)
        self._lib.pt_table_pull(self._h, native.as_i64_ptr(keys), keys.size,
                                native.as_f32_ptr(out))
        return out

    def push(self, keys, grads) -> None:
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1), np.int64)
        grads = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(keys.size, self.embed_dim))
        self._lib.pt_table_push(self._h, native.as_i64_ptr(keys),
                                native.as_f32_ptr(grads), keys.size)

    def push_raw(self, keys, deltas) -> None:
        """Add raw deltas to embeddings, bypassing the optimizer rule — the
        geo communicator's additive delta merge (GeoCommunicator)."""
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1), np.int64)
        deltas = np.ascontiguousarray(
            np.asarray(deltas, np.float32).reshape(keys.size, self.embed_dim))
        self._lib.pt_table_push_raw(self._h, native.as_i64_ptr(keys),
                                    native.as_f32_ptr(deltas), keys.size)

    def push_show_click(self, keys, shows, clicks) -> None:
        """Accumulate CTR usage stats per key (CtrCommonAccessor shows the
        reference pushing these alongside gradients)."""
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1), np.int64)
        sc = np.empty((keys.size, 2), np.float32)
        sc[:, 0] = np.asarray(shows, np.float32).reshape(-1)
        sc[:, 1] = np.asarray(clicks, np.float32).reshape(-1)
        self._lib.pt_table_push_show_click(
            self._h, native.as_i64_ptr(keys),
            native.as_f32_ptr(np.ascontiguousarray(sc)), keys.size)

    def set_learning_rate(self, lr: float) -> None:
        self._lib.pt_table_set_lr(self._h, float(lr))

    def __len__(self) -> int:
        return int(self._lib.pt_table_size(self._h))

    def keys(self) -> np.ndarray:
        n = len(self)
        out = np.empty(n, np.int64)
        w = self._lib.pt_table_keys(self._h, native.as_i64_ptr(out), n)
        return out[:w]

    def shrink(self, threshold: float = 1.0) -> int:
        """Evict keys with usage counter below ``threshold`` (counters decay
        by half each call), cf. ``MemorySparseTable::Shrink``."""
        return int(self._lib.pt_table_shrink(self._h, float(threshold)))

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        rc = self._lib.pt_table_save(self._h, path.encode())
        if rc != 0:
            raise IOError(f"table save failed ({rc}): {path}")

    def load(self, path: str, merge: bool = False) -> None:
        """Load a snapshot. ``merge=True`` inserts only keys missing from
        RAM — live rows win over snapshot rows (begin_pass semantics)."""
        fn = self._lib.pt_table_load_merge if merge else self._lib.pt_table_load
        rc = fn(self._h, path.encode())
        if rc != 0:
            raise IOError(f"table load failed ({rc}): {path}")

    def clear(self) -> None:
        self._lib.pt_table_clear(self._h)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and native is not None:  # interpreter teardown safety
            try:
                self._lib.pt_table_destroy(h)
            except Exception:
                pass


_DENSE_OPTIMIZERS = {"sgd": 0, "adagrad": 1, "sum": 3}


class MemoryDenseTable:
    """Dense parameter vector with a server-side update rule — the
    reference's ``MemoryDenseTable`` (``table/memory_dense_table.cc``),
    which holds the model's dense weights on PS servers in async/geo
    modes. Optimizers: ``sgd``, ``adagrad``, ``sum`` (raw accumulate)."""

    def __init__(self, length: int, optimizer: str = "sgd",
                 learning_rate: float = 0.05, epsilon: float = 1e-8):
        if optimizer not in _DENSE_OPTIMIZERS:
            raise ValueError(
                f"optimizer must be one of {sorted(_DENSE_OPTIMIZERS)}")
        self.optimizer = optimizer
        self._lib = native.get_lib()
        self._h = self._lib.pt_dense_create(
            int(length), _DENSE_OPTIMIZERS[optimizer], learning_rate, epsilon)

    def __len__(self) -> int:
        return int(self._lib.pt_dense_len(self._h))

    def pull(self, offset: int = 0, length: int = -1) -> np.ndarray:
        n = len(self) - offset if length < 0 else length
        out = np.empty(n, np.float32)
        rc = self._lib.pt_dense_get(self._h, int(offset), n,
                                    native.as_f32_ptr(out))
        if rc != 0:
            raise IndexError(f"dense pull out of range ({rc})")
        return out

    def set(self, values, offset: int = 0) -> None:
        values = np.ascontiguousarray(
            np.asarray(values, np.float32).reshape(-1))
        rc = self._lib.pt_dense_set(self._h, int(offset), values.size,
                                    native.as_f32_ptr(values))
        if rc != 0:
            raise IndexError(f"dense set out of range ({rc})")

    def push(self, grads, offset: int = 0) -> None:
        grads = np.ascontiguousarray(np.asarray(grads, np.float32).reshape(-1))
        rc = self._lib.pt_dense_push(self._h, int(offset), grads.size,
                                     native.as_f32_ptr(grads))
        if rc != 0:
            raise IndexError(f"dense push out of range ({rc})")

    def set_learning_rate(self, lr: float) -> None:
        self._lib.pt_dense_set_lr(self._h, float(lr))

    def save(self, path: str) -> None:
        rc = self._lib.pt_dense_save(self._h, path.encode())
        if rc != 0:
            raise IOError(f"dense save failed ({rc})")

    def load(self, path: str) -> None:
        rc = self._lib.pt_dense_load(self._h, path.encode())
        if rc != 0:
            raise IOError(f"dense load failed ({rc})")

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and native is not None:
            try:
                self._lib.pt_dense_destroy(h)
            except Exception:
                pass


class SSDSparseTable(MemorySparseTable):
    """Beyond-RAM table with pass-based spill, cf. ``SSDSparseTable``
    (``table/ssd_sparse_table.cc``: hot keys in RAM, cold on SSD).

    TPU-native lifecycle mirrors the reference's *pass* structure
    (``PSGPUWrapper::BuildGPUTask`` / ``EndPass``,
    ``ps_gpu_wrapper.h:191``): train on the in-RAM working set, then
    ``end_pass()`` persists everything to the spill file and evicts cold
    keys; a later pass touching an evicted key transparently reloads from
    the snapshot on construction/``begin_pass``.
    """

    def __init__(self, spill_dir: str,
                 accessor: Optional[SparseAccessorConfig] = None,
                 cache_threshold: float = 1.0, **kw):
        super().__init__(accessor, **kw)
        self.spill_dir = spill_dir
        self.cache_threshold = cache_threshold
        os.makedirs(spill_dir, exist_ok=True)
        self._snapshot = os.path.join(spill_dir, "table.bin")
        if os.path.exists(self._snapshot):
            self.load(self._snapshot)

    def end_pass(self) -> int:
        """Persist the full table, then evict cold keys from RAM."""
        self.save(self._snapshot)
        return self.shrink(self.cache_threshold)

    def begin_pass(self) -> None:
        """Reload the snapshot so previously evicted keys are warm again.

        Merge-mode: only keys absent from RAM are inserted, so rows updated
        since the last ``end_pass`` are never rolled back to snapshot values
        (and shrink's counter decay is not undone) even when passes are not
        strictly paired."""
        if os.path.exists(self._snapshot):
            self.load(self._snapshot, merge=True)
