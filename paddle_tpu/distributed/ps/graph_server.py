"""Graph-shard server subprocess entrypoint.

``python -m paddle_tpu.distributed.ps.graph_server --port 0`` prints
``PORT <p>`` once bound, then serves until a client sends STOP — the graph
half of the reference's PS server loop (``graph_brpc_server.cc`` behind
``fleet.init_server()``/``run_server()``)."""
from __future__ import annotations

import argparse


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)

    from ...utils.procutil import start_ppid_watchdog
    from .graph import GraphServer

    # belt-and-braces with the launcher's PDEATHSIG: exit when the parent
    # disappears, so an aborted run can't leak shard servers
    start_ppid_watchdog()
    srv = GraphServer(port=args.port)
    print(f"PORT {srv.port}", flush=True)
    srv.wait()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
