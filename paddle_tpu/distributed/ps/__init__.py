"""Parameter-server subsystem (TPU-native "the one PS").

Reference parity: ``paddle/fluid/distributed/ps/`` (brpc tables/services,
``ps/README.md``), ``python/paddle/distributed/ps/the_one_ps.py`` (table
construction from strategy), and the in-process ``PsLocalClient``
(``ps/service/ps_local_client.h``) that the GPU-PS path uses.

TPU-native shape: tables are host-RAM C++ (:mod:`.table`). Two deployments:

- *Local client* (single host): one process owns all shards in-proc, zero
  RPC — the PsLocalClient trick the reference uses for GpuPS.
- *Service* (multi-host): each host runs a :class:`PsServer` process (C++
  TCP service over its table shard, ``native/src/ps_service.cc``);
  :class:`PsClient` partitions keys by hash across servers and presents the
  same table interface, so :class:`SparseEmbedding` works over the network
  unchanged. :class:`Communicator` adds the reference's sync/async/geo send
  modes (``ps/service/communicator/communicator.h``).
"""
from __future__ import annotations

import weakref
from typing import Dict, Optional

from .embedding import (SparseEmbedding, StagedPull, callbacks_supported,
                        make_lookup)
from .coordinator import (ClientInfoAttr, Coordinator, FLClient, FLStrategy)
from .graph import (DistGraphClient, GraphDataGenerator, GraphServer,
                    GraphTable, launch_graph_servers)
from .pass_builder import PipelinedPassBuilder
from .service import (Communicator, PsClient, PsRpcError, PsServer,
                      launch_servers, shard_of)
from .table import (MemoryDenseTable, MemorySparseTable, SSDSparseTable,
                    SparseAccessorConfig)

__all__ = [
    "SparseAccessorConfig", "MemorySparseTable", "MemoryDenseTable",
    "SSDSparseTable", "PsRpcError",
    "SparseEmbedding", "StagedPull", "callbacks_supported", "make_lookup",
    "PsServer", "PsClient", "Communicator", "launch_servers", "shard_of",
    "ClientInfoAttr", "Coordinator", "FLClient", "FLStrategy",
    "GraphTable", "GraphServer", "DistGraphClient", "GraphDataGenerator",
    "launch_graph_servers", "PipelinedPassBuilder",
    "PSContext", "get_ps_context",
]


class PSContext:
    """Table registry + lifecycle — the ``the_one_ps.py`` analogue.

    ``init_server``/``init_worker`` mirror ``fleet.init_server()`` /
    ``init_worker()``; with the local client they only manage the registry
    (no network to bring up).

    ``configure_mode`` consumes ``DistributedStrategy.a_sync`` /
    ``a_sync_configs`` (reference ``the_one_ps.py`` sync/async/geo mode
    selection): tables served over a :class:`PsClient` get a
    :class:`Communicator` in the matching send mode, and
    :meth:`communicator_for` hands it out for the training loop's pushes.
    """

    def __init__(self):
        self._tables: Dict[str, MemorySparseTable] = {}
        self._running = False
        self._mode = "sync"
        self._geo_k = 4
        # live communicators as weakrefs (for flush-on-reconfigure); the
        # communicator itself is cached ON its client, so its lifetime is
        # the client's — no registry entry can outlive or pin either one
        self._comm_refs: list = []
        self._comm_gen = 0

    def configure_mode(self, strategy) -> str:
        """Derive the communicator mode from a DistributedStrategy
        (``a_sync=False`` -> sync; ``a_sync=True`` -> async; with
        ``a_sync_configs["k_steps"] > 0`` -> geo with that period).

        Reconfiguring flushes and drops any cached communicators — they
        carry the OLD mode/k_steps and must not be handed out again."""
        cfg = getattr(strategy, "a_sync_configs", None) or {}
        if getattr(strategy, "a_sync", False):
            k = int(cfg.get("k_steps", 0))
            mode = "geo" if k > 0 or cfg.get("geo") else "async"
            geo_k = max(k, 1) if mode == "geo" else 4
        else:
            mode, geo_k = "sync", 4
        if (mode, geo_k) != (self._mode, self._geo_k):
            self._drop_communicators()
        self._mode, self._geo_k = mode, geo_k
        return self._mode

    @property
    def mode(self) -> str:
        return self._mode

    def communicator_for(self, client) -> "Communicator":
        """A (cached) Communicator over ``client`` in the configured mode.

        Cached on the client object itself (not an id-keyed registry:
        CPython reuses ids after garbage collection, and a recycled id must
        never hand out a Communicator bound to a dead client's sockets).
        A generation counter invalidates caches when the mode changes."""
        cached = getattr(client, "_ps_communicator", None)
        if cached is not None:
            comm, gen = cached
            if gen == self._comm_gen:
                return comm
        comm = Communicator(client, mode=self._mode, k_steps=self._geo_k)
        client._ps_communicator = (comm, self._comm_gen)
        self._comm_refs.append(weakref.ref(comm))
        return comm

    def create_table(self, name: str,
                     accessor: Optional[SparseAccessorConfig] = None,
                     ssd_spill_dir: Optional[str] = None,
                     **accessor_kw) -> MemorySparseTable:
        if name in self._tables:
            raise ValueError(f"table {name!r} already exists")
        accessor = accessor or SparseAccessorConfig(**accessor_kw)
        if ssd_spill_dir:
            table = SSDSparseTable(ssd_spill_dir, accessor)
        else:
            table = MemorySparseTable(accessor)
        self._tables[name] = table
        return table

    def create_slot_tables(self, slot_dims: Dict[str, int],
                           **accessor_kw) -> Dict[str, MemorySparseTable]:
        """One table per feature slot with its own embedding dim — the
        per-slot-dimension capability of the reference's ``CtrDymfAccessor``
        (dynamic-dim embeddings), expressed as table-per-slot: each slot
        keeps its own accessor, LR, and shrink policy."""
        return {name: self.create_table(name, embed_dim=dim, **accessor_kw)
                for name, dim in slot_dims.items()}

    def get_table(self, name: str) -> MemorySparseTable:
        return self._tables[name]

    @property
    def tables(self) -> Dict[str, MemorySparseTable]:
        return dict(self._tables)

    def init_server(self) -> None:
        self._running = True

    def init_worker(self) -> None:
        self._running = True

    def _drop_communicators(self) -> None:
        """Flush and invalidate cached communicators; the FIRST flush
        failure re-raises — a dead drain thread means pushes were lost, and
        swallowing that would report a clean shutdown over lost gradients."""
        refs, self._comm_refs = self._comm_refs, []
        self._comm_gen += 1  # invalidate every client-side cache entry
        first_err = None
        for ref in refs:
            comm = ref()
            if comm is None:
                continue
            try:
                comm.stop()  # flush pending async/geo pushes
            except BaseException as e:
                first_err = first_err or e
        if first_err is not None:
            raise first_err

    def stop_server(self) -> None:
        try:
            self._drop_communicators()
        finally:
            self._running = False

    def save_persistables(self, dirname: str) -> None:
        """``fleet.save_persistables`` analogue: one snapshot per table."""
        import os

        os.makedirs(dirname, exist_ok=True)
        for name, table in self._tables.items():
            table.save(os.path.join(dirname, f"{name}.table"))

    def load_persistables(self, dirname: str) -> None:
        import os

        for name, table in self._tables.items():
            path = os.path.join(dirname, f"{name}.table")
            if os.path.exists(path):
                table.load(path)


_ctx = PSContext()


def get_ps_context() -> PSContext:
    return _ctx
