"""Pipelined pass building: prefetch the next pass's embedding working set
while the current pass trains.

Reference parity: ``PSGPUWrapper::BuildGPUTask`` driven by
``pre_build_thread`` (``paddle/fluid/framework/fleet/ps_gpu_wrapper.h:191,
198``): pass N trains on device-resident tables while pass N+1's feature
set is pulled from the CPU/SSD table in the background, hiding the
build latency entirely. TPU-native restatement over :class:`StagedPull`:
the "GPU hashtable" is the dense ``rows`` array a jitted step consumes, so
building a pass = dedup + pull; this overlaps it with training on a host
thread.

Usage::

    builder = PipelinedPassBuilder(table)
    builder.prefetch(0, ids_of_pass(0))
    for p in range(num_passes):
        builder.prefetch(p + 1, ids_of_pass(p + 1))   # overlaps training
        rows, inv, uniq = builder.get(p)              # ready or joins
        ... train pass p with rows/inv (StagedPull.lookup) ...
        builder.push(p, row_grads)                    # table update
        builder.end_pass(p)
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from .embedding import StagedPull
from .table import MemorySparseTable

__all__ = ["PipelinedPassBuilder"]


class PipelinedPassBuilder:
    """One background build at a time (the reference also serializes its
    pre-build thread); results are cached until consumed."""

    def __init__(self, table: MemorySparseTable):
        self.table = table
        self.staged = StagedPull(table)
        self._built: Dict[int, Tuple] = {}
        self._threads: Dict[int, threading.Thread] = {}
        self._errors: Dict[int, BaseException] = {}
        self._uniq: Dict[int, np.ndarray] = {}
        self._lock = threading.Lock()
        # serializes table *lifecycle* transitions (begin_pass warm-reload
        # inside a build vs end_pass spill+evict in the foreground): either
        # order is correct because begin_pass is a merge-load that never
        # rolls back live rows, but interleaving halves of them is not
        self._table_lock = threading.Lock()

    def prefetch(self, pass_id: int, ids) -> None:
        """Start building ``pass_id`` in the background (idempotent)."""
        with self._lock:
            if pass_id in self._built or pass_id in self._threads:
                return

            ids = np.asarray(ids)

            def build():
                try:
                    with self._table_lock:
                        # warm evicted keys from the spill snapshot first —
                        # without this, an SSD table would re-initialize
                        # evicted keys fresh and silently lose training
                        if hasattr(self.table, "begin_pass"):
                            self.table.begin_pass()
                        rows, inv, uniq = self.staged.pull(ids)
                    with self._lock:
                        self._built[pass_id] = (rows, inv, uniq)
                        self._uniq[pass_id] = uniq
                except BaseException as e:
                    with self._lock:
                        self._errors[pass_id] = e

            t = threading.Thread(target=build, daemon=True)
            self._threads[pass_id] = t
            t.start()

    def get(self, pass_id: int, timeout: Optional[float] = None):
        """The built pass (joins the build thread if still running)."""
        t = self._threads.get(pass_id)
        if t is None and pass_id not in self._built:
            raise KeyError(f"pass {pass_id} was never prefetched")
        if t is not None:
            t.join(timeout=timeout)
            if t.is_alive():
                raise TimeoutError(f"pass {pass_id} build did not finish")
        with self._lock:
            self._threads.pop(pass_id, None)
            if pass_id in self._errors:
                raise self._errors.pop(pass_id)
            return self._built.pop(pass_id)

    def push(self, pass_id: int, row_grads) -> None:
        """Push the pass's deduped row gradients back (the EndPass flush of
        trained embeddings, ``ps_gpu_wrapper.h`` EndPass)."""
        uniq = self._uniq.get(pass_id)
        if uniq is None:
            raise KeyError(f"pass {pass_id} has no pulled key set")
        with self._table_lock:
            # warm-reload first: in the pipelined order an intervening
            # end_pass may have evicted this pass's keys, and pushing into
            # FindOrInit-re-initialized rows would permanently lose their
            # trained snapshot values
            if hasattr(self.table, "begin_pass"):
                self.table.begin_pass()
            self.staged.push(uniq, row_grads)

    def end_pass(self, pass_id: int) -> None:
        with self._lock:
            self._uniq.pop(pass_id, None)
        if hasattr(self.table, "end_pass"):
            with self._table_lock:
                self.table.end_pass()
