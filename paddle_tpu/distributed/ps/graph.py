"""Graph table + walk-based batch generator over the native CSR store.

Reference parity:
  - ``GraphGpuWrapper``/``GpuPsGraphTable`` (``paddle/fluid/framework/fleet/
    heter_ps/graph_gpu_wrapper.h:25``, ``graph_gpu_ps_table.h:32``) — graph
    storage + ``graph_neighbor_sample_v2``;
  - ``GraphDataGenerator`` (``paddle/fluid/framework/data_feed.h:893``,
    walk kernel ``data_feed.cu:708``, ``FillWalkBuf`` ``data_feed.cu:883``) —
    random-walk window batches with negative sampling for
    deepwalk/node2vec-style GNN+CTR training;
  - CPU-side ``CommonGraphTable`` (``ps/table/common_graph_table.cc``).

TPU-native: sampling runs on host C++ threads (no device hashtable); every
batch is padded to static shapes before reaching XLA (SURVEY.md §7 dynamic-
shape strategy), so the jitted model never recompiles.
"""
from __future__ import annotations

import struct
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from ... import native


class GraphTable:
    """CSR graph with thread-parallel neighbor sampling and random walks."""

    def __init__(self):
        self._lib = native.get_lib()
        self._h = self._lib.pt_graph_create()
        self._built = False

    def add_edges(self, src, dst, weights=None) -> None:
        """Add directed edges; optional per-edge float weights bias
        neighbor sampling and walks toward heavier edges (the reference's
        weighted CSR, ``gpu_graph_node.h`` weight payloads)."""
        src = np.ascontiguousarray(np.asarray(src).reshape(-1), np.int64)
        dst = np.ascontiguousarray(np.asarray(dst).reshape(-1), np.int64)
        assert src.size == dst.size
        if weights is None:
            self._lib.pt_graph_add_edges(
                self._h, native.as_i64_ptr(src), native.as_i64_ptr(dst),
                src.size)
        else:
            w = np.ascontiguousarray(
                np.asarray(weights, np.float32).reshape(-1))
            assert w.size == src.size
            self._lib.pt_graph_add_edges_weighted(
                self._h, native.as_i64_ptr(src), native.as_i64_ptr(dst),
                native.as_f32_ptr(w), src.size)
        self._built = False

    def clear_edges(self) -> None:
        """Drop all edges (and the derived CSR); features are kept."""
        self._lib.pt_graph_clear_edges(self._h)
        self._built = False

    def build(self, symmetric: bool = False) -> None:
        """Finalize into CSR. ``symmetric=True`` adds reverse edges
        (reverse edges reuse their forward edge's weight)."""
        self._lib.pt_graph_build(self._h, 1 if symmetric else 0)
        self._built = True

    @property
    def num_nodes(self) -> int:
        return int(self._lib.pt_graph_num_nodes(self._h))

    @property
    def num_edges(self) -> int:
        return int(self._lib.pt_graph_num_edges(self._h))

    def node_ids(self) -> np.ndarray:
        n = self.num_nodes
        out = np.empty(n, np.int64)
        w = self._lib.pt_graph_node_ids(self._h, native.as_i64_ptr(out), n)
        return out[:w]

    def degree(self, key: int) -> int:
        return int(self._lib.pt_graph_degree(self._h, int(key)))

    def sample_neighbors(self, nodes, sample_size: int, replace: bool = False,
                         seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
        """Sample up to ``sample_size`` neighbors per node.

        Returns ``(neighbors [n, k] int64 padded -1, counts [n] int32)`` —
        the padded-static-shape form of ``graph_neighbor_sample_v2``.
        """
        assert self._built, "call build() first"
        nodes = np.ascontiguousarray(np.asarray(nodes).reshape(-1), np.int64)
        out = np.empty((nodes.size, sample_size), np.int64)
        counts = np.empty(nodes.size, np.int32)
        self._lib.pt_graph_sample_neighbors(
            self._h, native.as_i64_ptr(nodes), nodes.size, sample_size,
            1 if replace else 0, seed, native.as_i64_ptr(out),
            native.as_i32_ptr(counts))
        return out, counts

    def random_walk(self, starts, walk_len: int, seed: int = 0) -> np.ndarray:
        """Fixed-length uniform random walks; [n, walk_len] int64, padded -1
        after dead ends (start node excluded). Each hop is deterministic in
        (seed, walk row, step, node) so the sharded client's hop-by-hop walk
        reproduces this exactly."""
        assert self._built, "call build() first"
        starts = np.ascontiguousarray(np.asarray(starts).reshape(-1), np.int64)
        out = np.empty((starts.size, walk_len), np.int64)
        self._lib.pt_graph_random_walk(
            self._h, native.as_i64_ptr(starts), starts.size, walk_len, seed,
            native.as_i64_ptr(out))
        return out

    def walk_step(self, nodes, idxs, step: int, seed: int = 0) -> np.ndarray:
        """One walk hop per node: ``next[i] = hop(nodes[i])`` chosen
        deterministically from ``(seed, idxs[i], step, nodes[i])``; -1 for
        sinks/unknown/negative inputs."""
        nodes = np.ascontiguousarray(np.asarray(nodes).reshape(-1), np.int64)
        idxs = np.ascontiguousarray(np.asarray(idxs).reshape(-1), np.int64)
        out = np.empty(nodes.size, np.int64)
        self._lib.pt_graph_walk_step(
            self._h, native.as_i64_ptr(nodes), native.as_i64_ptr(idxs),
            nodes.size, int(step), seed, native.as_i64_ptr(out))
        return out

    # -- node features (GpuPsCommGraphFea, gpu_graph_node.h:326) ----------
    def set_features(self, keys, feats) -> None:
        """Attach a float feature vector to each node (first call fixes the
        feature dim)."""
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1), np.int64)
        feats = np.ascontiguousarray(
            np.asarray(feats, np.float32).reshape(keys.size, -1))
        rc = self._lib.pt_graph_set_features(
            self._h, native.as_i64_ptr(keys), native.as_f32_ptr(feats),
            keys.size, feats.shape[1])
        if rc != 0:
            raise ValueError(
                f"feature dim {feats.shape[1]} != table dim {self.feature_dim}")

    @property
    def feature_dim(self) -> int:
        return int(self._lib.pt_graph_feature_dim(self._h))

    def sample_with_features(self, nodes, sample_size: int,
                             replace: bool = False, seed: int = 0):
        """Neighbor sample with features attached (graph_neighbor_sample_v3
        analogue); see :func:`_sample_with_features`."""
        return _sample_with_features(self, nodes, sample_size, replace, seed)

    def get_features(self, keys) -> np.ndarray:
        """[n, dim] float32 features; zero-filled for nodes without any."""
        dim = self.feature_dim
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1), np.int64)
        if dim == 0:
            return np.zeros((keys.size, 0), np.float32)
        out = np.empty((keys.size, dim), np.float32)
        rc = self._lib.pt_graph_get_features(
            self._h, native.as_i64_ptr(keys), keys.size, dim,
            native.as_f32_ptr(out))
        if rc != 0:
            raise ValueError("feature dim mismatch")
        return out

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and native is not None:
            try:
                self._lib.pt_graph_destroy(h)
            except Exception:
                pass


def _sample_with_features(store, nodes, sample_size: int, replace: bool,
                          seed: int):
    """Neighbor sample + feature gather in one call (the reference's
    ``graph_neighbor_sample_v3``: samples arrive with their
    ``GpuPsCommGraphFea`` payloads). Returns ``(neighbors [n,k], counts [n],
    feats [n,k,dim])`` with zero features on padding."""
    nb, cnt = store.sample_neighbors(nodes, sample_size, replace=replace,
                                     seed=seed)
    dim = store.feature_dim
    flat = nb.reshape(-1)
    feats = np.zeros((flat.size, dim), np.float32)
    valid = np.where(flat >= 0)[0]
    if valid.size and dim:
        feats[valid] = store.get_features(flat[valid])
    return nb, cnt, feats.reshape(nb.shape[0], sample_size, dim)


class GraphServer:
    """One graph shard served over TCP (in-proc flavor for tests; real
    deployments run ``python -m paddle_tpu.distributed.ps.graph_server``).

    The multi-host half of the reference's graph engine: GraphBrpcServer
    (``ps/service/graph_brpc_server.cc``) dispatching into its
    CommonGraphTable shard. Ingest (add_edges/build/set_features) is phased
    before serving reads, matching the reference's pass-based build."""

    def __init__(self, port: int = 0, table: Optional[GraphTable] = None):
        self.table = table or GraphTable()
        self._lib = native.get_lib()
        self._h = self._lib.pt_graph_server_start(self.table._h, int(port))
        if not self._h:
            raise OSError(f"failed to bind graph server on port {port}")

    @property
    def port(self) -> int:
        return int(self._lib.pt_graph_server_port(self._h))

    def wait(self) -> None:
        self._lib.pt_graph_server_wait(self._h)

    def stop(self) -> None:
        h, self._h = self._h, None
        if h:
            self._lib.pt_graph_server_stop(h)
            self._lib.pt_graph_server_destroy(h)

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


# graph service opcodes (native/src/graph_service.cc)
_GOP_ADD_EDGES = 1
_GOP_BUILD = 2
_GOP_NUM_NODES = 3
_GOP_NUM_EDGES = 4
_GOP_NODE_IDS = 5
_GOP_DEGREE = 6
_GOP_SAMPLE = 7
_GOP_WALK_STEP = 8
_GOP_SET_FEAT = 9
_GOP_GET_FEAT = 10
_GOP_FEAT_DIM = 11
_GOP_STOP = 12
_GOP_CLEAR_EDGES = 13
_GOP_ADD_EDGES_W = 14
_GOP_WALK_MULTI = 15


class DistGraphClient:
    """Sharded graph client: the :class:`GraphTable` interface over N graph
    servers, nodes partitioned by ``shard_of`` (a node's adjacency and
    features live wholly on its owner shard).

    Parity contract with the single-host store (tested in test_graph.py):

    - ``sample_neighbors`` routes each query node to its owner; the owner
      holds that node's full CSR row in the same order the single-host
      store would, and sampling is deterministic per (seed, node) — so
      results are bit-identical.
    - ``random_walk`` steps hop-by-hop: at step t the frontier is grouped
      by owner shard, each owner picks the next neighbor deterministically
      from (seed, walk row, step, node) — the HeterComm per-hop key
      exchange (``graph_gpu_ps_table.h:128-134``) restated client-side.
      Bit-identical to the single-host walk.
    - ``set_features``/``get_features`` route by owner.

    Edges are buffered client-side and partitioned at :meth:`build` (both
    directions for ``symmetric=True``, forward before reverse, preserving
    the single-host CSR row order).
    """

    def __init__(self, endpoints: Sequence[Tuple[str, int]]):
        from .service import _Conn
        import threading

        if not endpoints:
            raise ValueError("need at least one graph endpoint")
        self.endpoints = list(endpoints)
        self._conns = [_Conn(h, p) for h, p in self.endpoints]
        self._locks = [threading.Lock() for _ in self._conns]
        self._src_buf: list = []
        self._dst_buf: list = []
        self._w_buf: list = []
        self._built = False

    def _shard_of(self, keys: np.ndarray) -> np.ndarray:
        from .service import shard_of

        return shard_of(keys, len(self._conns))

    def _request(self, s: int, op: int, body: bytes = b"") -> bytes:
        with self._locks[s]:
            return self._conns[s].request(op, body)

    def _request_multi(self, reqs):
        """Scatter-gather: write EVERY request before reading any reply,
        so the shards' server-side work overlaps instead of serializing
        one round-trip per shard (the brpc parallel-channel pattern,
        ``brpc_ps_client.cc`` DownpourBrpcClosure over N requests).
        ``reqs`` is ``[(shard, op, body), ...]``; replies come back in the
        same order (the framed protocol answers pipelined requests in
        FIFO order per connection)."""
        held = sorted({s for s, _, _ in reqs})
        for s in held:
            self._locks[s].acquire()
        try:
            for s, op, body in reqs:
                self._conns[s].send(op, body)
            # EVERY pipelined reply must be read even when one is an error
            # frame — an unread reply would desync that connection and the
            # next request would parse a stale payload as its own
            results, first_err = [], None
            for s, op, _ in reqs:
                try:
                    results.append(self._conns[s].recv(op))
                except Exception as e:  # noqa: BLE001 — re-raised below
                    results.append(None)
                    if first_err is None:
                        first_err = e
            if first_err is not None:
                raise first_err
            return results
        finally:
            for s in held:
                self._locks[s].release()

    # -- ingest ------------------------------------------------------------
    def clear_edges(self) -> None:
        """Drop the client-side edge buffer (a later build() starts from
        scratch; servers clear on every build anyway)."""
        self._src_buf, self._dst_buf, self._w_buf = [], [], []
        self._built = False

    def add_edges(self, src, dst, weights=None) -> None:
        src = np.ascontiguousarray(np.asarray(src).reshape(-1), np.int64)
        dst = np.ascontiguousarray(np.asarray(dst).reshape(-1), np.int64)
        assert src.size == dst.size
        if weights is not None:
            weights = np.ascontiguousarray(
                np.asarray(weights, np.float32).reshape(-1))
            assert weights.size == src.size
        self._src_buf.append(src)
        self._dst_buf.append(dst)
        self._w_buf.append(weights)
        self._built = False

    def build(self, symmetric: bool = False) -> None:
        src = (np.concatenate(self._src_buf) if self._src_buf
               else np.empty(0, np.int64))
        dst = (np.concatenate(self._dst_buf) if self._dst_buf
               else np.empty(0, np.int64))
        weighted = any(w is not None for w in self._w_buf)
        if weighted:
            w = np.concatenate([
                np.ones(s.size, np.float32) if wb is None else wb
                for s, wb in zip(self._src_buf, self._w_buf)])
        else:
            w = None
        if symmetric:
            # forward stream first, then the reversed stream — the order the
            # single-host Build(symmetric) appends them, so each owner's CSR
            # rows match (reverse edges keep their forward weight)
            src, dst = (np.concatenate([src, dst]), np.concatenate([dst, src]))
            if weighted:
                w = np.concatenate([w, w])
        owner = self._shard_of(src)
        # clear first: the client re-sends its FULL buffer each build.
        # Scatter incrementally — build each shard's edge body, send its
        # three pipelined requests, and FREE the body before building the
        # next shard's (the bodies together would double the edge set's
        # footprint) — then gather every reply at the end, so each shard
        # partitions/sorts while the client streams the next shard's edges.
        sent = []  # (shard, op) in send order
        for s in range(len(self._conns)):
            self._locks[s].acquire()
        try:
            for s in range(len(self._conns)):
                sel = owner == s
                ss, dd = src[sel], dst[sel]
                if weighted:
                    body = (struct.pack("<I", ss.size) + ss.tobytes()
                            + dd.tobytes() + w[sel].tobytes())
                    add_op = _GOP_ADD_EDGES_W
                else:
                    body = (struct.pack("<I", ss.size) + ss.tobytes()
                            + dd.tobytes())
                    add_op = _GOP_ADD_EDGES
                del ss, dd
                self._conns[s].send(_GOP_CLEAR_EDGES)
                self._conns[s].send(add_op, body)
                del body
                self._conns[s].send(_GOP_BUILD, struct.pack("<B", 0))
                sent += [(s, _GOP_CLEAR_EDGES), (s, add_op), (s, _GOP_BUILD)]
            first_err = None
            for s, op in sent:
                try:
                    self._conns[s].recv(op)
                except Exception as e:  # noqa: BLE001 — re-raised below
                    if first_err is None:
                        first_err = e
            if first_err is not None:
                raise first_err
        finally:
            for s in range(len(self._conns)):
                self._locks[s].release()
        self._built = True

    # -- control plane -----------------------------------------------------
    @property
    def num_nodes(self) -> int:
        return int(self.node_ids().size)

    @property
    def num_edges(self) -> int:
        return sum(
            struct.unpack("<q", self._request(s, _GOP_NUM_EDGES))[0]
            for s in range(len(self._conns)))

    def node_ids(self) -> np.ndarray:
        parts = [np.frombuffer(self._request(s, _GOP_NODE_IDS), np.int64)
                 for s in range(len(self._conns))]
        # endpoints of cross-shard edges are interned on both sides; the
        # global node set is the union
        return np.unique(np.concatenate(parts)) if parts else \
            np.empty(0, np.int64)

    def degree(self, key: int) -> int:
        s = int(self._shard_of(np.asarray([key], np.int64))[0])
        return struct.unpack(
            "<q", self._request(s, _GOP_DEGREE, struct.pack("<q", int(key))))[0]

    # -- data plane --------------------------------------------------------
    def sample_neighbors(self, nodes, sample_size: int, replace: bool = False,
                         seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
        assert self._built, "call build() first"
        nodes = np.ascontiguousarray(np.asarray(nodes).reshape(-1), np.int64)
        out = np.empty((nodes.size, sample_size), np.int64)
        counts = np.empty(nodes.size, np.int32)
        owner = self._shard_of(nodes)
        reqs, sels = [], []
        for s in range(len(self._conns)):
            sel = np.where(owner == s)[0]
            if sel.size == 0:
                continue
            part = nodes[sel]
            body = (struct.pack("<IiBQ", part.size, sample_size,
                                1 if replace else 0, seed) + part.tobytes())
            reqs.append((s, _GOP_SAMPLE, body))
            sels.append(sel)
        for sel, payload in zip(sels, self._request_multi(reqs)):
            nb = np.frombuffer(payload[:sel.size * sample_size * 8],
                               np.int64).reshape(sel.size, sample_size)
            ct = np.frombuffer(payload[sel.size * sample_size * 8:], np.int32)
            out[sel] = nb
            counts[sel] = ct
        return out, counts

    def random_walk(self, starts, walk_len: int, seed: int = 0) -> np.ndarray:
        """Distributed walk with server-side multi-hop runs: each walker
        advances ON its owner shard until it dies, finishes, or its next
        node belongs to another shard — one scatter-gather round per
        shard-crossing instead of one round-trip per hop (for 2 uniform
        shards that halves the RPC rounds; the reference's server-side
        FillWalkBuf + HeterComm handoff, ``ps_gpu_wrapper.h:198``).
        Per-hop hashing is unchanged, so output stays bit-identical to the
        single-host :meth:`GraphTable.random_walk`."""
        assert self._built, "call build() first"
        starts = np.ascontiguousarray(np.asarray(starts).reshape(-1), np.int64)
        n = starts.size
        out = np.full((n, walk_len), -1, np.int64)
        cur = starts.copy()
        step = np.zeros(n, np.int32)
        rows = np.arange(n, dtype=np.int64)
        num_shards = len(self._conns)
        active = np.where(cur >= 0)[0]
        # chunk so BOTH frames stay safely under the server's 256 MB cap:
        # worst-case reply is walk_len*8+5 bytes/walker, the request is a
        # flat 20 bytes/walker (which dominates at walk_len=1)
        max_per_req = max(1, (200 << 20) // max(walk_len * 8 + 5, 20))
        while active.size:
            owner = self._shard_of(cur[active])
            reqs, sels = [], []
            for s in range(num_shards):
                shard_sel = active[owner == s]
                for lo in range(0, shard_sel.size, max_per_req):
                    sel = shard_sel[lo:lo + max_per_req]
                    body = (struct.pack("<IiIIQ", sel.size, walk_len, s,
                                        num_shards, seed)
                            + cur[sel].tobytes() + rows[sel].tobytes()
                            + step[sel].tobytes())
                    reqs.append((s, _GOP_WALK_MULTI, body))
                    sels.append(sel)
            still = []
            for sel, payload in zip(sels, self._request_multi(reqs)):
                m = sel.size
                adv = np.frombuffer(payload[:4 * m], np.int32)
                status = np.frombuffer(payload[4 * m:5 * m], np.uint8)
                flat = np.frombuffer(payload[5 * m:], np.int64)
                adv64 = adv.astype(np.int64)
                # scatter variable-length runs into out[row, step:step+adv]
                tgt_rows = np.repeat(sel, adv64)
                run_end = np.cumsum(adv64)
                tgt_cols = (np.arange(flat.size, dtype=np.int64)
                            - np.repeat(run_end - adv64, adv64)
                            + np.repeat(step[sel].astype(np.int64), adv64))
                out[tgt_rows, tgt_cols] = flat
                step[sel] += adv
                has = adv64 > 0
                cur[sel[has]] = flat[run_end[has] - 1]
                still.append(sel[status == 2])  # handoff: still walking
            active = (np.concatenate(still) if still
                      else np.empty(0, np.int64))
        return out

    # -- features ----------------------------------------------------------
    def set_features(self, keys, feats) -> None:
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1), np.int64)
        feats = np.ascontiguousarray(
            np.asarray(feats, np.float32).reshape(keys.size, -1))
        dim = feats.shape[1]
        owner = self._shard_of(keys)
        reqs = []
        for s in range(len(self._conns)):
            sel = owner == s
            if not sel.any():
                continue
            kk, ff = keys[sel], feats[sel]
            body = (struct.pack("<Ii", kk.size, dim) + kk.tobytes()
                    + ff.tobytes())
            reqs.append((s, _GOP_SET_FEAT, body))
        self._request_multi(reqs)

    @property
    def feature_dim(self) -> int:
        dims = [struct.unpack("<i", self._request(s, _GOP_FEAT_DIM))[0]
                for s in range(len(self._conns))]
        return max(dims) if dims else 0

    def sample_with_features(self, nodes, sample_size: int,
                             replace: bool = False, seed: int = 0):
        """Neighbor sample with features attached (graph_neighbor_sample_v3
        analogue); see :func:`_sample_with_features`."""
        return _sample_with_features(self, nodes, sample_size, replace, seed)

    def get_features(self, keys) -> np.ndarray:
        dim = self.feature_dim
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1), np.int64)
        if dim == 0:
            return np.zeros((keys.size, 0), np.float32)
        out = np.zeros((keys.size, dim), np.float32)
        owner = self._shard_of(keys)
        reqs, sels = [], []
        for s in range(len(self._conns)):
            sel = np.where(owner == s)[0]
            if sel.size == 0:
                continue
            kk = keys[sel]
            body = struct.pack("<Ii", kk.size, dim) + kk.tobytes()
            reqs.append((s, _GOP_GET_FEAT, body))
            sels.append(sel)
        for sel, payload in zip(sels, self._request_multi(reqs)):
            out[sel] = np.frombuffer(payload, np.float32).reshape(sel.size,
                                                                  dim)
        return out

    # -- lifecycle ---------------------------------------------------------
    def stop_servers(self) -> None:
        for s in range(len(self._conns)):
            try:
                self._request(s, _GOP_STOP)
            except (IOError, ConnectionError):
                pass  # server exits as it acks

    def close(self) -> None:
        for conn in self._conns:
            conn.close()


def launch_graph_servers(num_servers: int, timeout: float = 30.0):
    """Spawn graph-shard server subprocesses on ephemeral ports; returns
    ``(procs, endpoints)`` via the PORT-line handshake."""
    import sys

    from .service import launch_port_subprocesses

    argv = [sys.executable, "-m", "paddle_tpu.distributed.ps.graph_server",
            "--port", "0"]
    return launch_port_subprocesses([argv] * num_servers, timeout=timeout)


class GraphDataGenerator:
    """Walk-window skip-gram batch stream with negative sampling.

    The ``GraphDataGenerator`` analogue (``data_feed.h:893``): walks start
    from every node (shuffled per epoch), a sliding window over each walk
    emits (center, context) positive pairs, and negatives are drawn uniformly
    from the node set — the deepwalk training feed of the reference's PGLBox
    pipeline. Batches are constant-shape ``(batch_size,)`` int64 triples
    (center, context, negatives[batch, num_neg]) so the jitted step compiles
    once; the final partial batch is dropped (reference drops it too).
    """

    def __init__(self, graph: GraphTable, batch_size: int = 512,
                 walk_len: int = 8, window: int = 2, num_neg: int = 4,
                 seed: int = 0, starts: Optional[np.ndarray] = None):
        self.graph = graph
        self.batch_size = batch_size
        self.walk_len = walk_len
        self.window = window
        self.num_neg = num_neg
        self.seed = seed
        self._starts = (np.asarray(starts, np.int64) if starts is not None
                        else graph.node_ids())
        self._nodes = graph.node_ids()
        self._epoch = 0

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        rng = np.random.default_rng(self.seed + self._epoch)
        self._epoch += 1
        starts = rng.permutation(self._starts)
        walks = self.graph.random_walk(
            starts, self.walk_len, seed=int(rng.integers(2 ** 62)))
        # full sequences: start node + its walk
        seqs = np.concatenate([starts[:, None], walks], axis=1)
        centers, contexts = [], []
        L = seqs.shape[1]
        for off in range(1, self.window + 1):
            src = seqs[:, :-off].reshape(-1)
            dst = seqs[:, off:].reshape(-1)
            ok = (src >= 0) & (dst >= 0)
            centers.append(src[ok])
            contexts.append(dst[ok])
            centers.append(dst[ok])   # symmetric window
            contexts.append(src[ok])
        centers = np.concatenate(centers)
        contexts = np.concatenate(contexts)
        perm = rng.permutation(centers.size)
        centers, contexts = centers[perm], contexts[perm]
        bs = self.batch_size
        for i in range(centers.size // bs):
            c = centers[i * bs:(i + 1) * bs]
            x = contexts[i * bs:(i + 1) * bs]
            neg = rng.choice(self._nodes, size=(bs, self.num_neg))
            yield c, x, neg.astype(np.int64)
