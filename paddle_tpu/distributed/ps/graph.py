"""Graph table + walk-based batch generator over the native CSR store.

Reference parity:
  - ``GraphGpuWrapper``/``GpuPsGraphTable`` (``paddle/fluid/framework/fleet/
    heter_ps/graph_gpu_wrapper.h:25``, ``graph_gpu_ps_table.h:32``) — graph
    storage + ``graph_neighbor_sample_v2``;
  - ``GraphDataGenerator`` (``paddle/fluid/framework/data_feed.h:893``,
    walk kernel ``data_feed.cu:708``, ``FillWalkBuf`` ``data_feed.cu:883``) —
    random-walk window batches with negative sampling for
    deepwalk/node2vec-style GNN+CTR training;
  - CPU-side ``CommonGraphTable`` (``ps/table/common_graph_table.cc``).

TPU-native: sampling runs on host C++ threads (no device hashtable); every
batch is padded to static shapes before reaching XLA (SURVEY.md §7 dynamic-
shape strategy), so the jitted model never recompiles.
"""
from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from ... import native


class GraphTable:
    """CSR graph with thread-parallel neighbor sampling and random walks."""

    def __init__(self):
        self._lib = native.get_lib()
        self._h = self._lib.pt_graph_create()
        self._built = False

    def add_edges(self, src, dst) -> None:
        src = np.ascontiguousarray(np.asarray(src).reshape(-1), np.int64)
        dst = np.ascontiguousarray(np.asarray(dst).reshape(-1), np.int64)
        assert src.size == dst.size
        self._lib.pt_graph_add_edges(
            self._h, native.as_i64_ptr(src), native.as_i64_ptr(dst), src.size)
        self._built = False

    def build(self, symmetric: bool = False) -> None:
        """Finalize into CSR. ``symmetric=True`` adds reverse edges."""
        self._lib.pt_graph_build(self._h, 1 if symmetric else 0)
        self._built = True

    @property
    def num_nodes(self) -> int:
        return int(self._lib.pt_graph_num_nodes(self._h))

    @property
    def num_edges(self) -> int:
        return int(self._lib.pt_graph_num_edges(self._h))

    def node_ids(self) -> np.ndarray:
        n = self.num_nodes
        out = np.empty(n, np.int64)
        w = self._lib.pt_graph_node_ids(self._h, native.as_i64_ptr(out), n)
        return out[:w]

    def degree(self, key: int) -> int:
        return int(self._lib.pt_graph_degree(self._h, int(key)))

    def sample_neighbors(self, nodes, sample_size: int, replace: bool = False,
                         seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
        """Sample up to ``sample_size`` neighbors per node.

        Returns ``(neighbors [n, k] int64 padded -1, counts [n] int32)`` —
        the padded-static-shape form of ``graph_neighbor_sample_v2``.
        """
        assert self._built, "call build() first"
        nodes = np.ascontiguousarray(np.asarray(nodes).reshape(-1), np.int64)
        out = np.empty((nodes.size, sample_size), np.int64)
        counts = np.empty(nodes.size, np.int32)
        self._lib.pt_graph_sample_neighbors(
            self._h, native.as_i64_ptr(nodes), nodes.size, sample_size,
            1 if replace else 0, seed, native.as_i64_ptr(out),
            native.as_i32_ptr(counts))
        return out, counts

    def random_walk(self, starts, walk_len: int, seed: int = 0) -> np.ndarray:
        """Fixed-length uniform random walks; [n, walk_len] int64, padded -1
        after dead ends (start node excluded)."""
        assert self._built, "call build() first"
        starts = np.ascontiguousarray(np.asarray(starts).reshape(-1), np.int64)
        out = np.empty((starts.size, walk_len), np.int64)
        self._lib.pt_graph_random_walk(
            self._h, native.as_i64_ptr(starts), starts.size, walk_len, seed,
            native.as_i64_ptr(out))
        return out

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and native is not None:
            try:
                self._lib.pt_graph_destroy(h)
            except Exception:
                pass


class GraphDataGenerator:
    """Walk-window skip-gram batch stream with negative sampling.

    The ``GraphDataGenerator`` analogue (``data_feed.h:893``): walks start
    from every node (shuffled per epoch), a sliding window over each walk
    emits (center, context) positive pairs, and negatives are drawn uniformly
    from the node set — the deepwalk training feed of the reference's PGLBox
    pipeline. Batches are constant-shape ``(batch_size,)`` int64 triples
    (center, context, negatives[batch, num_neg]) so the jitted step compiles
    once; the final partial batch is dropped (reference drops it too).
    """

    def __init__(self, graph: GraphTable, batch_size: int = 512,
                 walk_len: int = 8, window: int = 2, num_neg: int = 4,
                 seed: int = 0, starts: Optional[np.ndarray] = None):
        self.graph = graph
        self.batch_size = batch_size
        self.walk_len = walk_len
        self.window = window
        self.num_neg = num_neg
        self.seed = seed
        self._starts = (np.asarray(starts, np.int64) if starts is not None
                        else graph.node_ids())
        self._nodes = graph.node_ids()
        self._epoch = 0

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        rng = np.random.default_rng(self.seed + self._epoch)
        self._epoch += 1
        starts = rng.permutation(self._starts)
        walks = self.graph.random_walk(
            starts, self.walk_len, seed=int(rng.integers(2 ** 62)))
        # full sequences: start node + its walk
        seqs = np.concatenate([starts[:, None], walks], axis=1)
        centers, contexts = [], []
        L = seqs.shape[1]
        for off in range(1, self.window + 1):
            src = seqs[:, :-off].reshape(-1)
            dst = seqs[:, off:].reshape(-1)
            ok = (src >= 0) & (dst >= 0)
            centers.append(src[ok])
            contexts.append(dst[ok])
            centers.append(dst[ok])   # symmetric window
            contexts.append(src[ok])
        centers = np.concatenate(centers)
        contexts = np.concatenate(contexts)
        perm = rng.permutation(centers.size)
        centers, contexts = centers[perm], contexts[perm]
        bs = self.batch_size
        for i in range(centers.size // bs):
            c = centers[i * bs:(i + 1) * bs]
            x = contexts[i * bs:(i + 1) * bs]
            neg = rng.choice(self._nodes, size=(bs, self.num_neg))
            yield c, x, neg.astype(np.int64)
