"""Unified fault-tolerance layer for the distributed stack.

Reference parity: the brpc PS client's reconnect/backoff loop
(``brpc_ps_client.cc``), the TCPStore client's retry-until-deadline
rendezvous, and the elastic manager's lease heartbeats — each subsystem of
the reference hand-rolls the same three mechanisms. This module centralises
them so ``rpc``, ``ps.service``, ``launch.kv_server`` and
``launch.elastic`` share one policy surface:

- :class:`RetryPolicy` — exponential backoff with jitter, an optional
  attempt cap and an optional wall-clock deadline, and a retryable-exception
  filter. ``policy.call(fn)`` is the single retry loop the whole stack
  uses; :class:`Unavailable` lets poll loops ("key not there yet") ride the
  same machinery as transport failures.
- :func:`with_timeout` — bound any blocking call by a deadline (worker
  thread + join; the thread is abandoned on timeout, so only use it around
  calls that are safe to orphan, e.g. during shutdown).
- :class:`FaultPlan` — deterministic fault injection. A plan is a list of
  :class:`FaultRule`\\ s keyed by call-site tag (``kv.put``,
  ``rpc.connect.worker1``, ``ps.request.0``, ``ckpt.shard_write``; the
  self-healing train loop adds ``train.step`` / ``train.ckpt`` /
  ``train.data`` — a ``drop`` at ``train.data`` is interpreted by the
  supervisor as a poisoned/NaN batch, ``delay`` at ``train.step`` as a
  step stall, ``crash`` anywhere as a SIGKILL);
  instrumented call sites invoke :func:`fault_point` which consults the
  active plan. Kinds: ``drop`` (raise :class:`InjectedFault`, a
  ``ConnectionError`` — production retry paths treat it as a transport
  failure), ``delay`` (sleep a fixed duration), ``slow`` (sleep a
  seeded-random duration in ``[0.5, 1.5) * delay`` — the gray-failure
  model: a replica that stays alive but each matching call drags by a
  different, replayable amount), ``crash`` (``os._exit(CRASH_EXIT)`` —
  the process dies as hard as a SIGKILL, no atexit/finally),
  ``partition`` (a contiguous outage window of calls), ``bitflip``
  (raise :class:`InjectedBitflip` — the owner of the site flips one
  seeded bit in one rank's physical tensor copies: the
  silent-data-corruption model of ``distributed/integrity.py``). All
  randomness is seeded per rule,
  so a plan replays identically. Activating a plan (``with plan:`` or
  ``plan.install(env=True)``) also exports it via the ``PT_FAULT_PLAN``
  env var, so subprocesses spawned under the plan inherit it.

Nothing here imports jax — the launcher and tools can use it without
initialising a backend.
"""
from __future__ import annotations

import fnmatch
import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple, Type, Union

__all__ = [
    "RetryPolicy", "Unavailable", "with_timeout", "Deadline",
    "FaultPlan", "FaultRule", "InjectedFault", "InjectedBitflip",
    "fault_point", "active_plan", "CRASH_EXIT", "FAULT_PLAN_ENV",
    "EXIT_PREEMPTED", "EXIT_HANG", "EXIT_EVICTED",
]

# Exit codes of the self-healing training layer (framework/supervisor.py).
# ``distributed.launch`` recognises them: a worker that exits with
# EXIT_PREEMPTED checkpointed cleanly under its grace deadline and is
# restarted WITHOUT charging --max_restarts (resume lands on the recorded
# step via AutoCheckpoint + the data cursor); EXIT_HANG is the hang
# watchdog's hard exit after a step exceeded step_timeout (restart charges
# the budget — a hang may be a real bug, not an infra blip).
EXIT_PREEMPTED = 44
EXIT_HANG = 45
# the integrity escalation ladder convicted a host of sticky silent data
# corruption (distributed/integrity.py): the quarantine record is already
# durable next to the checkpoints, and the launcher restarts the job on the
# surviving capacity — elastic_mesh absorbs the shrink like a preemption,
# but the restart DOES charge the budget (a conviction names real hardware)
EXIT_EVICTED = 46


class Deadline:
    """A monotonic wall-clock budget stamped once at creation (the same
    single-budget discipline ``bench.py``'s supervisor applies to its
    probe + bench retries). The serving scheduler stamps one per request
    at submit: a request that waits out its budget in the queue is
    expired with ``TimeoutError``, never admitted.
    """

    __slots__ = ("expires_at", "total")

    def __init__(self, seconds: float):
        self.total = float(seconds)
        self.expires_at = time.monotonic() + self.total

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def __repr__(self):
        return f"Deadline(remaining={self.remaining():.3f}s)"


class Unavailable(ConnectionError):
    """A resource is not ready yet (missing KV key, absent peer). Raised by
    poll-style callables run under a :class:`RetryPolicy` so "not there
    yet" retries exactly like a transport failure."""


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter with an attempt cap and/or deadline.

    Give-up semantics: exhausting ``max_attempts`` re-raises the last
    underlying exception (callers keep their original error types);
    exceeding ``deadline`` raises :class:`TimeoutError` chained to the last
    failure. ``jitter`` is a +/- fraction of each delay; with ``seed`` set
    the jitter sequence is deterministic (fault-injection tests replay
    byte-identical schedules).
    """

    max_attempts: Optional[int] = None   # None = unlimited (deadline bounds)
    deadline: Optional[float] = None     # total seconds across all attempts
    base_delay: float = 0.2
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.0
    retryable: Tuple[Type[BaseException], ...] = (ConnectionError, OSError)
    seed: Optional[int] = None

    def __post_init__(self):
        if self.max_attempts is None and self.deadline is None:
            raise ValueError("RetryPolicy needs max_attempts or deadline "
                             "(an unbounded retry loop hides dead peers)")

    def delays(self):
        """The backoff schedule (unbounded generator; deterministic given
        ``seed``)."""
        rng = random.Random(self.seed)
        delay = self.base_delay
        while True:
            d = delay
            if self.jitter:
                d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
            yield max(0.0, d)
            delay = min(delay * self.multiplier, self.max_delay)

    def call(self, fn: Callable, *args,
             what: str = "operation",
             on_retry: Optional[Callable[[int, BaseException, float], None]]
             = None, **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying ``retryable`` failures.

        ``on_retry(attempt, exc, sleep)`` fires before each backoff sleep —
        the hook where callers drop poisoned connections.
        """
        start = time.monotonic()
        schedule = self.delays()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except self.retryable as e:
                elapsed = time.monotonic() - start
                if (self.max_attempts is not None
                        and attempt >= self.max_attempts):
                    raise
                if (self.deadline is not None
                        and elapsed >= self.deadline):
                    raise TimeoutError(
                        f"{what} still failing after {attempt} attempts / "
                        f"{elapsed:.1f}s (deadline {self.deadline}s): "
                        f"{e}") from e
                sleep = next(schedule)
                if self.deadline is not None:
                    sleep = min(sleep,
                                max(0.0, self.deadline - elapsed))
                if on_retry is not None:
                    on_retry(attempt, e, sleep)
                time.sleep(sleep)

    def until(self, poll: Callable[[], Optional[object]],
              what: str = "condition"):
        """Retry ``poll`` until it returns a non-``None`` value. ``None``
        results and transport failures both back off through this policy —
        the TCPStore ``wait`` shape."""
        def step():
            out = poll()
            if out is None:
                raise Unavailable(f"{what} not ready")
            return out
        return self.call(step, what=what)


def with_timeout(fn: Callable, timeout: float, what: str = "operation"):
    """Run ``fn()`` bounded by ``timeout`` seconds.

    Runs on a daemon worker thread and joins it; on timeout the thread is
    ABANDONED (python threads cannot be killed), so wrap only calls that
    are safe to orphan — shutdown barriers, best-effort teardown RPCs.
    Raises :class:`TimeoutError` on timeout, else returns/raises what
    ``fn`` did.
    """
    out: List[object] = []
    err: List[BaseException] = []

    def run():
        try:
            out.append(fn())
        except BaseException as e:
            err.append(e)

    t = threading.Thread(target=run, daemon=True, name=f"timeout:{what}")
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise TimeoutError(f"{what} did not finish within {timeout}s")
    if err:
        raise err[0]
    return out[0]


# ---------------------------------------------------------------------------
# deterministic fault injection
# ---------------------------------------------------------------------------

FAULT_PLAN_ENV = "PT_FAULT_PLAN"
# the exit code of an injected crash — tests and the sweep runner assert on
# it to tell "the plan killed the process" from a genuine failure
CRASH_EXIT = 43


class InjectedFault(ConnectionError):
    """An injected ``drop``/``partition`` fault. Subclasses
    ``ConnectionError`` so every production retry path treats it exactly
    like a real transport failure."""


class InjectedBitflip(InjectedFault):
    """An injected ``bitflip`` fault: the raising site's OWNER must flip
    one bit of tensor ``tensor`` in the physical copies held by vote-axis
    rank ``rank`` (``distributed.integrity.apply_bitflip`` is the
    canonical realiser). ``draw`` is a per-activation seeded integer —
    the realiser derives every remaining choice (which matching tensor,
    which element, which bit) from it, so a plan replays the identical
    corruption. Subclasses :class:`InjectedFault` so a site without
    tensor context degrades to an ordinary transport-failure drop."""

    def __init__(self, message: str, *, tensor: Optional[str] = None,
                 rank: int = 0, bit: Optional[int] = None, draw: int = 0):
        super().__init__(message)
        self.tensor = tensor
        self.rank = int(rank)
        self.bit = bit
        self.draw = int(draw)


@dataclass
class FaultRule:
    """One fault at matching call sites.

    ``site`` is an ``fnmatch`` pattern over the tag passed to
    :func:`fault_point` (``"kv.*"``, ``"ps.request.0"``). ``after`` skips
    the first N matching calls; ``times`` caps how often the rule fires
    (``None`` = unlimited). ``prob`` fires probabilistically from a per-rule
    seeded RNG, so the hit sequence is a pure function of (seed, call
    order). Kinds:

    - ``drop``: raise :class:`InjectedFault`.
    - ``delay``: sleep ``delay`` seconds, then let the call proceed.
    - ``slow``: sleep a seeded-random duration in ``[0.5, 1.5) * delay``,
      then let the call proceed — latency injection for gray-failure
      drills (the sequence of durations is a pure function of the rule's
      seed, so a slow-replica soak replays identically).
    - ``crash``: ``os._exit(CRASH_EXIT)`` — no cleanup, like SIGKILL.
    - ``partition``: every matching call in ``[after, after+times)`` fails
      (contiguous outage window; ``times=None`` = never heals).
    - ``bitflip``: raise :class:`InjectedBitflip` carrying ``tensor``
      (fnmatch pattern over parameter names), ``rank`` (vote-axis rank
      whose physical copies get corrupted) and ``bit`` (``None`` = seeded
      draw) — silent-data-corruption injection. The site's owner realises
      the flip (``integrity.apply_bitflip``); ``times=1`` models a
      transient cosmic-ray hit, ``times=None`` a sticky lying chip.
    """

    site: str
    kind: str
    times: Optional[int] = 1
    prob: float = 1.0
    delay: float = 0.05
    after: int = 0
    tensor: Optional[str] = None   # bitflip: parameter-name pattern
    rank: int = 0                  # bitflip: vote-axis rank to corrupt
    bit: Optional[int] = None      # bitflip: fixed bit (None = seeded)

    _KINDS = ("drop", "delay", "slow", "crash", "partition", "bitflip")

    def __post_init__(self):
        if self.kind not in self._KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {self._KINDS}")


class FaultPlan:
    """A seeded, replayable set of :class:`FaultRule`\\ s.

    Use as a context manager in tests::

        plan = FaultPlan([{"site": "kv.get", "kind": "drop", "times": 2}],
                         seed=7)
        with plan:            # installs globally + exports PT_FAULT_PLAN
            ...               # subprocesses spawned here inherit the plan

    ``fired`` counts per-rule activations — tests assert the plan actually
    exercised the path they meant to break.
    """

    def __init__(self, rules: Sequence[Union[FaultRule, dict]],
                 seed: int = 0):
        self.rules: List[FaultRule] = [
            r if isinstance(r, FaultRule) else FaultRule(**r) for r in rules]
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._seen = [0] * len(self.rules)   # matching calls per rule
        self.fired = [0] * len(self.rules)   # activations per rule
        self._rngs = [random.Random(self.seed * 1_000_003 + i)
                      for i in range(len(self.rules))]
        self._prev: Optional[Tuple[Optional["FaultPlan"], Optional[str]]] = None

    # -- (de)serialisation: the subprocess-inheritance channel -------------
    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "rules": [{"site": r.site, "kind": r.kind, "times": r.times,
                       "prob": r.prob, "delay": r.delay, "after": r.after,
                       **({"tensor": r.tensor, "rank": r.rank,
                           "bit": r.bit} if r.kind == "bitflip" else {})}
                      for r in self.rules]})

    @classmethod
    def from_json(cls, raw: str) -> "FaultPlan":
        data = json.loads(raw)
        return cls(data["rules"], seed=data.get("seed", 0))

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        raw = os.environ.get(FAULT_PLAN_ENV)
        return cls.from_json(raw) if raw else None

    # -- activation --------------------------------------------------------
    def install(self, env: bool = True) -> "FaultPlan":
        """Make this the process-wide active plan; with ``env`` the plan is
        also exported so subprocesses inherit it."""
        global _active
        self._prev = (_active, os.environ.get(FAULT_PLAN_ENV))
        _active = self
        if env:
            os.environ[FAULT_PLAN_ENV] = self.to_json()
        return self

    def uninstall(self) -> None:
        global _active
        prev_plan, prev_env = self._prev or (None, None)
        _active = prev_plan
        if prev_env is None:
            os.environ.pop(FAULT_PLAN_ENV, None)
        else:
            os.environ[FAULT_PLAN_ENV] = prev_env
        self._prev = None

    def __enter__(self) -> "FaultPlan":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    # -- the hook ----------------------------------------------------------
    def check(self, site: str) -> None:
        """Evaluate every rule against one call at ``site`` (called from
        :func:`fault_point`). Raises/sleeps/exits per the first firing
        drop/partition rule; delay rules stack."""
        for i, rule in enumerate(self.rules):
            if not fnmatch.fnmatchcase(site, rule.site):
                continue
            with self._lock:
                n = self._seen[i]
                self._seen[i] += 1
                if n < rule.after:
                    continue
                if rule.kind == "partition":
                    if rule.times is not None and n >= rule.after + rule.times:
                        continue
                elif rule.times is not None and self.fired[i] >= rule.times:
                    continue
                if rule.prob < 1.0 and self._rngs[i].random() >= rule.prob:
                    continue
                self.fired[i] += 1
                # the RNG lives under the lock (prob draws share it);
                # the sleep itself happens after release
                sleep_s = rule.delay
                draw = 0
                if rule.kind == "slow":
                    sleep_s = rule.delay * (0.5 + self._rngs[i].random())
                elif rule.kind == "bitflip":
                    draw = self._rngs[i].randrange(1 << 31)
            if rule.kind in ("delay", "slow"):
                time.sleep(sleep_s)
            elif rule.kind == "crash":
                os._exit(CRASH_EXIT)
            elif rule.kind == "bitflip":
                raise InjectedBitflip(
                    f"injected bitflip at {site} "
                    f"(rule {i}, hit {self.fired[i]}, rank {rule.rank})",
                    tensor=rule.tensor, rank=rule.rank, bit=rule.bit,
                    draw=draw)
            else:  # drop / partition
                raise InjectedFault(
                    f"injected {rule.kind} at {site} "
                    f"(rule {i}, hit {self.fired[i]})")


_active: Optional[FaultPlan] = None
_env_checked = False


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, lazily loading ``PT_FAULT_PLAN`` on first use —
    subprocesses spawned under an active plan inherit it without any code
    on their side."""
    global _active, _env_checked
    if _active is None and not _env_checked:
        _env_checked = True
        _active = FaultPlan.from_env()
    return _active


def fault_point(site: str) -> None:
    """Instrumentation hook. Call sites tag themselves
    (``fault_point("kv.put")``); with no active plan this is two attribute
    loads and a comparison — cheap enough for hot paths."""
    plan = _active if _env_checked or _active is not None else active_plan()
    if plan is not None:
        plan.check(site)
