"""DistributedStrategy (reference: 233-field protobuf
``paddle/fluid/framework/distributed_strategy.proto:305`` + python wrapper
``fleet/base/distributed_strategy.py``).

Kept fields are the ones with TPU meaning; NCCL/brpc plumbing knobs
(fuse_grad_size_in_MB, nccl_comm_num, hierarchical_allreduce...) are obsolete
under XLA and intentionally absent. Unknown attribute reads return None so
ported configs don't crash.
"""
from __future__ import annotations

from typing import Any, Dict


class DistributedStrategy:
    def __init__(self):
        # mesh topology (reference hybrid_configs)
        self.hybrid_configs: Dict[str, int] = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sp_degree": 1, "ep_degree": 1,
        }
        # ZeRO stage 0-3 (reference sharding_configs / group_sharded levels)
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {"stage": 1}
        # AMP
        self.amp = False
        self.amp_configs: Dict[str, Any] = {"level": "O1", "dtype": "bfloat16",
                                            "init_loss_scaling": 2.0 ** 15}
        # recompute
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": [], "policy": None}
        # gradient merge / accumulation
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1, "avg": True}
        # pipeline
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {"accumulate_steps": 1,
                                                 "schedule_mode": "1F1B"}
        # parameter server mode (reference a_sync / a_sync_configs)
        self.a_sync = False
        self.a_sync_configs: Dict[str, Any] = {"k_steps": 0, "geo": False}
        # misc parity fields
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True  # no-op: XLA fuses
        self.nccl_comm_num = 1  # no-op
        self.lamb = False
        # LARS (consumed: distributed_optimizer wraps Momentum into
        # LarsMomentum with these knobs)
        self.lars = False
        self.lars_configs: Dict[str, Any] = {
            "lars_coeff": 0.001, "lars_weight_decay": 0.0005,
            "epsilon": 1e-8}
        # LocalSGD (consumed: distributed_model returns a LocalSGDStep)
        self.localsgd = False
        self.localsgd_configs: Dict[str, Any] = {"k_steps": 4}
        self.dgc = False

    @property
    def sharding_stage(self) -> int:
        if not self.sharding:
            return 0
        return int(self.sharding_configs.get("stage", 1))

    def __getattr__(self, name):
        # tolerate reads of reference-only knobs
        if name.startswith("__"):
            raise AttributeError(name)
        return None

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items()}
        return f"DistributedStrategy({fields})"
