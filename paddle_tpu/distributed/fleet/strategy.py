"""DistributedStrategy (reference: 233-field protobuf
``paddle/fluid/framework/distributed_strategy.proto:305`` + python wrapper
``fleet/base/distributed_strategy.py``).

Every reference strategy field is CLASSIFIED (the full proto audit is the
module-level tables below):

- ``_CONSUMED``  — drives behavior here (mesh axes, ZeRO stage, AMP,
  recompute, gradient merge, pipeline, PS modes, LARS/LAMB, LocalSGD, DGC,
  fp16_allreduce, find_unused_parameters).
- ``_COLLAPSED`` — meaningful in the reference's NCCL/brpc/cuDNN runtime
  but satisfied BY CONSTRUCTION under XLA/TPU (the compiler fuses, schedules
  streams, and routes collectives hierarchically over ICI); accepted and
  stored so ported configs keep working, with the collapse reason on record.
- anything else — raises AttributeError at assignment, so a typo'd or
  genuinely unsupported knob can never be silently ignored (the VERDICT r2
  "unknown keys pass silently" failure mode).
"""
from __future__ import annotations

from typing import Any, Dict

# reference knobs that collapse into the XLA/TPU execution model; the value
# is the reason (also the user-facing documentation, via `explain`)
_COLLAPSED: Dict[str, str] = {
    "sync_nccl_allreduce": "XLA schedules collectives; no NCCL streams",
    "nccl_comm_num": "no NCCL communicators; ICI mesh is implicit",
    "use_hierarchical_allreduce": "XLA routes reductions hierarchically "
                                  "over ICI/DCN on its own",
    "hierarchical_allreduce_inter_nranks": "see use_hierarchical_allreduce",
    "sync_batch_norm": "use nn.SyncBatchNorm / mesh-axis BN explicitly",
    "fuse_all_reduce_ops": "XLA fuses collectives",
    "fuse_grad_size_in_MB": "XLA sizes fusion buffers",
    "fuse_grad_size_in_num": "XLA sizes fusion buffers",
    "fuse_grad_merge": "grad-merge accumulators fuse in XLA",
    "calc_comm_same_stream": "no stream distinction under XLA",
    "cudnn_exhaustive_search": "no cuDNN; XLA autotunes",
    "conv_workspace_size_limit": "no cuDNN workspaces",
    "cudnn_batchnorm_spatial_persistent": "no cuDNN",
    "without_graph_optimization": "graph passes are XLA's; not bypassable",
    "heter_ccl_mode": "single SPMD program; no heterogeneous CCL",
    "split_data": "DataLoader/DistributedBatchSampler own data splitting",
    "adam_d2sum": "server-side accessor detail; see ps accessors",
    "semi_auto": "sharding propagation is GSPMD's default behavior",
    "auto_search": "use auto_parallel.ParallelTuner explicitly",
    "build_strategy": "SSA-graph build options have no XLA analogue",
    "execution_strategy": "executor threads/iteration knobs collapse to jit",
    "gradient_scale_configs": "loss scaling lives in amp.GradScaler",
    "trainer_desc_configs": "no TrainerDesc proto; TrainStep is the trainer",
    "downpour_table_param": "tables configure via ps.SparseAccessorConfig",
    "fs_client_param": "no HDFS client; use filesystem paths",
    "qat": "use paddle_tpu.quantization directly",
    "qat_configs": "use paddle_tpu.quantization directly",
    "auto": "use auto_parallel.Engine / ParallelTuner",
    "elastic": "elastic membership lives in launch.elastic",
    "asp": "apply incubate.asp pruning masks explicitly",
    "tensor_parallel": "declare mp_degree in hybrid_configs instead",
    "tensor_parallel_configs": "declare mp_degree in hybrid_configs instead",
    "is_fl_ps_mode": "drive distributed.ps.coordinator explicitly",
    "with_coordinator": "drive distributed.ps.coordinator explicitly",
}

# accepted as fields but raising when ENABLED: not implemented, and
# pretending otherwise would silently train without the feature
_UNSUPPORTED_WHEN_TRUE = {
    "adaptive_localsgd": "use localsgd with an explicit k_steps schedule",
}


class DistributedStrategy:
    # fields this framework CONSUMES (set + read by fleet/TrainStep/PS)
    _CONSUMED = {
        "hybrid_configs", "sharding", "sharding_configs",
        "amp", "amp_configs", "recompute", "recompute_configs",
        "gradient_merge", "gradient_merge_configs",
        "pipeline", "pipeline_configs",
        "a_sync", "a_sync_configs",
        "find_unused_parameters",
        "lamb", "lamb_configs", "lars", "lars_configs",
        "localsgd", "localsgd_configs",
        "adaptive_localsgd_configs",
        "dgc", "dgc_configs",
        "fp16_allreduce",
        "mode",
    }

    def __init__(self):
        # mesh topology (reference hybrid_configs)
        self.hybrid_configs: Dict[str, int] = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sp_degree": 1, "ep_degree": 1,
        }
        # ZeRO stage 0-3 (reference sharding_configs / group_sharded levels)
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {"stage": 1}
        # AMP
        self.amp = False
        self.amp_configs: Dict[str, Any] = {"level": "O1", "dtype": "bfloat16",
                                            "init_loss_scaling": 2.0 ** 15}
        # recompute
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": [], "policy": None}
        # gradient merge / accumulation
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1, "avg": True}
        # pipeline
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {"accumulate_steps": 1,
                                                 "schedule_mode": "1F1B"}
        # parameter server mode (reference a_sync / a_sync_configs)
        self.a_sync = False
        self.a_sync_configs: Dict[str, Any] = {"k_steps": 0, "geo": False}
        self.find_unused_parameters = False
        self.lamb = False
        self.lamb_configs: Dict[str, Any] = {}
        # LARS (consumed: distributed_optimizer wraps Momentum into
        # LarsMomentum with these knobs)
        self.lars = False
        self.lars_configs: Dict[str, Any] = {
            "lars_coeff": 0.001, "lars_weight_decay": 0.0005,
            "epsilon": 1e-8}
        # LocalSGD (consumed: distributed_model returns a LocalSGDStep)
        self.localsgd = False
        self.localsgd_configs: Dict[str, Any] = {"k_steps": 4}
        self.adaptive_localsgd_configs: Dict[str, Any] = {"init_k_steps": 1}
        # deep gradient compression (consumed: distributed_optimizer wraps
        # Momentum into DGCMomentum — top-k sparsified, residual-corrected)
        self.dgc = False
        self.dgc_configs: Dict[str, Any] = {"rampup_begin_step": 0,
                                            "rampup_step": 1,
                                            "sparsity": [0.999]}
        # cast grads to fp16 for the reduction, restore after (consumed:
        # distributed_model installs the cast as a grad transform)
        self.fp16_allreduce = False
        self.mode = "collective"

    @property
    def sharding_stage(self) -> int:
        if not self.sharding:
            return 0
        return int(self.sharding_configs.get("stage", 1))

    def __setattr__(self, name, value):
        if name in _UNSUPPORTED_WHEN_TRUE and value:
            raise NotImplementedError(
                f"strategy.{name} is not implemented: "
                f"{_UNSUPPORTED_WHEN_TRUE[name]}")
        if name.startswith("_") or name in self._CONSUMED \
                or name in _COLLAPSED or name in _UNSUPPORTED_WHEN_TRUE:
            object.__setattr__(self, name, value)
            return
        raise AttributeError(
            f"DistributedStrategy has no field {name!r}: it is neither "
            f"consumed by this framework nor a documented collapsed-by-"
            f"design knob (see strategy.explain()). Refusing to silently "
            f"ignore it.")

    def __getattr__(self, name):
        # collapsed knobs read back their default-ish falsy value;
        # unsupported knobs read False (only truthy WRITES raise)
        if name in _COLLAPSED:
            return None
        if name in _UNSUPPORTED_WHEN_TRUE:
            return False
        raise AttributeError(name)

    @staticmethod
    def explain(name: str = None):
        """Why a reference knob is accepted-but-inert here; with no name,
        the whole collapsed-by-design table."""
        if name is None:
            return dict(_COLLAPSED)
        return _COLLAPSED.get(name)

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items()}
        return f"DistributedStrategy({fields})"
