"""Cross-trainer metric reduction.

Reference parity: ``python/paddle/distributed/fleet/metrics/metric.py``
(``sum``/``max``/``min``/``auc``/``mae``/``rmse``/``acc`` all-reduced over
trainers via the fleet util's Gloo/NCCL all_reduce). TPU-native: metric
state lives host-side as numpy; reduction rides whichever transport the job
already has —

- a live ``jax.distributed`` multi-process world: reduce on-device over the
  global device mesh (one tiny psum, ICI/DCN does the work);
- a launch KV store (``PADDLE_KV_ENDPOINT``): HTTP gather-reduce-broadcast,
  the TCPStore pattern — works between plain processes, no chips involved;
- neither: single-trainer identity.

All functions accept numpy arrays or scalars and return numpy.
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional

import numpy as np

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "acc"]


def _world() -> int:
    return int(os.environ.get("PADDLE_TRAINERS_NUM",
                              os.environ.get("WORLD_SIZE", "1")))


def _rank() -> int:
    return int(os.environ.get("PADDLE_TRAINER_ID",
                              os.environ.get("RANK", "0")))


def _jax_world_live() -> bool:
    import jax

    return jax.process_count() > 1


def _device_allreduce(value: np.ndarray, op: str) -> np.ndarray:
    """Reduce across processes through the global device world: each process
    contributes its local array on its first addressable device; a tiny
    jitted reduction over a 1-axis mesh spanning all devices returns the
    global result everywhere."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.asarray(jax.devices())
    mesh = Mesh(devs, ("metric",))
    # every process stacks its value on the leading axis; psum-style reduce
    stacked = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("metric")),
        np.repeat(value[None, ...], repeats=len(jax.local_devices()), axis=0),
        (len(devs),) + value.shape)
    red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[op]

    @jax.jit
    def reduce(x):
        # device copies within one process hold the same value; global sum
        # over-counts by local_device_count, so normalize for "sum"
        if op == "sum":
            return red(x, axis=0) / len(jax.local_devices())
        return red(x, axis=0)

    # tpu-lint: disable=R1(eager collective metric — delivering the reduced value to the host IS the operation)
    return np.asarray(jax.device_get(reduce(stacked)))


_kv_seq = 0  # in-process call counter; see namespace derivation below
_KV_KEY_TTL = 600.0  # metric keys are transient; lease them so the KV
                     # store can't grow unboundedly with per-step metrics


def _kv_allreduce(value: np.ndarray, op: str,
                  timeout: float = 120.0) -> np.ndarray:
    """TCPStore-style gather→reduce→broadcast over the launch KV server.

    Namespace: ``metrics/{job}/{pod generation}/{call #}``. The generation
    comes from ``PADDLE_MASTER`` (the coordinator address) — unique per pod
    incarnation and identical across its ranks — and the call counter is
    in-process, so an elastic restart resets every rank to call 0 together.
    (A counter persisted in the KV would desynchronize ranks whenever a pod
    died between increments, deadlocking all later reductions.)
    """
    global _kv_seq
    from ..launch.kv_server import KVClient

    kv = KVClient(os.environ["PADDLE_KV_ENDPOINT"])
    world, rank = _world(), _rank()
    gen = os.environ.get("PADDLE_MASTER",
                         os.environ.get("PADDLE_METRIC_GEN"))
    if gen is None:
        gen = "0"
        if not _kv_seq:
            logging.warning(
                "fleet.metrics: neither PADDLE_MASTER nor PADDLE_METRIC_GEN "
                "is set — the KV namespace is not incarnation-scoped, so a "
                "restarted trainer within %ss may read the previous run's "
                "leased metric keys. Run under paddle_tpu launch or set "
                "PADDLE_METRIC_GEN uniquely per run.", int(_KV_KEY_TTL))
    gen = gen.replace("/", "_").replace(":", "_")
    seq = _kv_seq
    _kv_seq += 1
    base = (f"metrics/{os.environ.get('PADDLE_JOB_ID', 'default')}"
            f"/{gen}/{seq}")
    kv.put(f"{base}/part/{rank}",
           json.dumps({"shape": list(value.shape),
                       "data": value.reshape(-1).tolist()}),
           ttl=_KV_KEY_TTL)
    if rank == 0:
        parts = []
        deadline = time.time() + timeout
        for r in range(world):
            raw = None
            while raw is None:
                raw = kv.get(f"{base}/part/{r}")
                if raw is None:
                    if time.time() > deadline:
                        raise TimeoutError(
                            f"metric allreduce: rank {r} never reported")
                    time.sleep(0.05)
            obj = json.loads(raw)
            parts.append(np.asarray(obj["data"]).reshape(obj["shape"]))
        fn = {"sum": np.sum, "max": np.max, "min": np.min}[op]
        out = fn(np.stack(parts), axis=0)
        kv.put(f"{base}/result",
               json.dumps({"shape": list(out.shape),
                           "data": out.reshape(-1).tolist()}),
               ttl=_KV_KEY_TTL)
        return out.astype(value.dtype)
    raw = kv.wait(f"{base}/result", timeout=timeout)
    obj = json.loads(raw)
    return np.asarray(obj["data"]).reshape(obj["shape"]).astype(value.dtype)


def _allreduce(value, op: str) -> np.ndarray:
    value = np.asarray(value, np.float64)
    scalar = value.ndim == 0
    value = np.atleast_1d(value)
    if _world() > 1:
        if _jax_world_live():
            out = _device_allreduce(value, op)
        elif "PADDLE_KV_ENDPOINT" in os.environ:
            out = _kv_allreduce(value, op)
        else:
            raise RuntimeError(
                "distributed metric reduction needs a jax.distributed world "
                "or PADDLE_KV_ENDPOINT (run under paddle_tpu launch)")
    else:
        out = value
    return out[0] if scalar else out


def sum(input, scope=None, util=None):  # noqa: A001 — reference name
    """Global sum over trainers (``fleet.metrics.metric.sum``)."""
    return _allreduce(input, "sum")


def max(input, scope=None, util=None):  # noqa: A001
    return _allreduce(input, "max")


def min(input, scope=None, util=None):  # noqa: A001
    return _allreduce(input, "min")


def auc(stat_pos, stat_neg, scope=None, util=None) -> float:
    """Global AUC from per-trainer histogram buckets — sums the bucket
    arrays across trainers, then runs the same trapezoid accumulation as
    :class:`paddle_tpu.metric.Auc` (reference ``metric.py`` ``auc``)."""
    stat_pos = _allreduce(np.asarray(stat_pos, np.float64), "sum")
    stat_neg = _allreduce(np.asarray(stat_neg, np.float64), "sum")
    tot_pos = tot_neg = area = 0.0
    for i in range(len(stat_pos) - 1, -1, -1):
        prev_pos, prev_neg = tot_pos, tot_neg
        tot_pos += float(stat_pos[i])
        tot_neg += float(stat_neg[i])
        area += abs(prev_neg - tot_neg) * (prev_pos + tot_pos) / 2.0
    denom = tot_pos * tot_neg
    return float(area / denom) if denom else 0.0


def mae(abserr_sum, total_ins_num, scope=None, util=None) -> float:
    """Global mean absolute error from (local abs-error sum, local count)."""
    s = _allreduce(np.asarray(abserr_sum, np.float64), "sum")
    n = _allreduce(np.asarray(total_ins_num, np.float64), "sum")
    return float(np.sum(s) / np.sum(n)) if np.sum(n) else 0.0


def rmse(sqrerr_sum, total_ins_num, scope=None, util=None) -> float:
    s = _allreduce(np.asarray(sqrerr_sum, np.float64), "sum")
    n = _allreduce(np.asarray(total_ins_num, np.float64), "sum")
    return float(np.sqrt(np.sum(s) / np.sum(n))) if np.sum(n) else 0.0


def acc(correct, total, scope=None, util=None) -> float:
    c = _allreduce(np.asarray(correct, np.float64), "sum")
    t = _allreduce(np.asarray(total, np.float64), "sum")
    return float(np.sum(c) / np.sum(t)) if np.sum(t) else 0.0
