"""Fleet — the high-level distributed facade.

Reference parity: ``python/paddle/distributed/fleet/fleet.py:98``
(``fleet.init`` / ``distributed_model`` / ``distributed_optimizer``) and
``DistributedStrategy`` (233-field protobuf,
``distributed_strategy.proto:305``). TPU-native: strategy fields that exist
to toggle hand-written comm rewrites (fuse_allreduce, sync_nccl, ...) are
obsolete; the surviving knobs configure the mesh (hybrid_configs), ZeRO
stage, AMP, and recompute, and ``distributed_model`` returns a
DistributedTrainStep factory bound to the mesh.
"""
from __future__ import annotations

from typing import Optional

from .. import env as _env
from ..mesh import HybridCommunicateGroup, get_mesh, init_mesh
from . import metrics  # noqa: F401  (fleet.metrics.sum/auc/... namespace)
from .strategy import DistributedStrategy

_fleet_state = {"strategy": None, "hcg": None, "initialized": False}


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None):
    """``fleet.init`` analogue: bootstrap processes + build the mesh from
    ``strategy.hybrid_configs`` (reference builds HybridCommunicateGroup from
    the same dict)."""
    strategy = strategy or DistributedStrategy()
    _env.init_parallel_env()
    hc = strategy.hybrid_configs
    shape = {}
    mapping = {"pp_degree": "pp", "dp_degree": "dp", "sharding_degree": "sdp",
               "mp_degree": "mp", "sp_degree": "sp", "ep_degree": "ep"}
    for key, axis in mapping.items():
        deg = hc.get(key, 1)
        if deg and deg != 1:
            shape[axis] = deg
    if not shape:
        shape = {"dp": -1}
    elif "dp" not in shape and hc.get("dp_degree", 1) == 1:
        # absorb remaining devices into dp
        import jax
        import numpy as np

        n = len(jax.devices())
        used = int(np.prod(list(shape.values())))
        if n % used == 0 and n // used > 1:
            shape["dp"] = n // used
    mesh = init_mesh(shape)
    _fleet_state.update(strategy=strategy, hcg=HybridCommunicateGroup(mesh),
                        initialized=True)
    # PS communicator mode (sync/async/geo), derived from
    # a_sync/a_sync_configs the way the_one_ps.py does — applied
    # UNCONDITIONALLY so a later plain init resets a prior async mode
    from ..ps import get_ps_context

    get_ps_context().configure_mode(strategy)
    return mesh


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return _fleet_state["hcg"]


def worker_index() -> int:
    return _env.get_rank()


def worker_num() -> int:
    return _env.get_world_size()


def is_first_worker() -> bool:
    return _env.get_rank() == 0


def barrier_worker():
    _env.barrier()


def distributed_model(model, optimizer=None, loss_fn=None, inputs_fn=None, **kw):
    """Wrap model+optimizer into a DistributedTrainStep configured from the
    active strategy (the reference dispatches to DataParallel /
    TensorParallel / PipelineParallel wrappers at ``fleet/model.py:126-165``;
    here one pjit step covers all of them via shardings)."""
    from ..shard import DistributedTrainStep

    strategy: DistributedStrategy = _fleet_state["strategy"] or DistributedStrategy()
    if strategy.localsgd:
        from ..parallel.localsgd import LocalSGDStep

        cfg = strategy.localsgd_configs or {}
        return LocalSGDStep(model, optimizer, loss_fn=loss_fn,
                            mesh=get_mesh(),
                            k_steps=int(cfg.get("k_steps", 4)),
                            inputs_fn=inputs_fn)
    stage = strategy.sharding_stage
    if strategy.gradient_merge and "grad_accum_steps" not in kw:
        cfg = strategy.gradient_merge_configs or {}
        kw["grad_accum_steps"] = int(cfg.get("k_steps", 1))
        kw["grad_accum_avg"] = bool(cfg.get("avg", True))
    if strategy.fp16_allreduce and "grad_transform" not in kw:
        # reference fp16_allreduce_optimizer: grads cross the wire in fp16.
        # Under GSPMD the reduction is implicit, so the numerically
        # equivalent move is casting grads to fp16 and back before the
        # update — same precision loss the reference accepts for half the
        # reduction bytes.
        import jax
        import jax.numpy as jnp

        kw["grad_transform"] = lambda grads: jax.tree.map(
            lambda g: g.astype(jnp.float16).astype(g.dtype)
            if g is not None else None, grads)
    return DistributedTrainStep(model, optimizer, loss_fn=loss_fn, inputs_fn=inputs_fn,
                                mesh=get_mesh(), sharding_stage=stage, **kw)


def distributed_optimizer(optimizer, strategy=None):
    """Mostly a pass-through — grad synchronization is GSPMD's job; ZeRO
    sharding is applied by DistributedTrainStep via opt-state specs. The
    rewrites kept from the reference's meta-optimizer stack:
    ``strategy.lars`` wraps Momentum into LarsMomentum (lars_optimizer.py)
    and ``strategy.dgc`` wraps it into DGCMomentum (dgc_optimizer.py —
    residual-corrected top-k gradient compression)."""
    if strategy is not None:
        _fleet_state["strategy"] = strategy
    strategy = _fleet_state["strategy"]
    if strategy is not None and strategy.dgc:
        from ...optimizer import DGCMomentum, Momentum

        if isinstance(optimizer, Momentum) and \
                not isinstance(optimizer, DGCMomentum):
            import logging

            cfg = strategy.dgc_configs or {}
            if optimizer.use_nesterov or optimizer.weight_decay:
                logging.getLogger(__name__).warning(
                    "strategy.dgc replaces Momentum's use_nesterov/"
                    "weight_decay: DGCMomentum applies neither")
            optimizer = DGCMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer.momentum,
                rampup_begin_step=cfg.get("rampup_begin_step", 0),
                rampup_step=cfg.get("rampup_step", 1),
                sparsity=cfg.get("sparsity", [0.999]),
                parameters=optimizer._parameters,
                grad_clip=optimizer.grad_clip,
                multi_precision=optimizer.multi_precision)
    if strategy is not None and strategy.lars:
        from ...optimizer import LarsMomentum, Momentum

        if isinstance(optimizer, Momentum) and \
                not isinstance(optimizer, LarsMomentum):
            import logging

            cfg = strategy.lars_configs or {}
            if optimizer.use_nesterov or optimizer.weight_decay:
                logging.getLogger(__name__).warning(
                    "strategy.lars replaces Momentum's "
                    "use_nesterov/weight_decay with LARS semantics "
                    "(lars_weight_decay folds into the trust ratio)")
            optimizer = LarsMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer.momentum,
                lars_coeff=cfg.get("lars_coeff", 0.001),
                lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
                epsilon=cfg.get("epsilon", 1e-8),
                exclude_from_weight_decay=cfg.get(
                    "exclude_from_weight_decay"),
                parameters=optimizer._parameters,
                multi_precision=optimizer.multi_precision,
                grad_clip=optimizer.grad_clip)
    return optimizer


# ------------------------------------------------------------------ PS mode
# Reference: fleet.init_server/init_worker/run_server/stop_worker
# (python/paddle/distributed/fleet/fleet.py) backed by the_one_ps.py. Here
# the PS is the in-proc local client (distributed/ps/__init__.py).
def init_server(*model_dir, **kw):
    from ..ps import get_ps_context

    ctx = get_ps_context()
    ctx.init_server()
    if model_dir:
        ctx.load_persistables(model_dir[0])
    return ctx


def run_server():
    from ..ps import get_ps_context

    return get_ps_context()


def init_worker():
    from ..ps import get_ps_context

    get_ps_context().init_worker()


def stop_worker():
    from ..ps import get_ps_context

    get_ps_context().stop_server()


def save_persistables(dirname: str, *a, **kw):
    from ..ps import get_ps_context

    get_ps_context().save_persistables(dirname)
