"""Composable pass registry.

Reference parity: ``python/paddle/distributed/passes`` — ``PassBase`` +
``register_pass`` + ``PassManager`` (``pass_base.py``), with the concrete
program-rewrite passes (``auto_parallel_amp.py``, ``_recompute.py``,
``_gradient_merge.py``, ``auto_parallel_fp16.py``, ...).

TPU-native shape: there is no ProgramDesc to rewrite — XLA owns the IR —
so a "pass" transforms the TRAINING-STEP CONSTRUCTION instead: each pass
edits a :class:`PassContext` (model, optimizer, grad-transform chain,
TrainStep kwargs) before the step compiles, and XLA performs the actual
graph rewriting the reference passes hand-coded. The registry gives the
reference's composability contract: passes are named, declare
compatibility, apply in order, and ``PassManager([...]).apply(ctx)``
builds the final step.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["PassBase", "PassContext", "PassManager", "register_pass",
           "new_pass", "list_passes"]

_REGISTRY: Dict[str, type] = {}


def register_pass(name: str):
    """Class decorator registering a PassBase subclass under ``name``
    (reference ``pass_base.py`` ``register_pass``)."""

    def deco(cls):
        if name in _REGISTRY:
            raise ValueError(f"pass {name!r} already registered")
        cls.name = name
        _REGISTRY[name] = cls
        return cls

    return deco


def new_pass(name: str, attrs: Optional[Dict[str, Any]] = None) -> "PassBase":
    if name not in _REGISTRY:
        raise ValueError(f"unknown pass {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**(attrs or {}))


def list_passes() -> List[str]:
    return sorted(_REGISTRY)


class PassContext:
    """What passes transform: the ingredients of a TrainStep."""

    def __init__(self, model, optimizer, loss_fn=None, **step_kwargs):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.step_kwargs: Dict[str, Any] = dict(step_kwargs)
        self.grad_transforms: List[Callable] = []
        self.applied: List[str] = []

    def chain_grad_transform(self) -> Optional[Callable]:
        if not self.grad_transforms:
            return None
        chain = list(self.grad_transforms)

        def run(grads):
            for t in chain:
                grads = t(grads)
            return grads

        return run

    def build_step(self, distributed: Optional[bool] = None):
        """Materialize the (Distributed)TrainStep with everything passes
        configured."""
        from ...framework.jit import TrainStep
        from ..mesh import get_mesh
        from ..shard import DistributedTrainStep

        kwargs = dict(self.step_kwargs)
        gt = self.chain_grad_transform()
        user_gt = kwargs.get("grad_transform")
        if gt is not None and user_gt is not None:
            # compose, never clobber: pass transforms model the reduction
            # path, the user's (e.g. clipping) applies after
            kwargs["grad_transform"] = lambda g: user_gt(gt(g))
        elif gt is not None:
            kwargs["grad_transform"] = gt
        if distributed is None:
            distributed = get_mesh() is not None
        cls = DistributedTrainStep if distributed else TrainStep
        if distributed:
            kwargs.setdefault("mesh", get_mesh())
        return cls(self.model, self.optimizer, loss_fn=self.loss_fn, **kwargs)


class PassBase:
    """One named transformation of a PassContext. Subclasses implement
    ``_apply_single_impl`` (reference naming) and may override
    ``_check_conflict`` to refuse bad compositions."""

    name = "base"

    def check_compatible(self, ctx: PassContext) -> bool:
        return self._check_conflict(ctx)

    def _check_conflict(self, ctx: PassContext) -> bool:
        return True

    def apply(self, ctx: PassContext) -> PassContext:
        if not self.check_compatible(ctx):
            raise ValueError(f"pass {self.name!r} incompatible with "
                             f"already-applied {ctx.applied}")
        self._apply_single_impl(ctx)
        ctx.applied.append(self.name)
        return ctx

    def _apply_single_impl(self, ctx: PassContext) -> None:
        raise NotImplementedError


class PassManager:
    """Ordered pass application (reference ``PassManager``)."""

    def __init__(self, passes: Sequence):
        self.passes = [p if isinstance(p, PassBase) else new_pass(p)
                       for p in passes]

    def apply(self, ctx: PassContext) -> PassContext:
        for p in self.passes:
            ctx = p.apply(ctx)
        return ctx

    @property
    def names(self) -> List[str]:
        return [p.name for p in self.passes]


# ----------------------------------------------------------- built-ins
@register_pass("amp")
class AmpPass(PassBase):
    """O1/O2 mixed precision (reference ``auto_parallel_amp.py`` /
    ``auto_parallel_fp16.py``): O2 casts the model and turns on f32 master
    weights in the optimizer."""

    def __init__(self, level: str = "O2", dtype: str = "bfloat16"):
        if level not in ("O1", "O2"):
            raise ValueError(f"amp level must be 'O1' or 'O2', got {level!r}")
        self.level = level
        self.dtype = dtype

    def _apply_single_impl(self, ctx: PassContext) -> None:
        from ...amp import decorate

        if self.level == "O2":
            ctx.model, ctx.optimizer = decorate(
                ctx.model, ctx.optimizer, level="O2", dtype=self.dtype)
            return
        # O1: the model's forward TRACES inside auto_cast, so white-listed
        # ops (F.linear / F.conv*) cast their operands to the low dtype;
        # the loss stays outside in f32. The wrap is an INSTANCE forward
        # override — ctx.model stays the same object, so later passes'
        # introspection (cfg/remat) and state_dict key paths are untouched;
        # the override is a module-level picklable descriptor-style object
        # bound to the instance (survives copy/pickle, unlike a closure
        # over a bound method).
        prior = ctx.model.__dict__.get("forward")  # instance-level only
        object.__setattr__(ctx.model, "forward",
                           _O1Forward(ctx.model, self.dtype, prior))


class _O1Forward:
    """Picklable per-instance forward override running the layer's forward
    under amp.auto_cast(O1). Composes with a pre-existing INSTANCE-level
    forward override when one exists (``inner`` holds it); re-binds
    through __reduce__, so deepcopy/pickle of the model reconstructs an
    override pointing at the COPY, not the original instance."""

    def __init__(self, layer, dtype, inner=None):
        self._layer = layer
        self._dtype = dtype
        self._inner = inner  # prior instance-level forward (or None)

    def __call__(self, *args, **kwargs):
        from ...amp import auto_cast

        with auto_cast(True, level="O1", dtype=self._dtype):
            if self._inner is not None:
                return self._inner(*args, **kwargs)
            return type(self._layer).forward(self._layer, *args, **kwargs)

    def __reduce__(self):
        return (_O1Forward, (self._layer, self._dtype, self._inner))


@register_pass("recompute")
class RecomputePass(PassBase):
    """Activation recompute (reference ``auto_parallel_recompute.py``):
    flips the model's recompute knobs where it exposes them (GPT-style
    ``cfg.use_recompute`` / pipeline ``remat``)."""

    def _apply_single_impl(self, ctx: PassContext) -> None:
        hit = False
        cfg = getattr(ctx.model, "cfg", None)
        if cfg is not None and hasattr(cfg, "use_recompute"):
            cfg.use_recompute = True
            hit = True
        for layer in getattr(ctx.model, "sublayers", lambda: [])():
            if hasattr(layer, "remat"):
                layer.remat = True
                hit = True
        if hasattr(ctx.model, "remat"):
            ctx.model.remat = True
            hit = True
        if not hit:
            raise ValueError(
                "recompute pass found no recompute-capable layer; wrap "
                "blocks with distributed.recompute(...) explicitly")


@register_pass("gradient_merge")
class GradientMergePass(PassBase):
    """k-step gradient accumulation (reference
    ``auto_parallel_gradient_merge.py``)."""

    def __init__(self, k_steps: int = 2, avg: bool = True):
        self.k_steps = int(k_steps)
        self.avg = bool(avg)

    def _apply_single_impl(self, ctx: PassContext) -> None:
        ctx.step_kwargs["grad_accum_steps"] = self.k_steps
        ctx.step_kwargs["grad_accum_avg"] = self.avg


@register_pass("fp16_allreduce")
class Fp16AllreducePass(PassBase):
    """Grads cross the (implicit GSPMD) reduction in fp16 (reference
    ``fp16_allreduce_optimizer.py``) — numerically, a cast-and-back grad
    transform."""

    def _apply_single_impl(self, ctx: PassContext) -> None:
        import jax
        import jax.numpy as jnp

        ctx.grad_transforms.append(lambda grads: jax.tree.map(
            lambda g: g.astype(jnp.float16).astype(g.dtype)
            if g is not None else None, grads))


@register_pass("dgc")
class DgcPass(PassBase):
    """Deep gradient compression (reference ``dgc_optimizer.py``): wraps a
    Momentum optimizer into DGCMomentum."""

    def __init__(self, rampup_begin_step: int = 0, rampup_step: int = 1,
                 sparsity: Sequence[float] = (0.999,)):
        self.rampup_begin_step = rampup_begin_step
        self.rampup_step = rampup_step
        self.sparsity = tuple(sparsity)

    def _check_conflict(self, ctx: PassContext) -> bool:
        return "lars" not in ctx.applied  # both rewrite the optimizer

    def _apply_single_impl(self, ctx: PassContext) -> None:
        from ...optimizer import DGCMomentum, Momentum

        opt = ctx.optimizer
        if not isinstance(opt, Momentum):
            raise ValueError("dgc pass needs a Momentum optimizer")
        if opt.weight_decay or opt.use_nesterov:
            raise ValueError(
                "dgc pass cannot preserve Momentum's weight_decay/"
                "use_nesterov (DGCMomentum applies neither); clear them "
                "or skip the pass")
        ctx.optimizer = DGCMomentum(
            learning_rate=opt._learning_rate, momentum=opt.momentum,
            rampup_begin_step=self.rampup_begin_step,
            rampup_step=self.rampup_step, sparsity=self.sparsity,
            parameters=opt._parameters, grad_clip=opt.grad_clip,
            multi_precision=opt.multi_precision)


@register_pass("lars")
class LarsPass(PassBase):
    """LARS meta-optimizer (reference ``lars_optimizer.py``)."""

    def __init__(self, lars_coeff: float = 0.001,
                 lars_weight_decay: float = 0.0005, epsilon: float = 1e-8):
        self.lars_coeff = lars_coeff
        self.lars_weight_decay = lars_weight_decay
        self.epsilon = epsilon

    def _check_conflict(self, ctx: PassContext) -> bool:
        return "dgc" not in ctx.applied

    def _apply_single_impl(self, ctx: PassContext) -> None:
        from ...optimizer import LarsMomentum, Momentum

        opt = ctx.optimizer
        if not isinstance(opt, Momentum):
            raise ValueError("lars pass needs a Momentum optimizer")
        if opt.weight_decay or opt.use_nesterov:
            raise ValueError(
                "lars pass replaces weight_decay/use_nesterov with LARS "
                "trust-ratio semantics; clear them (set lars_weight_decay "
                "instead) or skip the pass")
        ctx.optimizer = LarsMomentum(
            learning_rate=opt._learning_rate, momentum=opt.momentum,
            lars_coeff=self.lars_coeff,
            lars_weight_decay=self.lars_weight_decay,
            epsilon=self.epsilon, parameters=opt._parameters,
            grad_clip=opt.grad_clip, multi_precision=opt.multi_precision)
