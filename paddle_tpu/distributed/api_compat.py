"""Distributed API long tail: groups, P2P, env wrappers, sharding API.

Reference parity for the remaining ``paddle.distributed`` exports
(``python/paddle/distributed/__init__.py``): process groups
(``new_group``/``get_group``/``destroy_process_group``,
``communication/group.py``), point-to-point ops
(``send``/``recv``/``isend``/``irecv``/``P2POp``/``batch_isend_irecv``,
``communication/``), ``ParallelEnv``/``ParallelMode``, the public ZeRO
entry (``sharding/group_sharded.py`` ``group_sharded_parallel``), sparse
entry configs (``entry_attr.py``), and ``paddle.distributed.split``.

TPU-native collapses, stated per item below: a "group" is a logical view
over mesh axes or the RPC world; in-graph transport between SPMD shards
is ``lax.ppermute``-family (see ``collective.py``); the P2P functions
here are the EAGER cross-process path — real tensors over the named-RPC
layer (``rpc.py``, the MessageBus analogue), used for host-side
orchestration exactly like the reference's gloo-backed CPU P2P.
"""
from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence

import numpy as np

__all__ = [
    "Group", "new_group", "get_group", "destroy_process_group",
    "ParallelEnv", "ParallelMode", "send", "recv", "isend", "irecv",
    "P2POp", "batch_isend_irecv", "wait", "reduce", "scatter",
    "alltoall_single", "all_gather_object", "group_sharded_parallel",
    "save_group_sharded_model", "split", "CountFilterEntry",
    "ShowClickEntry", "ProbabilityEntry",
]


# ----------------------------------------------------------------- groups
@dataclass
class Group:
    """Logical process group (reference ``communication/group.py``): under
    GSPMD a group is a mesh axis; ranks are bookkeeping for ported code."""

    id: int
    ranks: List[int]
    axis: Optional[str] = None  # mesh axis this group maps onto

    @property
    def nranks(self) -> int:
        return len(self.ranks)

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank)


_groups = {}
_next_gid = [1]


def new_group(ranks: Optional[Sequence[int]] = None, backend: Optional[str] = None,
              timeout: Optional[int] = None, axis: Optional[str] = None) -> Group:
    from . import env

    if ranks is None:
        ranks = list(range(env.get_world_size()))
    g = Group(_next_gid[0], list(ranks), axis=axis)
    _groups[g.id] = g
    _next_gid[0] += 1
    return g


def get_group(id: int = 0) -> Optional[Group]:  # noqa: A002
    if id == 0 and 0 not in _groups:
        # the default world group exists implicitly (paddle group 0)
        from . import env

        _groups[0] = Group(0, list(range(env.get_world_size())), axis="dp")
    return _groups.get(id)


def destroy_process_group(group: Optional[Group] = None) -> None:
    if group is None:
        _groups.clear()
    else:
        _groups.pop(group.id, None)


class ParallelEnv:
    """Env-derived rank info (reference ``parallel.ParallelEnv``)."""

    @property
    def rank(self):
        from . import env

        return env.get_rank()

    @property
    def world_size(self):
        from . import env

        return env.get_world_size()

    # paddle aliases
    local_rank = rank
    nranks = world_size

    @property
    def device_id(self):
        return 0  # PJRT owns placement; one logical device per process


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


# -------------------------------------------------------------------- P2P
# Eager cross-process tensors over the named-RPC layer. Mailboxes are
# per-(src, tag) queues on the receiving process.
_mailbox: dict = {}
_mailbox_lock = threading.Lock()


def _box(src: int, tag: int) -> "queue.Queue":
    with _mailbox_lock:
        return _mailbox.setdefault((src, tag), queue.Queue())


def _deliver(src: int, tag: int, payload) -> int:
    _box(src, tag).put(payload)
    return 0


def _peer_name(rank: int) -> str:
    from . import rpc

    infos = rpc.get_all_worker_infos()
    return infos[rank].name


def _my_rank() -> int:
    """This process's rank: the RPC world's own registration when
    initialized (launch env vars are absent under bare init_rpc), else
    the launch env."""
    from . import env
    from .rpc import rpc as rpc_impl

    me = rpc_impl._state.get("self")
    return me.rank if me is not None else env.get_rank()


def send(tensor, dst=0, group=None, sync_op=True, tag: int = 0,
         timeout: float = 120.0):
    """Ship a host tensor to ``dst``'s mailbox (reference eager
    ``send``; requires ``rpc.init_rpc`` — the in-graph SPMD transport is
    ``collective.ppermute``/``shift_*``). Bounded by ``timeout`` like
    the matching :func:`recv` (tpu_lint R11: a dead peer must fail this
    caller at ITS deadline, not the transport's)."""
    from . import rpc

    payload = np.asarray(tensor)
    rpc.rpc_sync(_peer_name(dst), _deliver, (_my_rank(), tag, payload),
                 timeout=timeout)


def recv(tensor=None, src=0, group=None, sync_op=True, tag: int = 0,
         timeout: float = 120.0):
    """Blocking mailbox receive; returns the tensor. When ``tensor`` is a
    numpy buffer it is ALSO filled in place (paddle's buffer API); jax
    arrays are immutable — use the return value."""
    out = np.asarray(_box(src, tag).get(timeout=timeout))
    if isinstance(tensor, np.ndarray):
        np.copyto(tensor, out)
    return out


class _Req:
    """Async P2P handle; ``wait()`` returns the result or RE-RAISES the
    transport error (a swallowed daemon-thread failure would hand the
    pipeline None data)."""

    def __init__(self, fn):
        self._res = {}

        def run():
            try:
                self._res["v"] = fn()
            except BaseException as e:  # noqa: BLE001 — carried to wait()
                self._res["e"] = e

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def wait(self):
        self._t.join()
        if "e" in self._res:
            raise self._res["e"]
        return self._res.get("v")


def isend(tensor, dst=0, group=None, tag: int = 0) -> _Req:
    return _Req(lambda: send(tensor, dst, tag=tag))


def irecv(tensor=None, src=0, group=None, tag: int = 0) -> _Req:
    return _Req(lambda: recv(tensor, src, tag=tag))


@dataclass
class P2POp:
    op: Any              # dist.isend or dist.irecv
    tensor: Any
    peer: int
    group: Optional[Group] = None
    tag: int = 0


def batch_isend_irecv(p2p_op_list: Sequence[P2POp]) -> List[_Req]:
    """Launch a batch of isend/irecv (reference ``batch_isend_irecv`` —
    the PP handshake API). Sends go first so no peer blocks on a recv
    whose matching send is queued behind it."""
    ordered = sorted(p2p_op_list, key=lambda o: o.op is not isend)
    return [o.op(o.tensor, o.peer, o.group, tag=o.tag) for o in ordered]


def wait(tensor, group=None, use_calc_stream: bool = True):
    """Reference ``wait`` orders the calc stream behind the comm stream;
    XLA owns scheduling, so this is the identity (document-level no-op)."""
    return tensor


# ---------------------------------------------------- collectives (extra)
def reduce(tensor, dst=0, op=None, group=None):
    """SPMD reduce-to-one: psum, result kept on ``dst`` (zeros elsewhere,
    the reference's undefined-on-others contract made explicit)."""
    import jax.numpy as jnp

    from .collective import ReduceOp, all_reduce, axis_index

    summed = all_reduce(tensor, op=op or ReduceOp.SUM, group=group)
    keep = axis_index(group) == dst
    return jnp.where(keep, summed, jnp.zeros_like(summed))


def scatter(tensor, tensor_list=None, src=0, group=None, axis=0):
    """SPMD scatter. Paddle contract: ``tensor_list`` (on ``src``) is the
    INPUT, one chunk per rank; ``tensor`` is the output buffer. Shards are
    functional here, so the chunk is RETURNED (assign it; in-place fill of
    a traced buffer is not a thing under XLA). With ``tensor_list=None``
    the torch-style form chunks ``tensor`` itself along ``axis``."""
    import jax
    import jax.numpy as jnp

    from .collective import axis_index, axis_size_of, broadcast

    if tensor_list is not None:
        # per-rank chunks concatenated along ``axis`` slice back out exactly
        full = jnp.concatenate([jnp.asarray(t) for t in tensor_list],
                               axis=axis)
    else:
        full = jnp.asarray(tensor)
    full = broadcast(full, src=src, group=group)
    n = axis_size_of(group)
    chunk = full.shape[axis] // n
    return jax.lax.dynamic_slice_in_dim(full, axis_index(group) * chunk,
                                        chunk, axis=axis)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    from .collective import alltoall

    if in_split_sizes or out_split_sizes:
        raise NotImplementedError(
            "uneven alltoall splits need static shapes on TPU; pad to "
            "equal splits")
    out = alltoall(in_tensor, group=group)
    if isinstance(out_tensor, np.ndarray):
        np.copyto(out_tensor, np.asarray(out))  # paddle's output buffer
    return out


_ag_generation = [0]


def all_gather_object(object_list, obj, group=None,
                      timeout: float = 120.0):
    """Host-object all-gather (collective: every rank calls it): each
    rank mails its object to every peer, then drains one object per peer
    from its own mailbox. Generation counters keep successive gathers
    from mixing (all ranks call collectives in the same order, so the
    per-process counter agrees across the world). Single-process (no RPC
    world): identity."""
    from . import rpc
    from .rpc import rpc as rpc_impl

    if not rpc_impl._state.get("workers"):
        object_list.append(obj)
        return object_list
    infos = rpc.get_all_worker_infos()
    me = _my_rank()
    gen = _ag_generation[0]
    _ag_generation[0] += 1
    tag = ("allgather", gen)
    for info in infos:
        if info.rank != me:
            rpc.rpc_sync(info.name, _deliver, (me, tag, obj),
                         timeout=timeout)
    for info in infos:
        object_list.append(obj if info.rank == me
                           else _box(info.rank, tag).get(timeout=timeout))
    return object_list


# ------------------------------------------------------- sharding API
_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def group_sharded_parallel(model, optimizer, level: str, scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=None, segment_size=None,
                           sync_comm=False):
    """Public ZeRO entry (reference ``sharding/group_sharded.py``):
    tags the optimizer with the requested stage; the stage engages when
    the pair reaches ``DistributedTrainStep`` / ``fleet.distributed_model``
    (GSPMD implements the sharding — stage 1/2/3 = os / os_g / p_g_os)."""
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {sorted(_LEVELS)}")
    optimizer._group_sharded_stage = _LEVELS[level]
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None) -> None:
    """Reference gathers the sharded params to rank 0 before saving;
    GSPMD state is already addressable as full arrays — plain save."""
    import os

    from ..framework.io import save

    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None and hasattr(optimizer, "state_dict"):
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Reference ``paddle.distributed.split`` creates a parallel layer in
    the static global scope on the fly. That pattern has no functional
    analogue — use the layer library directly:
    ``distributed.parallel.mp_layers.ColumnParallelLinear`` /
    ``RowParallelLinear`` / ``VocabParallelEmbedding``."""
    raise NotImplementedError(split.__doc__)


# ------------------------------------------------------- PS entry configs
@dataclass
class CountFilterEntry:
    """Admit a sparse feature only after ``count`` shows (reference
    ``entry_attr.h`` CountFilterEntry); consumed by the PS accessor's
    show-threshold."""

    count: int = 1

    def accessor_kwargs(self) -> dict:
        return {"min_show_to_keep": float(self.count)}


@dataclass
class ShowClickEntry:
    """Names the show/click input slots driving the CTR accessor's
    show/click statistics (reference ShowClickEntry)."""

    show_name: str = "show"
    click_name: str = "click"

    def accessor_kwargs(self) -> dict:
        return {"show_name": self.show_name, "click_name": self.click_name}


@dataclass
class ProbabilityEntry:
    """Admit new features with the given probability (reference
    ProbabilityEntry)."""

    probability: float = 1.0

    def accessor_kwargs(self) -> dict:
        return {"admit_probability": float(self.probability)}
