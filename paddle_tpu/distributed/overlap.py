"""Bucketed gradient-reduction schedule + ZeRO weight-update sharding.

The serial ``DistributedTrainStep`` lets GSPMD place one fused gradient
all-reduce wherever it likes — in practice at the very end of the
backward pass, leaving the interconnect idle during compute and the
cores idle during reduction (MFU 0.41 flat since bench r02). This
module is the scheduling half of ROADMAP item 1:

- **Bucketing** (T3, arXiv:2401.16677; the reference's C++ ``Reducer``
  bucketed-fused-allreduce rebuilt as a GSPMD schedule): parameters are
  grouped into size-targeted buckets in *reverse-backward order* (the
  order their grads are produced), and each bucket's reduction is
  pinned as its own schedulable unit via ``with_sharding_constraint``
  placement plus an ``optimization_barrier`` dependency chain, so XLA's
  latency-hiding scheduler can issue bucket k's collective while bucket
  k+1's grads are still being computed — instead of fusing everything
  into one tail-of-step all-reduce.
- **Weight-update sharding** (arXiv:2004.13336, ZeRO via GSPMD
  arXiv:2105.04663): under ``sharding_stage >= 1`` the bucket target
  specs shard each grad over ``sdp`` (the constraint turns GSPMD's
  all-reduce into a reduce-scatter), the optimizer update runs on each
  replica's shard, and the existing param-spec constraint after the
  update is the all-gather — the replicated update stops being
  replicated work.

Everything here is deterministic host-side schedule construction plus
pure traced placement; the dim-picking rule is shared with
``shard.opt_state_specs`` so the param-update shard and the moment
shards can never drift apart.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["GradBucket", "build_buckets", "bucket_order",
           "shard_first_free_dim", "weight_update_specs",
           "bucketed_reduce"]

P = PartitionSpec


@dataclass(frozen=True)
class GradBucket:
    """One schedulable reduction unit: ``names`` in reverse-backward
    order, ``bytes`` the summed grad payload."""

    index: int
    names: Tuple[str, ...]
    bytes: int = 0

    def to_dict(self) -> dict:
        return {"bucket": self.index, "params": list(self.names),
                "bytes": int(self.bytes)}


def bucket_order(names: Sequence[str]) -> List[str]:
    """Reverse-backward order: grads are produced roughly in reverse
    declaration order during the backward pass, so the LAST declared
    parameter's bucket is ready (and its collective issuable) first."""
    return list(reversed(list(names)))


def build_buckets(sizes: Dict[str, int], bucket_bytes: int,
                  bucket_count: Optional[int] = None) -> List[GradBucket]:
    """Deterministic size-targeted bucket assignment.

    ``sizes`` maps parameter path -> grad payload bytes, in declaration
    order (a plain dict preserves it); buckets are cut greedily over
    :func:`bucket_order` with ``bucket_bytes`` as a CAP (the DDP Reducer
    semantic): a bucket closes before an item would push it past the
    target, so only a single oversized param ever exceeds it.
    ``bucket_count`` overrides the size target (the ``--buckets N``
    sweep knob): the target becomes ``ceil(total / N)``.
    """
    order = bucket_order(list(sizes))
    if not order:
        return []
    total = sum(int(sizes[n]) for n in order)
    if bucket_count is not None and bucket_count > 0:
        bucket_bytes = max(1, -(-total // int(bucket_count)))
    bucket_bytes = max(1, int(bucket_bytes))
    buckets: List[GradBucket] = []
    names: List[str] = []
    acc = 0
    for name in order:
        size = int(sizes[name])
        if names and acc + size > bucket_bytes:
            buckets.append(GradBucket(len(buckets), tuple(names), acc))
            names, acc = [], 0
        names.append(name)
        acc += size
    if names:
        buckets.append(GradBucket(len(buckets), tuple(names), acc))
    return buckets


def shard_first_free_dim(spec: Sequence, shape: Sequence[int], axis: str,
                         mesh) -> Tuple[PartitionSpec, bool]:
    """THE weight-update dim rule (shared by ``shard.opt_state_specs``
    and :func:`weight_update_specs`, so moments and params shard the
    same dim): add ``axis`` on the first unsharded dim it divides.
    Returns ``(spec, True)`` on success, ``(spec unchanged, False)``
    when the spec already uses ``axis`` (nothing to add), and
    ``(spec unchanged, False)`` via the caller's fallback accounting
    when no divisible dim exists."""
    spec = list(spec) + [None] * (len(shape) - len(list(spec)))
    used = set()
    for s in spec:
        if isinstance(s, (tuple, list)):
            used.update(s)
        elif s is not None:
            used.add(s)
    if axis in used:
        return PartitionSpec(*spec), True
    ax = mesh.shape[axis]
    for i in range(len(shape)):
        if spec[i] is None and shape[i] % ax == 0 and shape[i] >= ax:
            spec[i] = axis
            return PartitionSpec(*spec), True
    return PartitionSpec(*spec), False


def weight_update_specs(param_specs: Dict[str, PartitionSpec],
                        shapes: Dict[str, Sequence[int]], axis: Optional[str],
                        mesh,
                        on_fallback: Optional[Callable[[str], None]] = None
                        ) -> Dict[str, PartitionSpec]:
    """Per-param spec for the SHARDED region of the step — grads after
    reduce-scatter, params during ``optimizer.update`` — i.e. the param
    spec with ``axis`` added on the first divisible dim. A param with no
    divisible dim stays at its base spec (replicated update for that
    leaf) and is reported through ``on_fallback`` — the silently-
    replicated case the metrics registry now counts."""
    if not axis or axis not in mesh.shape:
        return dict(param_specs)
    out = {}
    for name, base in param_specs.items():
        shape = shapes[name]
        if len(shape) == 0:
            out[name] = base
            continue
        spec, ok = shard_first_free_dim(list(base), shape, axis, mesh)
        out[name] = spec
        if not ok and on_fallback is not None:
            on_fallback(name)
    return out


def bucketed_reduce(grads: Dict[str, jax.Array], buckets: List[GradBucket],
                    target_specs: Dict[str, PartitionSpec], mesh
                    ) -> Dict[str, jax.Array]:
    """Apply the bucketed reduction schedule inside a traced step.

    Bucket by bucket (reverse-backward order) each grad is pinned to its
    target spec — under ``sharding_stage >= 1`` that spec carries the
    ``sdp`` shard, so GSPMD lowers the psum into a reduce-scatter — and
    the bucket's leaves are fused into one schedulable unit with
    ``optimization_barrier``. A cross-bucket operand chain (bucket k+1's
    barrier takes a leaf of bucket k as an extra operand) gives XLA's
    latency-hiding scheduler the DDP-Reducer issue order: bucket k's
    collective may start as soon as its own grads exist, and must retire
    before bucket k+1's, instead of everything fusing into one tail
    all-reduce. Values pass through mathematically untouched — barriers
    and sharding constraints are placement, not arithmetic."""
    out = dict(grads)
    anchor = None
    for bucket in buckets:
        vals = [out[n] for n in bucket.names]
        if anchor is not None:
            *vals, _ = jax.lax.optimization_barrier((*vals, anchor))
        vals = [jax.lax.with_sharding_constraint(
                    v, NamedSharding(mesh, target_specs[n]))
                for n, v in zip(bucket.names, vals)]
        vals = list(jax.lax.optimization_barrier(tuple(vals)))
        anchor = vals[0]
        for n, v in zip(bucket.names, vals):
            out[n] = v
    return out
