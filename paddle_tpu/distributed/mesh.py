"""Device mesh & hybrid-parallel topology.

Reference parity: ``python/paddle/distributed/fleet/base/topology.py`` —
``CommunicateTopology:51`` (cartesian rank topology over [dp, pp, sharding,
mp]) and ``HybridCommunicateGroup:137`` (one NCCL group per axis). TPU-native:
the topology IS a ``jax.sharding.Mesh``; axes are named, groups are implicit
(a collective names its mesh axis), and XLA routes them over ICI/DCN. The
``HybridCommunicateGroup`` API surface is preserved so fleet-style code ports.

Canonical axis names:
  "dp"   data parallel            "pp"  pipeline stage
  "sdp"  sharded data parallel    "mp"  tensor (model) parallel
  (ZeRO / sharding axis)          "sp"  sequence/context parallel
                                  "ep"  expert parallel
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_current_mesh: List[Optional[Mesh]] = [None]

# standard axis order: outermost (slowest-varying, DCN-friendly) first.
# pp outermost (stage boundaries tolerate latency), then dp/sdp, then
# mp/sp innermost (latency-critical -> ICI neighbors).
AXIS_ORDER = ("pp", "dp", "sdp", "ep", "mp", "sp")


def init_mesh(shape: Dict[str, int] = None, devices=None, **axes) -> Mesh:
    """Create and install a named device mesh.

    init_mesh({"dp": 2, "mp": 4}) or init_mesh(dp=2, mp=4).
    Axis sizes must multiply to the device count (use -1 for "rest").
    """
    shape = dict(shape or {})
    shape.update(axes)
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    names, sizes = [], []
    for name in AXIS_ORDER:
        if name in shape:
            names.append(name)
            sizes.append(shape.pop(name))
    for name, size in shape.items():  # non-standard axis names, appended
        names.append(name)
        sizes.append(size)
    if not names:
        names, sizes = ["dp"], [n]
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n or n % total != 0:
        raise ValueError(f"mesh {dict(zip(names, sizes))} does not fit {n} devices")
    mesh = Mesh(devices.reshape(-1)[:total].reshape(sizes), tuple(names))
    _current_mesh[0] = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _current_mesh[0]


def set_mesh(mesh: Mesh):
    _current_mesh[0] = mesh


def require_mesh() -> Mesh:
    m = get_mesh()
    if m is None:
        raise RuntimeError("no device mesh installed; call init_mesh(...) first")
    return m


@contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    prev = _current_mesh[0]
    _current_mesh[0] = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _current_mesh[0] = prev


def sharding(*spec, mesh: Optional[Mesh] = None) -> NamedSharding:
    """NamedSharding helper: sharding("dp", None) etc."""
    m = mesh or require_mesh()
    return NamedSharding(m, PartitionSpec(*spec))


def axis_size(name: str, mesh: Optional[Mesh] = None) -> int:
    m = mesh or get_mesh()
    if m is None or name not in m.shape:
        return 1
    return m.shape[name]


class HybridCommunicateGroup:
    """Fleet topology facade over a Mesh (reference ``topology.py:137``)."""

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh or require_mesh()

    def _size(self, axis):
        return self.mesh.shape.get(axis, 1)

    # sizes ----------------------------------------------------------------
    def get_data_parallel_world_size(self):
        return self._size("dp")

    def get_model_parallel_world_size(self):
        return self._size("mp")

    def get_pipe_parallel_world_size(self):
        return self._size("pp")

    def get_sharding_parallel_world_size(self):
        return self._size("sdp")

    def get_expert_parallel_world_size(self):
        return self._size("ep")

    def get_sequence_parallel_world_size(self):
        return self._size("sp")

    # axis names (the "group" handle in this framework) --------------------
    def get_data_parallel_group(self):
        return "dp"

    def get_model_parallel_group(self):
        return "mp"

    def get_pipe_parallel_group(self):
        return "pp"

    def get_sharding_parallel_group(self):
        return "sdp"

    def topology(self):
        return dict(self.mesh.shape)

    def nranks(self):
        return int(np.prod(list(self.mesh.shape.values())))
