"""Builtin HTTP KV store for rendezvous.

Reference parity: ``python/paddle/distributed/launch/utils/kv_server.py``
(``KVServer`` used by ``Master.sync_peers``) and the C++ ``TCPStore``
(``paddle/fluid/distributed/store/tcp_store.h``) — wait/barrier semantics
over a tiny KV namespace. Same role here: exchange the JAX coordinator
address and worker endpoints before ``jax.distributed.initialize``.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..resilience import RetryPolicy, fault_point


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle_tpu_kv/1"

    def log_message(self, *a):  # quiet
        pass

    def _store(self) -> Dict[str, bytes]:
        return self.server.kv  # type: ignore[attr-defined]

    def _purge(self) -> None:
        """Drop expired lease keys (caller holds the lock)."""
        now = time.monotonic()
        expiry = self.server.expiry  # type: ignore[attr-defined]
        for k in [k for k, t in expiry.items() if t <= now]:
            expiry.pop(k, None)
            self._store().pop(k, None)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        ttl = self.headers.get("X-TTL")  # lease: expires unless re-PUT
        with self.server.lock:  # type: ignore[attr-defined]
            self._store()[self.path] = value
            if ttl is not None:
                self.server.expiry[self.path] = (  # type: ignore[attr-defined]
                    time.monotonic() + float(ttl))
            else:
                self.server.expiry.pop(self.path, None)  # type: ignore[attr-defined]
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        with self.server.lock:  # type: ignore[attr-defined]
            self._purge()
            if self.path == "/" or self.path.startswith("/?prefix="):
                prefix = (self.path.split("=", 1)[1]
                          if "=" in self.path else "")
                body = json.dumps(
                    {k: v.decode("utf-8", "replace")
                     for k, v in self._store().items()
                     if k.lstrip("/").startswith(prefix)}).encode()
                self.send_response(200)
                self.end_headers()
                self.wfile.write(body)
                return
            value = self._store().get(self.path)
        if value is None:
            self.send_response(404)
            self.end_headers()
        else:
            self.send_response(200)
            self.end_headers()
            self.wfile.write(value)

    def do_DELETE(self):
        with self.server.lock:  # type: ignore[attr-defined]
            self._store().pop(self.path, None)
        self.send_response(200)
        self.end_headers()


class KVServer:
    """Threaded HTTP KV server; ``with KVServer(port) as s: ...``."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.kv = {}          # type: ignore[attr-defined]
        self._httpd.expiry = {}      # type: ignore[attr-defined]
        self._httpd.lock = threading.Lock()  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class KVClient:
    """Client with the TCPStore-style wait/barrier helpers.

    ``retry``: optional :class:`~paddle_tpu.distributed.resilience.
    RetryPolicy` applied to every single-shot operation (put/get/list/
    delete) — transport failures and injected faults back off through it.
    ``retry=None`` (the default) keeps one-attempt semantics for callers
    that run their own policy around the client. Every operation passes a
    ``fault_point`` (``kv.put``/``kv.get``/``kv.list``/``kv.delete``), so a
    :class:`~paddle_tpu.distributed.resilience.FaultPlan` can drop, delay
    or crash any KV touch deterministically.

    ``timeout`` bounds each HTTP request — deadline-sensitive callers
    (elastic heartbeats, whose lease expires in seconds) pass a short one
    so a slow-but-alive store cannot stall an attempt past its budget.
    """

    def __init__(self, endpoint: str, retry: Optional[RetryPolicy] = None,
                 timeout: float = 10.0):
        if not endpoint.startswith("http"):
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.retry = retry
        self.timeout = float(timeout)

    def _op(self, fn, what: str):
        if self.retry is None:
            return fn()
        return self.retry.call(fn, what=what)

    def put(self, key: str, value: str, ttl: Optional[float] = None) -> None:
        """``ttl``: lease seconds — the key vanishes unless re-PUT within
        that window (etcd-lease analogue for elastic membership)."""
        def once():
            fault_point("kv.put")
            req = urllib.request.Request(
                f"{self.endpoint}/{key.lstrip('/')}",
                data=value.encode(), method="PUT")
            if ttl is not None:
                req.add_header("X-TTL", str(ttl))
            urllib.request.urlopen(req, timeout=self.timeout).read()
        self._op(once, f"kv put {key!r}")

    def list(self, prefix: str = "") -> Dict[str, str]:
        """Live keys under ``prefix`` (expired leases excluded)."""
        def once():
            fault_point("kv.list")
            with urllib.request.urlopen(
                    f"{self.endpoint}/?prefix={prefix.lstrip('/')}",
                    timeout=self.timeout) as r:
                return {k.lstrip("/"): v
                        for k, v in json.loads(r.read()).items()}
        return self._op(once, f"kv list {prefix!r}")

    def get(self, key: str) -> Optional[str]:
        def once():
            fault_point("kv.get")
            try:
                with urllib.request.urlopen(
                        f"{self.endpoint}/{key.lstrip('/')}",
                        timeout=self.timeout) as r:
                    return r.read().decode()
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return None
                raise
        return self._op(once, f"kv get {key!r}")

    def delete(self, key: str) -> None:
        def once():
            fault_point("kv.delete")
            req = urllib.request.Request(
                f"{self.endpoint}/{key.lstrip('/')}", method="DELETE")
            urllib.request.urlopen(req, timeout=self.timeout).read()
        self._op(once, f"kv delete {key!r}")

    def wait(self, key: str, timeout: float = 300.0,
             interval: float = 0.2) -> str:
        """Poll until ``key`` exists (transport failures retry too — the
        server may still be coming up on the other side of rendezvous)."""
        policy = RetryPolicy(deadline=timeout, base_delay=interval,
                             multiplier=1.0, max_delay=interval)
        try:
            return policy.until(lambda: self.get(key), what=f"kv key {key!r}")
        except TimeoutError:
            raise TimeoutError(f"kv wait timed out on {key!r}") from None

    def barrier(self, name: str, rank: int, world: int,
                timeout: float = 300.0, gen: int = 0) -> None:
        """All ranks put their mark, then wait for everyone. ``gen`` must
        differ across reuses of the same name (e.g. elastic restart
        attempts) so stale marks from a previous generation can't satisfy
        the new barrier."""
        self.put(f"barrier/{name}/{gen}/{rank}", "1")

        def arrived():
            ok = all(self.get(f"barrier/{name}/{gen}/{r}") is not None
                     for r in range(world))
            return True if ok else None

        policy = RetryPolicy(deadline=timeout, base_delay=0.2,
                             multiplier=1.0, max_delay=0.2)
        try:
            policy.until(arrived, what=f"barrier {name!r}")
        except TimeoutError:
            raise TimeoutError(
                f"barrier {name!r} (gen {gen}) timed out") from None
