"""Elastic membership over the launch KV store.

Reference parity: ``python/paddle/distributed/fleet/elastic/manager.py:127``
(``ElasticManager``: etcd lease per node, watch on the node directory,
world resize between ``--nnodes min:max``). TPU-native restatement: the
builtin HTTP KV store grows etcd-style TTL leases (``kv_server.py``), each
launcher heartbeats its node key, and membership IS the set of live lease
keys — no etcd dependency, same semantics:

- node loss    -> lease expires -> watchers see a smaller membership,
  terminate their pods and re-rendezvous at the new world size;
- node arrival -> new lease key -> watchers see a larger membership and
  resize up (scale-up), as long as max_nodes allows.

Workers resume from the latest AutoCheckpoint
(:mod:`paddle_tpu.distributed.checkpoint`), which re-slices sharded state
onto the new topology — the part the reference delegates to
``fleet.save/load`` + program re-build.
"""
from __future__ import annotations

import json
import threading
import time
from typing import List, Optional

from .kv_server import KVClient


class ElasticManager:
    """One per launcher process. ``node_id`` must be unique per launcher
    incarnation (a rejoining host gets a fresh id, so membership hashes
    never collide across generations)."""

    def __init__(self, kv_endpoint: str, job_id: str, node_id: str,
                 ttl: float = 6.0):
        self.kv = KVClient(kv_endpoint)
        self.job_id = job_id
        self.node_id = node_id
        self.ttl = ttl
        self._prefix = f"elastic/{job_id}/nodes/"
        self._key = f"{self._prefix}{node_id}"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------ lease lifecycle
    def register(self) -> None:
        """Write our lease and start the heartbeat thread."""
        self.kv.put(self._key, "1", ttl=self.ttl)
        self._thread = threading.Thread(target=self._heartbeat, daemon=True)
        self._thread.start()

    def _heartbeat(self) -> None:
        while not self._stop.wait(self.ttl / 3.0):
            try:
                self.kv.put(self._key, "1", ttl=self.ttl)
            except OSError:
                pass  # KV briefly unreachable; retry next tick

    def leave(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.ttl)
        try:
            self.kv.delete(self._key)
        except OSError:
            pass

    # ---------------------------------------------------------- membership
    def members(self) -> List[str]:
        return sorted(k[len(self._prefix):]
                      for k in self.kv.list(self._prefix))

    def wait_stable(self, min_nodes: int, max_nodes: int,
                    timeout: float = 300.0, settle: float = 1.0) -> List[str]:
        """Block until membership has >= min_nodes and hasn't changed for
        ``settle`` seconds (or has reached max_nodes) — the reference's
        pre-launch hold that lets stragglers join before ranks freeze.

        Returns the FULL membership (may exceed max_nodes): the caller
        takes ``members[:max_nodes]`` as the active set and keeps overflow
        nodes as spares, so every node computes the same view."""
        deadline = time.time() + timeout
        last, last_change = None, time.time()
        while time.time() < deadline:
            try:
                cur = self.members()
            except OSError:
                time.sleep(0.5)  # transient KV hiccup; keep polling
                continue
            if cur != last:
                last, last_change = cur, time.time()
            if len(cur) >= max_nodes:
                return cur
            if (len(cur) >= min_nodes
                    and time.time() - last_change >= settle):
                return cur
            time.sleep(0.2)
        raise TimeoutError(
            f"elastic rendezvous: {len(last or [])}/{min_nodes} nodes after "
            f"{timeout}s")

    def watch(self, baseline: List[str], interval: float = 1.0,
              stop: Optional[threading.Event] = None) -> List[str]:
        """Block until membership differs from ``baseline``; returns the new
        membership (the etcd watch loop, polled)."""
        while stop is None or not stop.is_set():
            time.sleep(interval)
            try:
                cur = self.members()
            except OSError:
                continue
            if cur != baseline:
                return cur
        return baseline

    # ---------------------------------------------------------- rendezvous
    def publish_coordinator(self, addr: str, members: List[str]) -> int:
        """Leader (lowest active member id) announces the JAX coordinator.
        Each publish bumps a monotonic generation so a *restart with
        unchanged membership* still produces a distinguishable value —
        followers matching only on the member list could otherwise grab the
        previous (dead) coordinator address. Returns the generation."""
        key = f"elastic/{self.job_id}/coord"
        raw = self.kv.get(key)
        gen = (json.loads(raw)["gen"] + 1) if raw else 1
        self.kv.put(key, json.dumps(
            {"addr": addr, "members": members, "gen": gen}))
        return gen

    def wait_coordinator(self, members: List[str], min_gen: int = 1,
                         timeout: float = 120.0) -> tuple:
        """Followers poll until a coordinator is published whose member list
        matches their view AND whose generation is >= ``min_gen`` (strictly
        newer than any coordinator this follower already used). Returns
        ``(addr, gen)``."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                raw = self.kv.get(f"elastic/{self.job_id}/coord")
            except OSError:
                raw = None  # transient KV hiccup
            if raw:
                data = json.loads(raw)
                if data["members"] == members and data.get("gen", 0) >= min_gen:
                    return data["addr"], data["gen"]
            time.sleep(0.2)
        raise TimeoutError("elastic: coordinator for current membership "
                           "never published")
