"""Elastic membership over the launch KV store.

Reference parity: ``python/paddle/distributed/fleet/elastic/manager.py:127``
(``ElasticManager``: etcd lease per node, watch on the node directory,
world resize between ``--nnodes min:max``). TPU-native restatement: the
builtin HTTP KV store grows etcd-style TTL leases (``kv_server.py``), each
launcher heartbeats its node key, and membership IS the set of live lease
keys — no etcd dependency, same semantics:

- node loss    -> lease expires -> watchers see a smaller membership,
  terminate their pods and re-rendezvous at the new world size;
- node arrival -> new lease key -> watchers see a larger membership and
  resize up (scale-up), as long as max_nodes allows.

Workers resume from the latest AutoCheckpoint
(:mod:`paddle_tpu.distributed.checkpoint`), which re-slices sharded state
onto the new topology — the part the reference delegates to
``fleet.save/load`` + program re-build.
"""
from __future__ import annotations

import json
import threading
import time
from typing import List, Optional

from ..resilience import RetryPolicy, fault_point
from .kv_server import KVClient


class ElasticManager:
    """One per launcher process. ``node_id`` must be unique per launcher
    incarnation (a rejoining host gets a fresh id, so membership hashes
    never collide across generations).

    Heartbeat health is OBSERVABLE: the thread never dies silently — any
    exception (transport or otherwise) is recorded in ``last_error`` and
    the tick keeps running; :meth:`is_healthy` reports whether a beat
    landed recently enough for our lease to still be alive, and the
    launcher polls it to warn before the rest of the cluster notices.
    """

    def __init__(self, kv_endpoint: str, job_id: str, node_id: str,
                 ttl: float = 6.0):
        # per-request timeout of ttl/4: two heartbeat attempts + backoff
        # always finish inside the lease TTL, so a slow-but-alive store
        # can never stall the refresh long enough to expire our own lease
        # (no fixed floor — it would break the invariant for small TTLs)
        self.kv = KVClient(kv_endpoint, timeout=max(0.05, ttl / 4.0))
        self.job_id = job_id
        self.node_id = node_id
        self.ttl = ttl
        self._prefix = f"elastic/{job_id}/nodes/"
        self._key = f"{self._prefix}{node_id}"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_error: Optional[BaseException] = None
        self._last_beat: Optional[float] = None  # monotonic, successful PUT
        # one tick = a couple of quick attempts; the outer loop is the
        # long-horizon retry, so a tick must never outlive its period
        self._beat_policy = RetryPolicy(max_attempts=2, base_delay=0.1,
                                        max_delay=0.5)

    # ------------------------------------------------------ lease lifecycle
    def register(self, timeout: Optional[float] = None) -> None:
        """Write our lease (retrying transport failures up to ``timeout``,
        default one TTL) and start the heartbeat thread."""
        policy = RetryPolicy(deadline=timeout or self.ttl, base_delay=0.2)
        policy.call(lambda: self.kv.put(self._key, "1", ttl=self.ttl),
                    what=f"elastic register {self.node_id}")
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(target=self._heartbeat, daemon=True)
        self._thread.start()

    def _beat_once(self) -> None:
        fault_point("elastic.heartbeat")
        self.kv.put(self._key, "1", ttl=self.ttl)

    def _heartbeat(self) -> None:
        while not self._stop.wait(self.ttl / 3.0):
            try:
                self._beat_policy.call(self._beat_once,
                                       what="elastic heartbeat")
            except BaseException as e:  # surfaced, never silently fatal
                self.last_error = e
            else:
                self.last_error = None
                self._last_beat = time.monotonic()

    def is_healthy(self) -> bool:
        """True while the heartbeat thread is alive and a beat landed
        within the lease TTL (i.e. our membership key cannot have expired
        for lack of refreshes)."""
        if self._thread is None or not self._thread.is_alive():
            return self._stop.is_set()  # post-leave() is not "unhealthy"
        return (self._last_beat is not None
                and time.monotonic() - self._last_beat < self.ttl)

    def leave(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.ttl)
        try:
            self.kv.delete(self._key)
        except OSError:
            pass

    # ---------------------------------------------------------- membership
    def members(self) -> List[str]:
        return sorted(k[len(self._prefix):]
                      for k in self.kv.list(self._prefix))

    def wait_stable(self, min_nodes: int, max_nodes: int,
                    timeout: float = 300.0, settle: float = 1.0) -> List[str]:
        """Block until membership has >= min_nodes and hasn't changed for
        ``settle`` seconds (or has reached max_nodes) — the reference's
        pre-launch hold that lets stragglers join before ranks freeze.

        Returns the FULL membership (may exceed max_nodes): the caller
        takes ``members[:max_nodes]`` as the active set and keeps overflow
        nodes as spares, so every node computes the same view."""
        state = {"last": None, "changed": time.monotonic()}

        def stable() -> Optional[List[str]]:
            cur = self.members()  # OSError retries through the policy
            if cur != state["last"]:
                state["last"], state["changed"] = cur, time.monotonic()
            if len(cur) >= max_nodes:
                return cur
            if (len(cur) >= min_nodes
                    and time.monotonic() - state["changed"] >= settle):
                return cur
            return None

        policy = RetryPolicy(deadline=timeout, base_delay=0.2,
                             multiplier=1.0, max_delay=0.5)
        try:
            return policy.until(stable, what="elastic rendezvous")
        except TimeoutError:
            raise TimeoutError(
                f"elastic rendezvous: {len(state['last'] or [])}/{min_nodes} "
                f"nodes after {timeout}s") from None

    def watch(self, baseline: List[str], interval: float = 1.0,
              stop: Optional[threading.Event] = None) -> List[str]:
        """Block until membership differs from ``baseline``; returns the new
        membership (the etcd watch loop, polled)."""
        while stop is None or not stop.is_set():
            time.sleep(interval)
            try:
                cur = self.members()
            except OSError:
                continue
            if cur != baseline:
                return cur
        return baseline

    # ---------------------------------------------------------- rendezvous
    def publish_coordinator(self, addr: str, members: List[str]) -> int:
        """Leader (lowest active member id) announces the JAX coordinator.
        Each publish bumps a monotonic generation so a *restart with
        unchanged membership* still produces a distinguishable value —
        followers matching only on the member list could otherwise grab the
        previous (dead) coordinator address. Returns the generation."""
        key = f"elastic/{self.job_id}/coord"
        raw = self.kv.get(key)
        gen = (json.loads(raw)["gen"] + 1) if raw else 1
        self.kv.put(key, json.dumps(
            {"addr": addr, "members": members, "gen": gen}))
        return gen

    def wait_coordinator(self, members: List[str], min_gen: int = 1,
                         timeout: float = 120.0) -> tuple:
        """Followers poll until a coordinator is published whose member list
        matches their view AND whose generation is >= ``min_gen`` (strictly
        newer than any coordinator this follower already used). Returns
        ``(addr, gen)``."""
        def published():
            raw = self.kv.get(f"elastic/{self.job_id}/coord")
            if raw:
                data = json.loads(raw)
                if (data["members"] == members
                        and data.get("gen", 0) >= min_gen):
                    return data["addr"], data["gen"]
            return None

        policy = RetryPolicy(deadline=timeout, base_delay=0.2,
                             multiplier=1.0, max_delay=0.5)
        try:
            return policy.until(published, what="elastic coordinator")
        except TimeoutError:
            raise TimeoutError("elastic: coordinator for current membership "
                               "never published") from None
