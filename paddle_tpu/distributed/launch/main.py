"""Launcher CLI: ``python -m paddle_tpu.distributed.launch train.py``.

Reference parity: ``python/paddle/distributed/launch/main.py:18`` +
``CollectiveController`` (``controllers/collective.py``) + elastic restart
(``fleet/elastic/manager.py:127``). TPU-native defaults: one worker per
host (JAX SPMD owns all local chips); ``--nproc_per_node`` exists for
CPU-simulated multi-process runs and debugging (each worker then gets a
slice of CPU devices via ``--devices-per-proc``).

Env contract handed to workers (superset of the reference's):
  PADDLE_TRAINER_ID / RANK, PADDLE_TRAINERS_NUM / WORLD_SIZE,
  PADDLE_MASTER (jax coordinator addr), PADDLE_KV_ENDPOINT.
"""
from __future__ import annotations

import argparse
import os
import socket
import sys
import time
from typing import List, Optional

from .job import Container, Pod
from .kv_server import KVClient, KVServer
from ..resilience import EXIT_PREEMPTED

# preemption exits restart for free (they checkpointed under their grace
# deadline and resume exactly where they left off), but a worker that
# "preempts" in a tight loop is a bug, not the scheduler — cap the free
# restarts so it cannot spin forever
_MAX_PREEMPT_RESTARTS = 16


def _note_preemption(args, status: int) -> bool:
    """True when ``status`` is a supervisor checkpoint-and-exit that should
    restart WITHOUT charging --max_restarts (bounded per launcher)."""
    if status != EXIT_PREEMPTED:
        return False
    count = getattr(args, "_preempt_restarts", 0) + 1
    args._preempt_restarts = count
    if count > _MAX_PREEMPT_RESTARTS:
        print(f"[launch] {count} preemption exits — treating further ones "
              f"as failures", flush=True)
        return False
    print(f"[launch] worker preempted (exit {status}); restarting to resume "
          f"from checkpoint ({count}/{_MAX_PREEMPT_RESTARTS} free restarts)",
          flush=True)
    return True


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu multi-process launcher")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count; a min:max range enables ELASTIC mode: "
                        "membership is lease-based via the KV store, node "
                        "loss/arrival resizes the world between min and max "
                        "and restarts workers (resume from AutoCheckpoint)")
    p.add_argument("--elastic_ttl", type=float, default=6.0,
                   help="elastic lease TTL seconds (heartbeat every ttl/3)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_LAUNCH_MASTER"),
                   help="kv server endpoint host:port (node 0 hosts it)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="workers per host (1 for real TPU; N for cpu sim)")
    p.add_argument("--devices_per_proc", type=int, default=0,
                   help="simulated CPU device count per worker (0 = off)")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic: relaunch failed pods up to N times")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("script", type=str, help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def _worker_env(args, local_rank: int, world: int, rank: int,
                coordinator: str, kv_endpoint: Optional[str],
                elastic: bool = False) -> dict:
    # workers must resolve the same paddle_tpu the launcher runs from
    # (python <script> does not add the launcher cwd to sys.path)
    import paddle_tpu

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(paddle_tpu.__file__)))
    py_path = os.environ.get("PYTHONPATH", "")
    if pkg_root not in py_path.split(os.pathsep):
        py_path = pkg_root + (os.pathsep + py_path if py_path else "")
    env = {
        "PYTHONPATH": py_path,
        "PADDLE_TRAINER_ID": str(rank),
        "RANK": str(rank),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "WORLD_SIZE": str(world),
        "PADDLE_MASTER": coordinator,
        "PADDLE_JOB_ID": args.job_id,
    }
    if kv_endpoint:
        env["PADDLE_KV_ENDPOINT"] = kv_endpoint
    if elastic:
        # the worker-side hint that THIS world size is provisional: meshes
        # should be rebuilt per incarnation from the newest checkpoint's
        # recorded topology (distributed.elastic_mesh.reshaped_mesh), so a
        # resume on N-k hosts reshard-restores instead of demanding the
        # exact mesh that wrote the snapshot
        env["PADDLE_ELASTIC"] = "1"
    if args.devices_per_proc:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{args.devices_per_proc}")
        # some PJRT plugins (axon TPU tunnel) pin jax_platforms via config
        # at sitecustomize time, overriding JAX_PLATFORMS — disable their
        # registration for cpu-sim workers
        env.setdefault("PALLAS_AXON_POOL_IPS", "")
    return env


def launch(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    parts = args.nnodes.split(":")
    min_nodes = int(parts[0])
    max_nodes = int(parts[1]) if len(parts) > 1 else min_nodes
    elastic = max_nodes > min_nodes
    nproc = args.nproc_per_node
    world = min_nodes * nproc
    if not elastic and args.node_rank >= min_nodes:
        raise ValueError(
            f"--node_rank {args.node_rank} out of range for --nnodes "
            f"{min_nodes}")
    if args.master and ":" not in args.master:
        raise ValueError(f"--master must be host:port, got {args.master!r}")

    kv_server = None
    kv_endpoint = None
    if elastic or min_nodes > 1:
        # node 0 hosts the KV store; everyone rendezvous through it
        if args.node_rank == 0:
            port = (int(args.master.rsplit(":", 1)[1])
                    if args.master else _free_port())
            kv_server = KVServer(port).start()
            host = socket.gethostbyname(socket.gethostname())
            kv_endpoint = args.master or f"{host}:{port}"
        else:
            if not args.master:
                raise ValueError("--master required for node_rank > 0")
            kv_endpoint = args.master

    def rendezvous(attempt: int) -> str:
        """Per-attempt coordinator exchange. Keys are generation-scoped so a
        relaunched pod never picks up a dead incarnation's address; peer
        nodes converge on the new attempt once their own pod fails and
        re-enters here (failure detection is per-node: a peer notices via
        its collectives erroring, then its launcher restarts into the same
        attempt key)."""
        if min_nodes == 1:
            return f"127.0.0.1:{_free_port()}"
        key = f"{args.job_id}/coordinator/a{attempt}"
        kv = KVClient(kv_endpoint)
        if args.node_rank == 0:
            host = socket.gethostbyname(socket.gethostname())
            kv.put(key, f"{host}:{_free_port()}")
        return kv.wait(key)

    if elastic:
        try:
            return _launch_elastic(args, min_nodes, max_nodes, nproc,
                                   kv_endpoint)
        finally:
            if kv_server:
                kv_server.stop()

    attempt = 0   # failures charged against --max_restarts
    gen = 0       # rendezvous generation: bumps on EVERY relaunch
    coordinator = rendezvous(gen)
    try:
        while True:
            pod = _build_pod(args, args.node_rank, world, nproc, coordinator,
                             kv_endpoint)
            pod.deploy()
            try:
                status = pod.join(watcher_interval=30.0)
            finally:
                pod.terminate()  # idempotent; closes log fds
            if status == 0:
                print(f"[launch] job {args.job_id} finished", flush=True)
                return 0
            gen += 1
            if _note_preemption(args, status):
                # graceful checkpoint-and-exit (supervisor EXIT_PREEMPTED):
                # restart to resume from the recorded step WITHOUT charging
                # --max_restarts (bounded by _MAX_PREEMPT_RESTARTS)
                time.sleep(1.0)
                coordinator = rendezvous(gen)
                continue
            attempt += 1
            if attempt > args.max_restarts:
                print(f"[launch] job {args.job_id} FAILED (exit {status}) "
                      f"after {attempt - 1} restarts", flush=True)
                return status
            # elastic restart: regenerate coordinator (old one is dead) and
            # go again — the ElasticManager relaunch path, minus etcd
            print(f"[launch] worker failed (exit {status}); restart "
                  f"{attempt}/{args.max_restarts}", flush=True)
            time.sleep(1.0)
            coordinator = rendezvous(gen)
    finally:
        if kv_server:
            kv_server.stop()


def _build_pod(args, node_rank: int, world: int, nproc: int,
               coordinator: str, kv_endpoint: Optional[str],
               elastic: bool = False) -> "Pod":
    """Shared by static and elastic paths so worker spawning can't drift."""
    pod = Pod()
    for local_rank in range(nproc):
        rank = node_rank * nproc + local_rank
        env = _worker_env(args, local_rank, world, rank, coordinator,
                          kv_endpoint, elastic=elastic)
        log = (os.path.join(args.log_dir, f"worker.{rank}.log")
               if args.log_dir else None)
        pod.add(Container(
            [sys.executable, "-u", args.script, *args.script_args],
            env, log))
    return pod


def _launch_elastic(args, min_nodes: int, max_nodes: int, nproc: int,
                    kv_endpoint: str) -> int:
    """Elastic supervision loop (``fleet/elastic/manager.py:127`` semantics
    over KV leases): membership -> ranks -> pod; a change in the ACTIVE set
    (first max_nodes members — later arrivals are spares) terminates the
    pod and re-enters rendezvous at the new world size; workers resume from
    AutoCheckpoint. Worker *failures* (not membership changes) count
    against --max_restarts."""
    import threading
    import uuid

    from .elastic import ElasticManager

    node_id = f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    mgr = ElasticManager(kv_endpoint, args.job_id, node_id,
                         ttl=args.elastic_ttl)
    mgr.register()
    restarts = 0
    coord_gen = 0  # newest coordinator generation we have used
    try:
        while True:
            try:
                done = mgr.kv.get(f"elastic/{args.job_id}/done")
            except OSError:
                done = None  # transient KV hiccup; proceed and retry later
            if done:
                # the job completed under another membership (we were a
                # spare, or raced the leader's exit) — don't resurrect it
                print(f"[launch] job {args.job_id} already finished",
                      flush=True)
                return 0
            members = mgr.wait_stable(min_nodes, max_nodes)
            active = members[:max_nodes]
            if node_id not in active:
                if node_id not in members:
                    raise RuntimeError("our own lease expired; clock stall?")
                # spare: hold until the active set has an opening
                print(f"[launch] standing by as spare "
                      f"({len(members)} nodes registered)", flush=True)
                while True:
                    members = mgr.watch(members, interval=args.elastic_ttl / 3)
                    if node_id in members[:max_nodes]:
                        break
                continue
            node_rank = active.index(node_id)
            world = len(active) * nproc
            host = socket.gethostbyname(socket.gethostname())
            if node_rank == 0:
                coordinator = f"{host}:{_free_port()}"
                coord_gen = mgr.publish_coordinator(coordinator, active)
            else:
                # gen must EXCEED the last one we used: a failure-restart
                # with unchanged membership needs a fresh coordinator, not
                # the dead one still in the KV
                coordinator, coord_gen = mgr.wait_coordinator(
                    active, min_gen=coord_gen + 1)
            print(f"[launch] elastic world: {len(active)} nodes x {nproc} "
                  f"procs (rank {node_rank})", flush=True)

            pod = _build_pod(args, node_rank, world, nproc, coordinator,
                             kv_endpoint, elastic=True)
            pod.deploy()

            # watch the ACTIVE set while the pod runs; on change, kill it
            resized = threading.Event()
            stop_watch = threading.Event()

            def health_watch():
                # surfaces silent heartbeat failure: if our own lease stops
                # refreshing, the rest of the cluster will resize us out in
                # one TTL — warn the operator BEFORE that happens
                while not stop_watch.wait(max(1.0, args.elastic_ttl / 2)):
                    if not mgr.is_healthy():
                        print(f"[launch] WARNING: elastic heartbeat "
                              f"unhealthy (last error: {mgr.last_error!r});"
                              f" lease may expire", flush=True)

            def watch():
                cur = members
                while not stop_watch.is_set():
                    cur = mgr.watch(cur, interval=args.elastic_ttl / 3.0,
                                    stop=stop_watch)
                    if stop_watch.is_set():
                        return
                    if cur[:max_nodes] != active:
                        resized.set()
                        pod.terminate()
                        return
                    # spare-only churn: keep watching, don't resize

            watcher = threading.Thread(target=watch, daemon=True)
            watcher.start()
            health = threading.Thread(target=health_watch, daemon=True)
            health.start()
            try:
                status = pod.join(watcher_interval=5.0)
            finally:
                stop_watch.set()
                pod.terminate()
            if resized.is_set():
                print("[launch] membership changed; resizing", flush=True)
                continue  # not a failure: re-rendezvous at new world
            if status == 0:
                print(f"[launch] job {args.job_id} finished", flush=True)
                if node_rank == 0:
                    # completion marker so spares don't resurrect the job.
                    # Leased (not permanent): it only needs to outlive the
                    # spares' watch wakeup, and a permanent key would make a
                    # REUSED job_id on a shared KV store return success
                    # without running anything.
                    try:
                        mgr.kv.put(f"elastic/{args.job_id}/done", "1",
                                   ttl=max(60.0, 10 * args.elastic_ttl))
                    except OSError:
                        pass
                return 0
            if _note_preemption(args, status):
                # self-reported checkpoint-and-exit: resume immediately,
                # no need to wait out a lease TTL diagnosing a dead peer
                continue
            # a worker failure is often the echo of a peer node dying: its
            # collectives error within seconds, long before the dead lease
            # expires (ttl). Wait one TTL and recheck membership BEFORE
            # charging max_restarts — peer loss must resize, not fail.
            time.sleep(args.elastic_ttl + 0.5)
            try:
                now_active = mgr.members()[:max_nodes]
            except OSError:
                now_active = active
            if now_active != active:
                print("[launch] membership changed; resizing", flush=True)
                continue
            restarts += 1
            if restarts > args.max_restarts:
                print(f"[launch] job {args.job_id} FAILED (exit {status}) "
                      f"after {restarts - 1} restarts", flush=True)
                return status
            print(f"[launch] worker failed (exit {status}); restart "
                  f"{restarts}/{args.max_restarts}", flush=True)
    finally:
        mgr.leave()


def main() -> None:
    sys.exit(launch())
