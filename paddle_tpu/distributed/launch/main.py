"""Launcher CLI: ``python -m paddle_tpu.distributed.launch train.py``.

Reference parity: ``python/paddle/distributed/launch/main.py:18`` +
``CollectiveController`` (``controllers/collective.py``) + elastic restart
(``fleet/elastic/manager.py:127``). TPU-native defaults: one worker per
host (JAX SPMD owns all local chips); ``--nproc_per_node`` exists for
CPU-simulated multi-process runs and debugging (each worker then gets a
slice of CPU devices via ``--devices-per-proc``).

Env contract handed to workers (superset of the reference's):
  PADDLE_TRAINER_ID / RANK, PADDLE_TRAINERS_NUM / WORLD_SIZE,
  PADDLE_MASTER (jax coordinator addr), PADDLE_KV_ENDPOINT.
"""
from __future__ import annotations

import argparse
import os
import socket
import sys
import time
from typing import List, Optional

from .job import Container, Pod
from .kv_server import KVClient, KVServer


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu multi-process launcher")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count (min:max range accepted; the job runs "
                        "at min — elastic world resizing not yet supported)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_LAUNCH_MASTER"),
                   help="kv server endpoint host:port (node 0 hosts it)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="workers per host (1 for real TPU; N for cpu sim)")
    p.add_argument("--devices_per_proc", type=int, default=0,
                   help="simulated CPU device count per worker (0 = off)")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic: relaunch failed pods up to N times")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("script", type=str, help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def _worker_env(args, local_rank: int, world: int, rank: int,
                coordinator: str, kv_endpoint: Optional[str]) -> dict:
    # workers must resolve the same paddle_tpu the launcher runs from
    # (python <script> does not add the launcher cwd to sys.path)
    import paddle_tpu

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(paddle_tpu.__file__)))
    py_path = os.environ.get("PYTHONPATH", "")
    if pkg_root not in py_path.split(os.pathsep):
        py_path = pkg_root + (os.pathsep + py_path if py_path else "")
    env = {
        "PYTHONPATH": py_path,
        "PADDLE_TRAINER_ID": str(rank),
        "RANK": str(rank),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "WORLD_SIZE": str(world),
        "PADDLE_MASTER": coordinator,
        "PADDLE_JOB_ID": args.job_id,
    }
    if kv_endpoint:
        env["PADDLE_KV_ENDPOINT"] = kv_endpoint
    if args.devices_per_proc:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{args.devices_per_proc}")
        # some PJRT plugins (axon TPU tunnel) pin jax_platforms via config
        # at sitecustomize time, overriding JAX_PLATFORMS — disable their
        # registration for cpu-sim workers
        env.setdefault("PALLAS_AXON_POOL_IPS", "")
    return env


def launch(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    min_nodes = int(args.nnodes.split(":")[0])
    nproc = args.nproc_per_node
    world = min_nodes * nproc
    if args.node_rank >= min_nodes:
        raise ValueError(
            f"--node_rank {args.node_rank} out of range for --nnodes "
            f"{min_nodes}")
    if args.master and ":" not in args.master:
        raise ValueError(f"--master must be host:port, got {args.master!r}")

    kv_server = None
    kv_endpoint = None
    if min_nodes > 1:
        # node 0 hosts the KV store; everyone rendezvous through it
        if args.node_rank == 0:
            port = (int(args.master.rsplit(":", 1)[1])
                    if args.master else _free_port())
            kv_server = KVServer(port).start()
            host = socket.gethostbyname(socket.gethostname())
            kv_endpoint = args.master or f"{host}:{port}"
        else:
            if not args.master:
                raise ValueError("--master required for node_rank > 0")
            kv_endpoint = args.master

    def rendezvous(attempt: int) -> str:
        """Per-attempt coordinator exchange. Keys are generation-scoped so a
        relaunched pod never picks up a dead incarnation's address; peer
        nodes converge on the new attempt once their own pod fails and
        re-enters here (failure detection is per-node: a peer notices via
        its collectives erroring, then its launcher restarts into the same
        attempt key)."""
        if min_nodes == 1:
            return f"127.0.0.1:{_free_port()}"
        key = f"{args.job_id}/coordinator/a{attempt}"
        kv = KVClient(kv_endpoint)
        if args.node_rank == 0:
            host = socket.gethostbyname(socket.gethostname())
            kv.put(key, f"{host}:{_free_port()}")
        return kv.wait(key)

    attempt = 0
    coordinator = rendezvous(attempt)
    try:
        while True:
            pod = Pod()
            for local_rank in range(nproc):
                rank = args.node_rank * nproc + local_rank
                env = _worker_env(args, local_rank, world, rank, coordinator,
                                  kv_endpoint)
                log = (os.path.join(args.log_dir, f"worker.{rank}.log")
                       if args.log_dir else None)
                pod.add(Container(
                    [sys.executable, "-u", args.script, *args.script_args],
                    env, log))
            pod.deploy()
            try:
                status = pod.join(watcher_interval=30.0)
            finally:
                pod.terminate()  # idempotent; closes log fds
            if status == 0:
                print(f"[launch] job {args.job_id} finished", flush=True)
                return 0
            attempt += 1
            if attempt > args.max_restarts:
                print(f"[launch] job {args.job_id} FAILED (exit {status}) "
                      f"after {attempt - 1} restarts", flush=True)
                return status
            # elastic restart: regenerate coordinator (old one is dead) and
            # go again — the ElasticManager relaunch path, minus etcd
            print(f"[launch] worker failed (exit {status}); restart "
                  f"{attempt}/{args.max_restarts}", flush=True)
            time.sleep(1.0)
            coordinator = rendezvous(attempt)
    finally:
        if kv_server:
            kv_server.stop()


def main() -> None:
    sys.exit(launch())
