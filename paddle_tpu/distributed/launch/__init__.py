"""paddle_tpu.distributed.launch — the multi-process job launcher.

Reference parity: ``python/paddle/distributed/launch/`` — ``main.py:18``
CLI, ``CollectiveController`` (``controllers/collective.py``), ``Master``
rendezvous with its builtin HTTP ``KVServer``
(``controllers/master.py:27``, ``utils/kv_server.py``), ``Job/Pod/
Container`` supervision (``job/``), ``Watcher`` (``controllers/
watcher.py``), and the etcd-backed ``ElasticManager``
(``fleet/elastic/manager.py:127``).

TPU-native shape: one worker process per *host* (JAX SPMD drives every
local chip from one process — no proc-per-GPU fan-out), coordination via
jax's distributed service whose address the launcher distributes through
its KV store; elastic restart re-executes workers with regenerated rank
env on failure.
"""
from .job import Container, Pod
from .kv_server import KVClient, KVServer
from .main import launch, main

__all__ = ["main", "launch", "KVServer", "KVClient", "Pod", "Container"]
