"""Process supervision: Container/Pod + watcher.

Reference parity: ``python/paddle/distributed/launch/job/`` (``Job/Pod/
Container`` — env construction, spawn, status poll, log handling) and the
GPU-util ``Watcher`` (``controllers/watcher.py``). One Container = one
worker process; a Pod is this host's set of containers.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, List, Optional


class Container:
    """One supervised worker process."""

    def __init__(self, cmd: List[str], env: Dict[str, str],
                 log_path: Optional[str] = None):
        self.cmd = list(cmd)
        self.env = dict(env)
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self._log_file = None
        self.restarts = 0

    def start(self) -> None:
        if self.log_path:
            os.makedirs(os.path.dirname(os.path.abspath(self.log_path)),
                        exist_ok=True)
            self._log_file = open(self.log_path, "ab", buffering=0)
            out = self._log_file
        else:
            out = None
        from ...utils.procutil import pdeathsig_preexec

        self.proc = subprocess.Popen(
            self.cmd, env={**os.environ, **self.env},
            stdout=out, stderr=subprocess.STDOUT if out else None,
            preexec_fn=pdeathsig_preexec())

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def exit_code(self) -> Optional[int]:
        return None if self.proc is None else self.proc.poll()

    def terminate(self, grace: float = 10.0) -> None:
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.terminate()
        try:
            self.proc.wait(grace)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()

    def close(self):
        if self._log_file:
            self._log_file.close()
            self._log_file = None


class Pod:
    """This host's containers + supervision loop (reference ``job/pod.py``)."""

    def __init__(self):
        self.containers: List[Container] = []

    def add(self, container: Container) -> None:
        self.containers.append(container)

    def deploy(self) -> None:
        for c in self.containers:
            c.start()

    def poll(self) -> Optional[int]:
        """None while all alive; first nonzero exit code on failure; 0 when
        every container exited cleanly."""
        codes = [c.exit_code for c in self.containers]
        for code in codes:
            if code not in (None, 0):
                return code
        if all(code == 0 for code in codes):
            return 0
        return None

    def join(self, poll_interval: float = 0.5,
             watcher_interval: float = 0.0) -> int:
        """Supervise until finish/failure. Returns final status code."""
        last_watch = time.time()
        while True:
            status = self.poll()
            if status is not None:
                if status != 0:
                    self.terminate()
                return status
            if watcher_interval and time.time() - last_watch > watcher_interval:
                alive = sum(c.alive for c in self.containers)
                print(f"[launch][watcher] {alive}/{len(self.containers)} "
                      f"workers alive", flush=True)
                last_watch = time.time()
            time.sleep(poll_interval)

    def terminate(self) -> None:
        for c in self.containers:
            c.terminate()
        for c in self.containers:
            c.close()
