"""Elastic mesh: rebuild a training topology on whatever capacity survives.

The self-healing layer (PR 5) treats preemption as "checkpoint and exit":
a run could only ever resume on the exact topology that wrote its
checkpoint. This module removes that restriction — the missing half of
"lose half the slice, keep training":

- :func:`plan_mesh_shape` reshapes a saved mesh onto a different device
  count. Axes whose size is SEMANTIC (``mp``/``sp``/``ep``/``pp`` —
  resizing them would change the partitioned program, not just the data
  distribution) are frozen; the data-parallel axes (``dp``/``sdp``)
  absorb the shrink or grow.
- :func:`reshaped_mesh` builds and installs that mesh for the current
  incarnation, reading the saved topology from the newest checkpoint's
  ``metadata.json`` (``checkpoint.mesh_info``). Old checkpoints without
  mesh metadata fall back to caller-supplied axes — i.e. the current
  same-topology path.
- :func:`rescale_batch` keeps the GLOBAL batch constant across a resize
  and returns the new per-replica slice, so the loss trajectory, the
  optimizer schedule, and the :class:`~paddle_tpu.io.cursor.DataCursor`
  all stay valid; ``DataCursor.rescale`` covers the deliberate
  global-batch-change case.

Restore itself is topology-agnostic already:
``checkpoint.load_state(shardings=...)`` streams per-shard reads re-sliced
to the new ``NamedSharding`` with bounded host memory (arXiv:2112.01075's
bounded-memory redistribution, realised through per-device callbacks
instead of collectives), so the only thing a shrunk/regrown worker must do
differently is build its mesh through :func:`reshaped_mesh` before
constructing the train step. ``TrainingSupervisor.restore`` then reshard-
restores the newest complete checkpoint and reports the resize.

Reference parity: the reference's elastic manager
(``fleet/elastic/manager.py``) resizes the WORLD but reuses
``fleet.save/load`` re-slicing for state; GSPMD (arXiv:2105.04663) is the
sharding substrate that makes the re-slice a metadata operation here.
"""
from __future__ import annotations

import math
import os
from typing import Dict, Optional, Sequence

import numpy as np

import jax

from . import checkpoint as _ckpt
from .mesh import init_mesh

__all__ = [
    "FROZEN_AXES", "plan_mesh_shape", "reshaped_mesh", "rescale_batch",
    "is_elastic",
]

# axes that partition the PROGRAM (tensor/sequence/expert/pipeline
# parallel): a resize must preserve them — shrinking "mp" would change
# every layer's shard shapes and the math itself, not just how many data
# replicas run. Only the data axes scale.
FROZEN_AXES = ("mp", "sp", "ep", "pp")
_PRIMARY_DATA_AXES = ("dp", "sdp")


def plan_mesh_shape(saved_axes: Dict[str, int], n_devices: int,
                    frozen: Sequence[str] = FROZEN_AXES) -> Dict[str, int]:
    """Reshape ``saved_axes`` (a ``{axis: size}`` mesh shape) onto
    ``n_devices`` devices.

    Frozen axes keep their exact size — ``n_devices`` must be divisible by
    their product, otherwise the surviving capacity cannot host the
    partitioned program and a :class:`ValueError` says so. The remaining
    (data) axes are rescaled to absorb the change: the primary data axis
    (``dp``, else ``sdp``, else the first non-frozen axis) takes whatever
    the others leave, and every other data axis is shrunk to
    ``gcd(old_size, remaining)`` so the product always lands exactly on
    ``n_devices`` — a deterministic plan both the shrink and the re-grow
    side compute identically.

    >>> plan_mesh_shape({"dp": 4, "mp": 2}, 4)
    {'dp': 2, 'mp': 2}
    >>> plan_mesh_shape({"dp": 2, "sdp": 2, "mp": 2}, 4)
    {'dp': 1, 'sdp': 2, 'mp': 2}
    """
    if n_devices < 1:
        raise ValueError(f"cannot build a mesh on {n_devices} devices")
    saved = {str(k): int(v) for k, v in dict(saved_axes).items()}
    out: Dict[str, int] = dict(saved)
    frozen_present = {k: v for k, v in saved.items() if k in frozen}
    frozen_prod = int(np.prod(list(frozen_present.values()))) \
        if frozen_present else 1
    if n_devices % frozen_prod != 0:
        raise ValueError(
            f"elastic resize impossible: frozen axes {frozen_present} need "
            f"a multiple of {frozen_prod} devices, got {n_devices} — the "
            f"surviving capacity cannot host the model-parallel layout "
            f"(restore onto >= {frozen_prod} devices, or retrain with a "
            f"smaller mp/pp degree)")
    remaining = n_devices // frozen_prod
    data_axes = [k for k in saved if k not in frozen_present]
    primary = next((a for a in _PRIMARY_DATA_AXES if a in data_axes),
                   data_axes[0] if data_axes else None)
    for k in data_axes:
        if k == primary:
            continue
        out[k] = math.gcd(saved[k], remaining)
        remaining //= out[k]
    if primary is not None:
        out[primary] = remaining
    elif remaining > 1:
        # a fully model-parallel mesh grown onto more devices: the extra
        # capacity becomes data parallelism
        out = {"dp": remaining, **out}
    return out


def _resolve_checkpoint_dir(path: Optional[str]) -> Optional[str]:
    """Accept either a concrete ``step_N`` checkpoint directory or an
    AutoCheckpoint root; returns the directory whose metadata to read."""
    if path is None:
        return None
    if os.path.exists(os.path.join(path, _ckpt._METADATA)):
        return path
    # cheap pick (verify=False): only the mesh RECORD is read here; the
    # actual restore re-validates through latest_checkpoint(verify=True)
    return _ckpt.latest_checkpoint(path, verify=False)


def reshaped_mesh(checkpoint_dir: Optional[str] = None,
                  default_axes: Optional[Dict[str, int]] = None,
                  devices=None,
                  frozen: Sequence[str] = FROZEN_AXES):
    """Build AND install (``init_mesh``) the mesh for this incarnation:
    the topology recorded in ``checkpoint_dir`` (a ``step_N`` dir or an
    AutoCheckpoint root), reshaped via :func:`plan_mesh_shape` onto the
    live device count.

    ``default_axes`` is the fresh-start/old-checkpoint fallback (no
    checkpoint yet, or one written before mesh metadata existed): its
    shape is planned onto the live devices the same way, so a worker can
    unconditionally call ``reshaped_mesh(root, default_axes={"dp": -1,
    "mp": 2})`` at startup — first launch, resume, shrink, and re-grow all
    take the same line. ``-1`` in ``default_axes`` means "the rest", as in
    ``init_mesh``.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    info = _ckpt.mesh_info(_resolve_checkpoint_dir(checkpoint_dir)) \
        if checkpoint_dir is not None else None
    if info is not None and info.get("axes"):
        shape = plan_mesh_shape(info["axes"], devs.size, frozen)
    else:
        shape = dict(default_axes or {"dp": devs.size})
        if -1 in shape.values():
            known = int(np.prod([s for s in shape.values() if s != -1]))
            shape = {k: (devs.size // known if v == -1 else v)
                     for k, v in shape.items()}
        shape = plan_mesh_shape(shape, devs.size, frozen)
    return init_mesh(shape, devices=devs)


def rescale_batch(global_batch: int, axes: Dict[str, int],
                  frozen: Sequence[str] = FROZEN_AXES) -> int:
    """Per-replica batch after an elastic resize.

    The GLOBAL batch stays constant across shrink/grow — that is what
    keeps the loss trajectory, the LR schedule, and the data cursor's
    batch accounting valid — so each data replica (the product of every
    non-frozen mesh axis) takes a larger or smaller slice. Raises :class:`ValueError` when the global
    batch does not divide the new replica count (the caller must then pad
    the batch or pick a compatible capacity; silently changing the global
    batch would corrupt the resumed trajectory).
    """
    # every non-frozen axis is a data axis (the same definition
    # plan_mesh_shape scales by), not just the canonical dp/sdp names —
    # a caller that planned with a custom `frozen` set must pass the
    # same set here or the replica count disagrees with the plan
    data = {a: int(s) for a, s in dict(axes).items() if a not in frozen}
    replicas = int(np.prod(list(data.values()))) if data else 1
    if global_batch % max(1, replicas) != 0:
        raise ValueError(
            f"global batch {global_batch} does not divide across "
            f"{replicas} data replicas ({data}); keep the global batch "
            f"divisible by every world size the job may shrink to, or "
            f"rescale the cursor with DataCursor.rescale")
    return global_batch // max(1, replicas)


def is_elastic() -> bool:
    """True when this worker was started by ``distributed.launch`` in
    elastic mode (``--nnodes min:max``) — the hint that meshes should be
    built through :func:`reshaped_mesh` rather than a fixed shape."""
    return os.environ.get("PADDLE_ELASTIC", "") == "1"
