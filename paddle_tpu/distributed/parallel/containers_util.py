"""Helpers for pipeline model surgery."""
from __future__ import annotations

from typing import List, Sequence, Tuple


def split_uniform_blocks(layers: Sequence) -> Tuple[List[int], List[int], List[int]]:
    """Find the longest run of same-class layers (the pipelined blocks);
    everything before runs pre-pipeline, everything after runs post."""
    if not layers:
        return [], [], []
    best_start, best_len = 0, 1
    i = 0
    n = len(layers)
    while i < n:
        j = i
        while j + 1 < n and type(layers[j + 1]) is type(layers[i]):
            j += 1
        if j - i + 1 > best_len:
            best_start, best_len = i, j - i + 1
        i = j + 1
    if best_len < 2:
        return list(range(n)), [], []
    head = list(range(best_start))
    blocks = list(range(best_start, best_start + best_len))
    tail = list(range(best_start + best_len, n))
    return head, blocks, tail
