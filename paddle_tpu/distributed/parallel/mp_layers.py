"""Tensor (model) parallel layers.

Reference parity: ``python/paddle/distributed/fleet/layers/mpu/mp_layers.py`` —
``VocabParallelEmbedding:37``, ``ColumnParallelLinear:175``,
``RowParallelLinear:334``, ``ParallelCrossEntropy:500`` — plus the CUDA
kernels ``c_embedding_op.cu`` and ``c_softmax_with_cross_entropy_op.cu``.

TPU-native: these layers do NOT issue collectives. They declare weight
shardings over the "mp" mesh axis and constrain activation shardings; GSPMD
derives the identity/allreduce pattern the reference hand-writes
(``_c_identity``/``_mp_allreduce`` in mp_ops.py). Math and parameter layout
are identical to the single-device layers, so checkpoints port across mesh
shapes by re-sharding, not re-slicing files.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn import functional as F
from ...nn.initializer import Constant, Normal, XavierUniform
from ...nn.layer import Layer
from ..mesh import get_mesh, sharding


def _constrain(x, *spec):
    """with_sharding_constraint if a mesh with the axes exists; no-op
    otherwise (single-device tests). The spec is (batch, ..., feature);
    middle dims are padded/truncated to match the input rank, so the same
    layer code covers [B, F] and [B, L, F] inputs."""
    mesh = get_mesh()
    if mesh is None:
        return x
    used = []
    for s in spec:
        if isinstance(s, (list, tuple)):
            used.extend(s)
        elif s is not None:
            used.append(s)
    if any(a not in mesh.shape for a in used):
        return x
    spec = list(spec)
    if len(spec) != x.ndim:
        if len(spec) >= 2 and x.ndim >= 2:
            spec = [spec[0]] + [None] * (x.ndim - 2) + [spec[-1]]
        else:
            return x
    return jax.lax.with_sharding_constraint(x, sharding(*spec, mesh=mesh))


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dimension sharded over "mp"."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=Normal(0.0, 0.02) if weight_attr is None else None)
        self.set_param_sharding("weight", ("mp", None))

    def forward(self, x):
        # global-index gather on a vocab-sharded table: GSPMD emits the
        # masked-lookup + psum the reference implements in c_embedding_op.cu
        out = jnp.take(self.weight, jnp.asarray(x), axis=0)
        return _constrain(out, "dp", None, None)


class ColumnParallelLinear(Layer):
    """Linear with out_features split over "mp" (weight [in, out/mp] per
    shard). ``gather_output=False`` keeps activations mp-sharded for a
    following RowParallelLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform() if weight_attr is None else None)
        self.set_param_sharding("weight", (None, "mp"))
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.set_param_sharding("bias", ("mp",))
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(out, "dp", None, None)
        return _constrain(out, "dp", None, "mp")


class RowParallelLinear(Layer):
    """Linear with in_features split over "mp" (weight [in/mp, out] per
    shard); GSPMD inserts the output psum at the sharded-contraction."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform() if weight_attr is None else None)
        self.set_param_sharding("weight", ("mp", None))
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, "dp", None, "mp")
        out = jnp.matmul(x, self.weight)  # contraction over mp-sharded dim -> psum
        if self.bias is not None:
            out = out + self.bias
        return _constrain(out, "dp", None, None)


class ParallelCrossEntropy(Layer):
    """Softmax CE over class-dim-sharded logits (reference
    ``c_softmax_with_cross_entropy``): with GSPMD the standard log-softmax
    reduction over the sharded axis compiles to the same two-allreduce
    pattern (max + sumexp)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        logits = _constrain(jnp.asarray(input), "dp", None, "mp")
        return F.cross_entropy(logits, label, reduction="none",
                               ignore_index=self.ignore_index)


def parallel_matmul(x, weight, transpose_y=False, tensor_parallel_output=True):
    """Utility for logit projection with a vocab-sharded embedding weight
    (tied-embeddings path in GPT)."""
    out = jnp.matmul(x, jnp.swapaxes(weight, -1, -2) if transpose_y else weight)
    if tensor_parallel_output:
        return _constrain(out, "dp", None, "mp")
    return _constrain(out, "dp", None, None)
