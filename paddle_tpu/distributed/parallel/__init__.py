from . import (localsgd, moe, mp_layers, pipeline, recompute,  # noqa: F401
               sequence_parallel)
from .data_parallel import DataParallel  # noqa: F401
