from . import moe, mp_layers, pipeline, recompute, sequence_parallel  # noqa: F401
