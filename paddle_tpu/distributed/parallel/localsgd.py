"""LocalSGD: per-replica local steps with periodic parameter averaging.

Reference parity: ``fleet/meta_optimizers/localsgd_optimizer.py`` (skip the
per-step grad all-reduce; every ``k_steps`` broadcast-average the weights)
— the comm-efficient data-parallel mode for slow interconnects.

TPU-native restatement: in SPMD there is one program, so "replicas with
different weights" become parameters STACKED on a leading axis sharded over
the dp mesh axis (per-device memory is still one replica). Each step runs
the local update inside ``shard_map`` — gradients are computed from the
local batch shard only, with NO cross-replica psum — and on every k-th step
the replicas' parameters are ``pmean``-ed over the axis. One ICI collective
per k steps instead of per step.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...framework.jax_compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ...nn.layer import Layer, buffer_state, functional_call, param_state
from ..mesh import require_mesh

__all__ = ["LocalSGDStep"]


class LocalSGDStep:
    """Drop-in alternative to ``DistributedTrainStep`` for the localsgd
    strategy (``DistributedStrategy.localsgd`` +
    ``localsgd_configs={"k_steps": k}``).

    Stages buffers as replicated constants (running-stat updates inside
    localsgd replicas are not threaded; use stateless norms).
    """

    def __init__(self, model: Layer, optimizer, loss_fn: Callable,
                 mesh=None, k_steps: int = 4, axis: str = "dp",
                 inputs_fn: Optional[Callable] = None):
        from ...framework.jit import resolve_inputs_fn

        self.mesh = mesh or require_mesh()
        if axis not in self.mesh.shape:
            raise ValueError(f"mesh has no {axis!r} axis")
        self.axis = axis
        self.dp = self.mesh.shape[axis]
        self.k_steps = int(k_steps)
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.inputs_fn = resolve_inputs_fn(inputs_fn, loss_fn)

        params = param_state(model)
        opt_state = optimizer.init(params)

        def stack(t):
            t = jnp.asarray(t)
            return jax.device_put(
                jnp.broadcast_to(t[None], (self.dp,) + t.shape),
                NamedSharding(self.mesh, P(axis, *([None] * t.ndim))))

        self.params = jax.tree.map(stack, params)
        self.opt_state = jax.tree.map(stack, opt_state)
        self.buffers = {k: jax.device_put(np.asarray(v),
                                          NamedSharding(self.mesh, P()))
                        for k, v in buffer_state(model).items()}
        self._t = 0
        self._compiled = jax.jit(self._step, donate_argnums=(0, 1),
                                 static_argnames=("sync",))

    # ------------------------------------------------------------------
    def _step(self, params_st, opt_st, batch, sync):
        axis = self.axis
        pspec = jax.tree.map(lambda _: P(axis), params_st)
        ospec = jax.tree.map(lambda _: P(axis), opt_st)
        bspec = jax.tree.map(
            lambda b: P(axis, *([None] * (jnp.asarray(b).ndim - 1))), batch)

        def local(p_st, o_st, b):
            p = jax.tree.map(lambda a: a[0], p_st)
            o = jax.tree.map(lambda a: a[0], o_st)

            def loss_of(pp):
                inputs = self.inputs_fn(b)
                if not isinstance(inputs, (tuple, list)):
                    inputs = (inputs,)
                out, _ = functional_call(self.model, pp, self.buffers,
                                         *inputs)
                return self.loss_fn(out, b)

            loss, grads = jax.value_and_grad(loss_of)(p)
            new_p, new_o = self.optimizer.update(grads, o, p)
            if sync:
                new_p = jax.tree.map(lambda a: lax.pmean(a, axis), new_p)
            loss = lax.pmean(loss, axis)
            return (jax.tree.map(lambda a: a[None], new_p),
                    jax.tree.map(lambda a: a[None], new_o), loss)

        fn = shard_map(local, mesh=self.mesh,
                       in_specs=(pspec, ospec, bspec),
                       out_specs=(pspec, ospec, P()), check_vma=False)
        return fn(params_st, opt_st, batch)

    def __call__(self, batch):
        """One local step (global batch sharded over the dp axis); every
        ``k_steps``-th call also averages the replicas."""
        sync = (self._t + 1) % self.k_steps == 0
        self._t += 1
        self.params, self.opt_state, loss = self._compiled(
            self.params, self.opt_state, batch, sync=sync)
        return loss

    # ------------------------------------------------------------------
    def replica_params(self):
        """The stacked [dp, ...] parameter pytree (replicas diverge between
        syncs; equal right after one)."""
        return self.params

    def averaged_params(self):
        """Consensus parameters (mean over replicas) — what you save."""
        return jax.tree.map(lambda a: jnp.mean(a, axis=0), self.params)

    def sync_to_model(self):
        for k, v in self.averaged_params().items():
            self.model._set_by_path(k, v)
        return self.model
