"""Activation recompute (reference:
``python/paddle/distributed/fleet/recompute/recompute.py`` —
``RecomputeFunction:224`` PyLayer saving RNG state + inputs and replaying
forward in backward; ``recompute_sequential:497``).

TPU-native: ``jax.checkpoint`` (remat) is the same trade expressed to the
compiler; RNG replay is automatic because layer randomness is functional
(keys are inputs). Policies map to jax.checkpoint_policies.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax

from ...nn.layer import Layer, buffer_state, functional_call, param_state

POLICIES = {
    None: None,
    "full": None,  # recompute everything
    "save_dots": jax.checkpoint_policies.checkpoint_dots,
    "save_dots_no_batch": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    "save_nothing": jax.checkpoint_policies.nothing_saveable,
    "save_anything": jax.checkpoint_policies.everything_saveable,
}


def recompute(function: Callable, *args, policy: Optional[str] = None,
              preserve_rng_state: bool = True, use_reentrant: bool = True, **kwargs):
    """``paddle.distributed.fleet.utils.recompute`` analogue."""
    pol = POLICIES.get(policy, policy)
    fn = jax.checkpoint(function, policy=pol) if pol is not None else jax.checkpoint(function)
    return fn(*args, **kwargs)


def recompute_wrap(function: Callable, policy: Optional[str] = None) -> Callable:
    pol = POLICIES.get(policy, policy)
    if pol is None:
        return jax.checkpoint(function)
    return jax.checkpoint(function, policy=pol)


def recompute_sequential(ctx: dict, functions, *args):
    """Segmented sequential recompute (reference ``recompute_sequential:497``):
    splits a Sequential into ``segments`` chunks, rematerializing each."""
    segments = int(ctx.get("segments", 1))
    layers = list(functions)
    seg_size = max(len(layers) // max(segments, 1), 1)
    out = args
    for start in range(0, len(layers), seg_size):
        chunk = layers[start:start + seg_size]

        def run_chunk(*xs, _chunk=tuple(chunk)):
            y = xs
            for f in _chunk:
                y = f(*y) if isinstance(y, tuple) else f(y)
                if not isinstance(y, tuple):
                    y = (y,)
            return y[0] if len(y) == 1 else y

        out = recompute(run_chunk, *(out if isinstance(out, tuple) else (out,)))
        if not isinstance(out, tuple):
            out = (out,)
    return out[0] if isinstance(out, tuple) and len(out) == 1 else out


class RecomputeLayer(Layer):
    """Wrap a sublayer so its forward is rematerialized in backward."""

    def __init__(self, inner: Layer, policy: Optional[str] = None):
        super().__init__()
        self.inner = inner
        self._policy = policy

    def forward(self, *args, **kwargs):
        inner = self.inner

        def run(params, buffers, *xs):
            out, new_buf = functional_call(inner, params, buffers, *xs)
            return out, new_buf

        pol = POLICIES.get(self._policy, self._policy)
        wrapped = jax.checkpoint(run, policy=pol) if pol is not None else jax.checkpoint(run)
        out, new_buf = wrapped(param_state(inner), buffer_state(inner), *args)
        for k, v in new_buf.items():
            inner._set_by_path(k, v)
        return out
