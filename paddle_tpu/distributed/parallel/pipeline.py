"""Pipeline parallelism.

Reference parity: ``fleet/meta_parallel/pipeline_parallel.py`` (1F1B python
scheduler ``forward_backward_pipeline:117`` driving NCCL P2P,
``PipelineParallelWithInterleave:461`` virtual stages), model surgery
``parallel_layers/pp_layers.py`` (``LayerDesc:56``, ``SegmentLayers:92``,
``PipelineLayer:208``), and the ``SendRecvMeta`` shape handshake.

TPU-native redesign: there is no multi-process scheduler to write. All "pp"
ranks execute ONE SPMD program; stage weights are stacked on a leading
layer axis sharded over "pp"; the microbatch schedule is a ``lax.scan``
whose carried activation rotates around the ring via ``ppermute`` (ICI
neighbor-hop). Autodiff through the scan generates the reverse-order
backward schedule — the hand-written ``backward_step`` machinery of the
reference falls out of ``jax.grad``.

Memory model (the 1F1B property): with ``remat=True`` the stage body is
``jax.checkpoint``-ed, so in-flight *internal* activations are O(1)
microbatches per stage regardless of ``num_micro`` — strictly better than
1F1B's O(pp) stash (only the per-microbatch stage *boundary* tensors are
carried, which any schedule must hold). See
``tests/test_pipeline.py::test_pipeline_memory_bounded`` for the compiled
HBM assertion.

Interleaved virtual stages (reference ``PipelineParallelWithInterleave``):
``num_virtual_stages=v`` gives each device v non-contiguous layer chunks
(device d owns global stages d, pp+d, 2*pp+d, ...). Microbatches run in
depth-first bursts of ``pp``: within one scan a burst crosses all ``v*pp``
virtual stages, each tick advancing one ring hop and selecting the chunk
``(t - d) // pp`` locally — conflict-free, one microbatch per device per
tick.

The shape handshake (``SendRecvMeta``) disappears: shapes are static.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ...framework.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from ...nn.layer import Layer, buffer_state, functional_call, param_state
from ..mesh import require_mesh


# ------------------------------------------------------- model surgery API
class LayerDesc:
    """Deferred layer constructor (reference ``pp_layers.py:56``)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-shared layer across stages (tied embeddings). In the SPMD
    design shared weights live outside the stacked stage params and are
    visible to every rank, so no grad-sync group is needed
    (reference builds a comm group per shared key)."""

    def __init__(self, key, layer_cls, *args, forward_func=None, shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layer descs into ``num_parts`` stages (reference
    ``pp_layers.py:92``): uniform, or proportional to parameter count, or a
    user-provided ``seg_method`` list of boundaries."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        if isinstance(self.method, (list, tuple)):
            assert len(self.method) == self.num_parts + 1
            return list(self.method)
        if self.method == "uniform":
            base = n // self.num_parts
            extra = n % self.num_parts
            bounds = [0]
            for i in range(self.num_parts):
                bounds.append(bounds[-1] + base + (1 if i < extra else 0))
            return bounds
        if self.method.startswith("layer:"):
            # segment only layers whose class name matches; others attach to
            # the nearest boundary (transformer-block segmentation)
            name = self.method.split(":", 1)[1]
            idxs = [i for i, d in enumerate(self.descs)
                    if getattr(d.layer_cls, "__name__", "") == name]
            if len(idxs) < self.num_parts:
                raise ValueError(
                    f"seg_method {self.method!r} matched {len(idxs)} layers, "
                    f"fewer than num_parts={self.num_parts}")
            per = len(idxs) // self.num_parts
            bounds = [0]
            for i in range(1, self.num_parts):
                bounds.append(idxs[i * per])
            bounds.append(n)
            return bounds
        raise ValueError(f"unknown seg method {self.method}")


# --------------------------------------------------------- SPMD pipelining
def _stack_params(layers: Sequence[Layer], order: Sequence[int]):
    """Stack homogeneous layers' params along a leading axis in ``order``."""
    states = [param_state(l) for l in layers]
    keys = list(states[0].keys())
    for s in states:
        assert list(s.keys()) == keys, "pipeline stages must be homogeneous"
    return {k: jnp.stack([states[i][k] for i in order]) for k in keys}


def _virtual_order(num_layers: int, pp: int, v: int) -> List[int]:
    """Stack order for interleaved virtual stages: device d's shard (stack
    rows [d*L/pp, (d+1)*L/pp)) holds its v chunks contiguously — chunk j of
    device d is global stage j*pp + d (reference interleave layout)."""
    lps = num_layers // (pp * v)  # layers per chunk
    order = []
    for d in range(pp):
        for j in range(v):
            g = j * pp + d  # global stage index
            order.extend(range(g * lps, (g + 1) * lps))
    return order


class PipelineStagedModule(Layer):
    """N homogeneous blocks executed as a "pp"-sharded pipeline.

    Holds the blocks' parameters stacked on a leading [num_layers] axis with
    sharding ("pp", ...). ``forward(x)`` consumes a full batch, internally
    splits it into ``num_micro`` microbatches and runs the ring schedule.
    With no mesh or pp=1 it degrades to a plain scan over layers (single-chip
    correctness path — loss parity with the distributed run is the
    ``TestDistBase`` pattern from SURVEY §4).

    ``num_virtual_stages`` > 1 enables the interleaved schedule (see module
    docstring).
    """

    def __init__(self, block_fn_layer: Layer, num_layers: int, num_micro: int = 1,
                 remat: bool = True, block_factory: Optional[Callable[[], Layer]] = None,
                 num_virtual_stages: int = 1):
        """``block_factory`` (e.g. a LayerDesc.build_layer) constructs each
        block with its own initializer draws; without it, blocks are deep
        copies of the template (identical initial weights, torch-deepcopy
        semantics).

        Blocks MAY hold buffers (BatchNorm running stats etc.): buffers are
        stacked on the same pp-sharded leading axis as params and threaded
        through the schedule — each microbatch's update lands in sequence,
        like the reference's per-microbatch BN updates."""
        super().__init__()
        # the template executes with stacked slices swapped in — its own
        # params must NOT register (they'd be dead weights), so bypass
        # __setattr__'s sublayer routing
        object.__setattr__(self, "template", block_fn_layer)
        self.num_layers = num_layers
        self.num_micro = num_micro
        self.remat = remat
        self.num_virtual_stages = int(num_virtual_stages)
        import copy

        if block_factory is not None:
            blocks = [block_fn_layer] + [block_factory() for _ in range(num_layers - 1)]
        else:
            blocks = [block_fn_layer] + [copy.deepcopy(block_fn_layer)
                                         for _ in range(num_layers - 1)]
        # stack rows are laid out so each pp shard holds its virtual chunks
        # contiguously; identity when v == 1
        self._order = list(range(num_layers))
        pp = _pp_size()
        if self.num_virtual_stages > 1 and pp > 1:
            if num_layers % (pp * self.num_virtual_stages):
                raise ValueError(
                    f"num_layers ({num_layers}) must divide pp*virtual "
                    f"({pp}*{self.num_virtual_stages})")
            self._order = _virtual_order(num_layers, pp, self.num_virtual_stages)
        stacked = _stack_params(blocks, self._order)
        for k, v in stacked.items():
            path = f"stacked__{k.replace('.', '__')}"
            self.add_parameter(path, v)
            self.set_param_sharding(path, ("pp",) + (None,) * (v.ndim - 1))
        self._stacked_keys = list(stacked.keys())
        # buffers stack exactly like params (rows in self._order)
        buf_states = [buffer_state(b) for b in blocks]
        self._stacked_buf_keys = list(buf_states[0].keys())
        for k in self._stacked_buf_keys:
            path = f"stackedbuf__{k.replace('.', '__')}"
            self.register_buffer(path, jnp.stack(
                [buf_states[i][k] for i in self._order]))

    def _stacked(self):
        return {k: self._parameters[f"stacked__{k.replace('.', '__')}"]
                for k in self._stacked_keys}

    def _stacked_bufs(self):
        return {k: self._buffers[f"stackedbuf__{k.replace('.', '__')}"]
                for k in self._stacked_buf_keys}

    def _write_stacked_bufs(self, bufs: Dict[str, Any]) -> None:
        for k, v in bufs.items():
            self._buffers[f"stackedbuf__{k.replace('.', '__')}"] = v

    def _apply_block(self, layer_params: Dict[str, Any],
                     layer_bufs: Dict[str, Any], x):
        """Run one block; returns (out, new_layer_bufs)."""
        tmpl = self.template

        def run(p, b, xx):
            return functional_call(tmpl, p, b, xx)

        if self.remat:
            run = jax.checkpoint(run)
        return run(layer_params, layer_bufs, x)

    def forward(self, x):
        mesh = require_mesh() if _has_pp() else None
        stacked = self._stacked()
        bufs = self._stacked_bufs()
        if mesh is None or mesh.shape.get("pp", 1) == 1:
            # plain sequential scan over layers, in GLOBAL stage order
            reordered = self._order != sorted(self._order)
            inv = np.argsort(self._order)
            ordered = {k: v[jnp.asarray(inv)] if reordered else v
                       for k, v in stacked.items()}
            ordered_b = {k: v[jnp.asarray(inv)] if reordered else v
                         for k, v in bufs.items()}

            def body(h, layer_state):
                lp, lb = layer_state
                out, new_b = self._apply_block(lp, lb, h)
                return out, new_b

            out, new_bufs = lax.scan(body, x, (ordered, ordered_b))
            if self._stacked_buf_keys:
                if reordered:
                    fwd = jnp.asarray(self._order)
                    new_bufs = {k: v[fwd] for k, v in new_bufs.items()}
                self._write_stacked_bufs(new_bufs)
            return out
        out, new_bufs = _pipeline_spmd(stacked, bufs, x, self._apply_block,
                                       mesh, self.num_micro, self.num_layers,
                                       self.num_virtual_stages)
        if self._stacked_buf_keys:
            self._write_stacked_bufs(new_bufs)
        return out


def _has_pp():
    from ..mesh import get_mesh

    m = get_mesh()
    return m is not None and "pp" in m.shape


def _pp_size() -> int:
    from ..mesh import get_mesh

    m = get_mesh()
    return m.shape.get("pp", 1) if m is not None else 1


def _pipeline_spmd(stacked_params, stacked_bufs, x, apply_block, mesh,
                   num_micro, num_layers, v=1):
    """Interleaved ring schedule over the "pp" mesh axis.

    Microbatches run in depth-first bursts of ``pp``: within a burst's scan,
    tick t advances every in-flight microbatch one ring hop; device d
    processes its local chunk ``(t - d) // pp`` (0 when v == 1). Outputs
    appear on the last device after ``v*pp`` hops.

    Buffers (BN running stats) ride alongside: each VALID tick's block run
    threads its layer-row buffers and writes them back; warmup/drain ticks
    (garbage activations in the bubble) keep the old buffer rows, so stats
    never see padding. Returns ``(out, new_stacked_bufs)``.
    """
    pp = mesh.shape["pp"]
    assert num_layers % (pp * v) == 0, \
        f"pp*virtual ({pp}*{v}) must divide num_layers ({num_layers})"
    B = x.shape[0]
    assert B % num_micro == 0, \
        f"num_micro ({num_micro}) must divide batch size ({B})"
    mb = B // num_micro
    lpc = num_layers // (pp * v)  # layers per chunk

    x_mb = x.reshape(num_micro, mb, *x.shape[1:])

    param_specs = {k: P("pp", *([None] * (val.ndim - 1)))
                   for k, val in stacked_params.items()}
    buf_specs = {k: P("pp", *([None] * (val.ndim - 1)))
                 for k, val in stacked_bufs.items()}
    in_specs = (param_specs, buf_specs, P(*([None] * (x_mb.ndim))))
    out_specs = (P(*([None] * x_mb.ndim)), buf_specs)

    def local(stage_params, stage_bufs, mb_inputs):
        # stage_params leaves: [v*lpc, ...] local rows; mb_inputs: [M, mb, ...]
        d = lax.axis_index("pp")
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        total_hops = v * pp

        def run_chunk(chunk_idx, h, bufs, valid):
            # local rows for this chunk: [chunk_idx*lpc, (chunk_idx+1)*lpc)
            def body(carry, i):
                hh, bufs = carry
                row = chunk_idx * lpc + i
                lp = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(
                        a, row, axis=0, keepdims=False), stage_params)
                lb = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(
                        a, row, axis=0, keepdims=False), bufs)
                out, new_lb = apply_block(lp, lb, hh)
                # bubble ticks must not pollute running stats
                bufs = jax.tree.map(
                    lambda a, nb, ob: lax.dynamic_update_index_in_dim(
                        a, jnp.where(valid, nb, ob), row, axis=0),
                    bufs, new_lb, lb)
                return (out, bufs), None

            (out, bufs), _ = lax.scan(body, (h, bufs), jnp.arange(lpc))
            return out, bufs

        zero = jnp.zeros(mb_inputs.shape[1:], mb_inputs.dtype)
        outputs0 = jnp.zeros_like(mb_inputs)

        def burst(carry, b0, burst_size):
            """One depth-first burst of ``burst_size`` (<= pp) microbatches
            starting at global microbatch b0."""
            n_ticks = total_hops + burst_size - 1

            def tick(carry, t):
                incoming, outputs, bufs = carry
                # device 0 feeds fresh microbatch t (chunk 0) while t < size
                feed_idx = jnp.clip(b0 + t, 0, num_micro - 1)
                first_in = lax.dynamic_index_in_dim(mb_inputs, feed_idx, axis=0,
                                                    keepdims=False)
                fresh = (d == 0) & (t < burst_size)
                h = jnp.where(fresh, first_in, incoming)
                # chunk this device runs at tick t; the activation it holds
                # is a real microbatch only inside the schedule window
                c = jnp.clip((t - d) // pp, 0, v - 1) if v > 1 else 0
                # device d holds microbatch m = (t-d) - chunk*pp; real iff
                # m is inside this burst and the chunk index is in range
                if v == 1:
                    valid = (t >= d) & (t - d < burst_size)
                else:
                    valid = ((t >= d) & ((t - d) % pp < burst_size)
                             & ((t - d) // pp < v))
                y, bufs = run_chunk(c, h, bufs, valid)
                # last device at its last chunk emits microbatch t-(total_hops-1)
                out_m = jnp.clip(b0 + t - (total_hops - 1), 0, num_micro - 1)
                emit = (d == pp - 1) & (t >= total_hops - 1)
                cur = lax.dynamic_index_in_dim(outputs, out_m, axis=0, keepdims=False)
                upd = jnp.where(emit, y, cur)
                outputs = lax.dynamic_update_index_in_dim(outputs, upd, out_m, axis=0)
                nxt = lax.ppermute(y, "pp", perm)
                return (nxt, outputs, bufs), None

            carry, _ = lax.scan(tick, carry, jnp.arange(n_ticks))
            return carry

        # v == 1: the continuous schedule is conflict-free, one burst of all
        # microbatches (bubble pp-1 total). v > 1: depth-first bursts of pp.
        step = num_micro if v == 1 else pp
        carry = (zero, outputs0, stage_bufs)
        for b0 in range(0, num_micro, step):
            carry = burst(carry, b0, min(step, num_micro - b0))
        _, outputs, bufs = carry

        # every rank returns its buffer; only the last rank's is real.
        # psum after masking replicates the result (out_specs replicated).
        outputs = jnp.where(d == pp - 1, outputs, jnp.zeros_like(outputs))
        return lax.psum(outputs, "pp"), bufs

    fn = shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    out_mb, new_bufs = fn(stacked_params, stacked_bufs, x_mb)
    return out_mb.reshape(B, *out_mb.shape[2:]), new_bufs


# ------------------------------------------------ heterogeneous stage path
class HeterogeneousPipeline(Layer):
    """Pipeline over ARBITRARY per-stage layers (different classes/shapes of
    compute, same activation signature between stages).

    Reference parity: ``PipelineLayer`` supports non-uniform stages because
    each process builds only its own sublayers. In SPMD there is one
    program, so every stage's computation is compiled into a ``lax.switch``
    and each device executes only its branch at runtime.

    Parameter placement: each stage's param pytree is raveled into one flat
    vector, padded to the longest stage, and the [pp, maxlen] stack is
    sharded over "pp" — so a rank holds ONLY its own stage's weights (plus
    padding), not pp replicas of everything. Optimizer state shards the
    same way. ``stage_state_dicts()`` unravels back to per-stage pytrees
    for checkpoint interchange.

    Stages must map [mb, ...] -> [mb, ...] with a fixed activation shape,
    be buffer-free, and share one floating param dtype (the ravel).
    """

    def __init__(self, stages: Sequence[Layer], num_micro: int = 1, remat: bool = True):
        super().__init__()
        from jax.flatten_util import ravel_pytree

        stages = list(stages)
        for l in stages:
            if list(l.named_buffers()):
                raise ValueError("pipeline stages must be buffer-free")
        # stage layers execute with raveled slices swapped in — their own
        # params must NOT register as this Layer's children
        object.__setattr__(self, "_stage_layers", stages)
        self.num_micro = num_micro
        self.remat = remat
        flats, unravels = [], []
        for l in stages:
            f, u = ravel_pytree(param_state(l))
            flats.append(f)
            unravels.append(u)
        dtypes = {f.dtype for f in flats}
        if len(dtypes) > 1:
            raise ValueError(
                f"heterogeneous stages must share one param dtype, got "
                f"{sorted(map(str, dtypes))}")
        self._stage_lens = [int(f.size) for f in flats]
        object.__setattr__(self, "_unravels", unravels)
        maxlen = max(self._stage_lens)
        stacked = jnp.stack([
            jnp.pad(f, (0, maxlen - f.size)) for f in flats])
        self.add_parameter("stages_flat", stacked)
        self.set_param_sharding("stages_flat", ("pp", None))

    @property
    def num_stages(self) -> int:
        return len(self._stage_layers)

    def _stage_params(self, flat_row, i):
        return self._unravels[i](flat_row[:self._stage_lens[i]])

    def stage_state_dicts(self):
        """Per-stage param pytrees unraveled from the sharded stack (for
        checkpoint interchange with per-process deployments)."""
        flat = self._parameters["stages_flat"]
        return [self._stage_params(flat[i], i)
                for i in range(self.num_stages)]

    def forward(self, x):
        mesh = require_mesh() if _has_pp() else None
        stages = self._stage_layers
        flat = self._parameters["stages_flat"]
        if mesh is None or mesh.shape.get("pp", 1) == 1:
            for i, l in enumerate(stages):
                p = self._stage_params(flat[i], i)
                x, _ = functional_call(l, p, {}, x)
            return x
        pp = mesh.shape["pp"]
        if len(stages) != pp:
            raise ValueError(f"{len(stages)} stages != pp axis size {pp}")
        B = x.shape[0]
        num_micro = self.num_micro
        assert B % num_micro == 0
        mb = B // num_micro
        x_mb = x.reshape(num_micro, mb, *x.shape[1:])
        remat = self.remat

        def make_branch(i):
            def branch(flat_local, h):
                def run(fl, hh):
                    p = self._stage_params(fl, i)
                    out, _ = functional_call(stages[i], p, {}, hh)
                    return out

                if remat:
                    run = jax.checkpoint(run)
                return run(flat_local, h)

            return branch

        branches = [make_branch(i) for i in range(pp)]

        # flat param stack sharded over pp: each rank sees ONLY its row
        in_specs = (P("pp", None), P(*([None] * x_mb.ndim)))
        out_specs = P(*([None] * x_mb.ndim))

        def local(flat_stack, mb_inputs):
            d = lax.axis_index("pp")
            flat_local = flat_stack[0]  # this rank's [maxlen] row
            perm = [(i, (i + 1) % pp) for i in range(pp)]
            n_ticks = num_micro + pp - 1
            zero = jnp.zeros(mb_inputs.shape[1:], mb_inputs.dtype)
            outputs0 = jnp.zeros_like(mb_inputs)

            def tick(carry, t):
                incoming, outputs = carry
                feed_idx = jnp.clip(t, 0, num_micro - 1)
                first_in = lax.dynamic_index_in_dim(mb_inputs, feed_idx, axis=0,
                                                    keepdims=False)
                h = jnp.where(d == 0, first_in, incoming)
                y = lax.switch(d, branches, flat_local, h)
                out_idx = jnp.clip(t - (pp - 1), 0, num_micro - 1)
                valid = (d == pp - 1) & (t >= pp - 1)
                cur = lax.dynamic_index_in_dim(outputs, out_idx, axis=0, keepdims=False)
                upd = jnp.where(valid, y, cur)
                outputs = lax.dynamic_update_index_in_dim(outputs, upd, out_idx, axis=0)
                nxt = lax.ppermute(y, "pp", perm)
                return (nxt, outputs), None

            (_, outputs), _ = lax.scan(tick, (zero, outputs0), jnp.arange(n_ticks))
            outputs = jnp.where(d == pp - 1, outputs, jnp.zeros_like(outputs))
            return lax.psum(outputs, "pp")

        fn = shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_vma=False)
        out_mb = fn(flat, x_mb)
        return out_mb.reshape(B, *out_mb.shape[2:])


class PipelineLayer(Layer):
    """Reference-shaped wrapper (``pp_layers.py:208``): build from LayerDescs,
    segment into stages. Homogeneous middle blocks run through
    PipelineStagedModule; leading/trailing non-uniform layers (embedding,
    head) run on every rank under plain GSPMD (cheap relative to the blocks,
    and sharded over dp/mp anyway). Tied embeddings (SharedLayerDesc) work
    naturally: the shared weight lives in pre/post outside the stacked stage
    params, so first/last-stage tying needs no grad-sync group."""

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 num_virtual_pipeline_stages=None, recompute_interval=0, num_micro=1):
        super().__init__()
        if seg_method != "uniform":
            raise NotImplementedError(
                "the SPMD pipeline segments the homogeneous block run "
                "uniformly over the 'pp' mesh axis; custom seg_method is not "
                "supported (stage count comes from the mesh, not num_stages)")
        from .containers_util import split_uniform_blocks
        from ...nn.layers.containers import LayerList

        descs = list(layers)
        built = [d.build_layer() if isinstance(d, LayerDesc) else d for d in descs]
        head_idx, block_idxs, tail_idx = split_uniform_blocks(built)

        self.pre = LayerList([built[i] for i in head_idx])
        self.post = LayerList([built[i] for i in tail_idx])
        self._loss_fn = loss_fn
        # Shard pre/post (embedding/head) weights over the pp axis instead of
        # replicating them on every pp rank: GSPMD partitions the gather/
        # matmul and inserts the collective, so their HBM and compute scale
        # with pp (Megatron vocab-parallel restated on the pp axis). Only
        # large unannotated matrices opt in; TP-annotated params keep theirs.
        pp = _pp_size()
        if pp > 1:
            for seg in (self.pre, self.post):
                for sub in seg:
                    self._shard_over_pp(sub, pp)
        if block_idxs:
            template = built[block_idxs[0]]
            # per-block initializer draws when the template came from a
            # LayerDesc; deepcopy semantics otherwise
            desc0 = descs[block_idxs[0]]
            factory = desc0.build_layer if isinstance(desc0, LayerDesc) else None
            self.blocks = PipelineStagedModule(
                template, len(block_idxs), num_micro=num_micro,
                remat=recompute_interval > 0, block_factory=factory,
                num_virtual_stages=num_virtual_pipeline_stages or 1)
        else:
            self.blocks = None

    @staticmethod
    def _shard_over_pp(layer: Layer, pp: int, min_elems: int = 1 << 16) -> None:
        """Annotate a layer tree's big unannotated matrices to shard dim 0
        over "pp" (recursing into sublayers)."""
        for name, p in layer._parameters.items():
            if (name not in layer._param_shardings and p is not None
                    and p.ndim >= 2 and p.size >= min_elems
                    and p.shape[0] % pp == 0):
                layer.set_param_sharding(name, ("pp",) + (None,) * (p.ndim - 1))
        for sub in layer._sub_layers.values():
            PipelineLayer._shard_over_pp(sub, pp, min_elems)

    def forward(self, x):
        for l in self.pre:
            x = l(x)
        if self.blocks is not None:
            x = self.blocks(x)
        for l in self.post:
            x = l(x)
        return x
