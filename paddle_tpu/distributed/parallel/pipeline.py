"""Pipeline parallelism.

Reference parity: ``fleet/meta_parallel/pipeline_parallel.py`` (1F1B python
scheduler ``forward_backward_pipeline:117`` driving NCCL P2P), model surgery
``parallel_layers/pp_layers.py`` (``LayerDesc:56``, ``SegmentLayers:92``,
``PipelineLayer:208``), and the ``SendRecvMeta`` shape handshake.

TPU-native redesign: there is no multi-process scheduler to write. All "pp"
ranks execute ONE SPMD program; stage weights are stacked on a leading
layer axis sharded over "pp"; the microbatch schedule is a ``lax.scan`` whose
carried activation rotates around the ring via ``ppermute`` (ICI
neighbor-hop). Autodiff through the scan generates the reverse-order backward
schedule — the hand-written ``backward_step`` machinery of the reference
falls out of ``jax.grad``. ``jax.checkpoint`` on the stage body keeps memory
at GPipe levels (per-stage activation stash of in-flight microbatches only).

The shape handshake (``SendRecvMeta``) disappears: shapes are static.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ...nn.layer import Layer, buffer_state, functional_call, param_state
from ..mesh import require_mesh


# ------------------------------------------------------- model surgery API
class LayerDesc:
    """Deferred layer constructor (reference ``pp_layers.py:56``)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-shared layer across stages (tied embeddings). In the SPMD
    design shared weights live outside the stacked stage params and are
    visible to every rank, so no grad-sync group is needed
    (reference builds a comm group per shared key)."""

    def __init__(self, key, layer_cls, *args, forward_func=None, shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layer descs into ``num_parts`` stages (reference
    ``pp_layers.py:92``): uniform, or proportional to parameter count, or a
    user-provided ``seg_method`` list of boundaries."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        if isinstance(self.method, (list, tuple)):
            assert len(self.method) == self.num_parts + 1
            return list(self.method)
        if self.method == "uniform":
            base = n // self.num_parts
            extra = n % self.num_parts
            bounds = [0]
            for i in range(self.num_parts):
                bounds.append(bounds[-1] + base + (1 if i < extra else 0))
            return bounds
        if self.method.startswith("layer:"):
            # segment only layers whose class name matches; others attach to
            # the nearest boundary (transformer-block segmentation)
            name = self.method.split(":", 1)[1]
            idxs = [i for i, d in enumerate(self.descs)
                    if getattr(d.layer_cls, "__name__", "") == name]
            if len(idxs) < self.num_parts:
                raise ValueError(
                    f"seg_method {self.method!r} matched {len(idxs)} layers, "
                    f"fewer than num_parts={self.num_parts}")
            per = len(idxs) // self.num_parts
            bounds = [0]
            for i in range(1, self.num_parts):
                bounds.append(idxs[i * per])
            bounds.append(n)
            return bounds
        raise ValueError(f"unknown seg method {self.method}")


# --------------------------------------------------------- SPMD pipelining
def _stack_params(layers: Sequence[Layer]):
    """Stack homogeneous layers' params/buffers along a leading axis."""
    states = [param_state(l) for l in layers]
    keys = list(states[0].keys())
    for s in states:
        assert list(s.keys()) == keys, "pipeline stages must be homogeneous"
    return {k: jnp.stack([s[k] for s in states]) for k in keys}


class PipelineStagedModule(Layer):
    """N homogeneous blocks executed as a "pp"-sharded pipeline.

    Holds the blocks' parameters stacked on a leading [num_layers] axis with
    sharding ("pp", ...). ``forward(x)`` consumes a full batch, internally
    splits it into ``num_micro`` microbatches and runs the ring schedule.
    With no mesh or pp=1 it degrades to a plain scan over layers (single-chip
    correctness path — loss parity with the distributed run is the
    ``TestDistBase`` pattern from SURVEY §4).
    """

    def __init__(self, block_fn_layer: Layer, num_layers: int, num_micro: int = 1,
                 remat: bool = True, block_factory: Optional[Callable[[], Layer]] = None):
        """``block_factory`` (e.g. a LayerDesc.build_layer) constructs each
        block with its own initializer draws; without it, blocks are deep
        copies of the template (identical initial weights, torch-deepcopy
        semantics).

        Limitation: blocks must be buffer-free (pure params). Buffer updates
        inside pipelined blocks (BatchNorm stats etc.) are not threaded
        through the stacked representation."""
        super().__init__()
        # the template executes with stacked slices swapped in — its own
        # params must NOT register (they'd be dead weights), so bypass
        # __setattr__'s sublayer routing
        object.__setattr__(self, "template", block_fn_layer)
        self.num_layers = num_layers
        self.num_micro = num_micro
        self.remat = remat
        if list(block_fn_layer.named_buffers()):
            raise ValueError(
                "PipelineStagedModule blocks must not hold buffers (running "
                "stats are not threaded through the stacked pipeline); use "
                "LayerNorm-style stateless layers inside pipeline stages")
        import copy

        if block_factory is not None:
            blocks = [block_fn_layer] + [block_factory() for _ in range(num_layers - 1)]
        else:
            blocks = [block_fn_layer] + [copy.deepcopy(block_fn_layer)
                                         for _ in range(num_layers - 1)]
        stacked = _stack_params(blocks)
        for k, v in stacked.items():
            path = f"stacked__{k.replace('.', '__')}"
            self.add_parameter(path, v)
            self.set_param_sharding(path, ("pp",) + (None,) * (v.ndim - 1))
        self._stacked_keys = list(stacked.keys())

    def _stacked(self):
        return {k: self._parameters[f"stacked__{k.replace('.', '__')}"]
                for k in self._stacked_keys}

    def _apply_block(self, layer_params: Dict[str, Any], x):
        tmpl = self.template

        def run(p, xx):
            out, _ = functional_call(tmpl, p, {}, xx)
            return out

        if self.remat:
            run = jax.checkpoint(run)
        return run(layer_params, x)

    def forward(self, x):
        mesh = require_mesh() if _has_pp() else None
        stacked = self._stacked()
        if mesh is None or mesh.shape.get("pp", 1) == 1:
            # plain sequential scan over layers
            def body(h, layer_params):
                return self._apply_block(layer_params, h), None

            out, _ = lax.scan(body, x, stacked)
            return out
        return _pipeline_spmd(stacked, x, self._apply_block, mesh,
                              self.num_micro, self.num_layers)


def _has_pp():
    from ..mesh import get_mesh

    m = get_mesh()
    return m is not None and "pp" in m.shape


def _pipeline_spmd(stacked_params, x, apply_block, mesh, num_micro, num_layers):
    pp = mesh.shape["pp"]
    assert num_layers % pp == 0, \
        f"pp axis size ({pp}) must divide num_layers ({num_layers})"
    B = x.shape[0]
    assert B % num_micro == 0, \
        f"num_micro ({num_micro}) must divide batch size ({B})"
    mb = B // num_micro
    layers_per_stage = num_layers // pp

    # [M, mb, ...] microbatch leading axis
    x_mb = x.reshape(num_micro, mb, *x.shape[1:])

    param_specs = {k: P("pp", *([None] * (v.ndim - 1))) for k, v in stacked_params.items()}
    # batch stays sharded over dp inside; replicated over pp
    in_specs = (param_specs, P(*([None] * (x_mb.ndim))))
    out_specs = P(*([None] * x_mb.ndim))

    def local(stage_params, mb_inputs):
        # stage_params leaves: [layers_per_stage, ...]; mb_inputs: [M, mb, ...]
        idx = lax.axis_index("pp")
        n_ticks = num_micro + pp - 1
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def run_stage(h):
            def body(hh, lp):
                return apply_block(lp, hh), None

            out, _ = lax.scan(body, h, stage_params)
            return out

        zero = jnp.zeros(mb_inputs.shape[1:], mb_inputs.dtype)
        outputs0 = jnp.zeros_like(mb_inputs)

        def tick(carry, t):
            incoming, outputs = carry
            # stage 0 pulls microbatch t (clamped); others use the ring input
            feed_idx = jnp.clip(t, 0, num_micro - 1)
            first_in = lax.dynamic_index_in_dim(mb_inputs, feed_idx, axis=0,
                                                keepdims=False)
            h = jnp.where(idx == 0, first_in, incoming)
            y = run_stage(h)
            # last stage writes output for microbatch t-(pp-1) when valid
            out_idx = jnp.clip(t - (pp - 1), 0, num_micro - 1)
            valid = (idx == pp - 1) & (t >= pp - 1)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, axis=0, keepdims=False)
            upd = jnp.where(valid, y, cur)
            outputs = lax.dynamic_update_index_in_dim(outputs, upd, out_idx, axis=0)
            nxt = lax.ppermute(y, "pp", perm)
            return (nxt, outputs), None

        (_, outputs), _ = lax.scan(tick, (zero, outputs0), jnp.arange(n_ticks))
        # every rank returns its buffer; only the last rank's is real.
        # psum after masking replicates the result (out_specs replicated).
        outputs = jnp.where(idx == pp - 1, outputs, jnp.zeros_like(outputs))
        return lax.psum(outputs, "pp")

    fn = shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    out_mb = fn(stacked_params, x_mb)
    return out_mb.reshape(B, *out_mb.shape[2:])


class PipelineLayer(Layer):
    """Reference-shaped wrapper (``pp_layers.py:208``): build from LayerDescs,
    segment into stages. Homogeneous middle blocks run through
    PipelineStagedModule; leading/trailing non-uniform layers (embedding,
    head) run on every rank under plain GSPMD (cheap relative to the blocks,
    and sharded over dp/mp anyway)."""

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 num_virtual_pipeline_stages=None, recompute_interval=0, num_micro=1):
        super().__init__()
        if seg_method != "uniform":
            raise NotImplementedError(
                "the SPMD pipeline segments the homogeneous block run "
                "uniformly over the 'pp' mesh axis; custom seg_method is not "
                "supported (stage count comes from the mesh, not num_stages)")
        from .containers_util import split_uniform_blocks

        descs = list(layers)
        built = [d.build_layer() if isinstance(d, LayerDesc) else d for d in descs]
        head_idx, block_idxs, tail_idx = split_uniform_blocks(built)
        from ...nn.layers.containers import LayerList

        self.pre = LayerList([built[i] for i in head_idx])
        self.post = LayerList([built[i] for i in tail_idx])
        self._loss_fn = loss_fn
        if block_idxs:
            template = built[block_idxs[0]]
            # per-block initializer draws when the template came from a
            # LayerDesc; deepcopy semantics otherwise
            desc0 = descs[block_idxs[0]]
            factory = desc0.build_layer if isinstance(desc0, LayerDesc) else None
            self.blocks = PipelineStagedModule(template, len(block_idxs),
                                               num_micro=num_micro,
                                               remat=recompute_interval > 0,
                                               block_factory=factory)
        else:
            self.blocks = None

    def forward(self, x):
        for l in self.pre:
            x = l(x)
        if self.blocks is not None:
            x = self.blocks(x)
        for l in self.post:
            x = l(x)
        return x
