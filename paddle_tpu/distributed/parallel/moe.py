"""Mixture-of-Experts with expert parallelism.

Reference parity: ``python/paddle/incubate/distributed/models/moe/`` —
``MoELayer`` (``moe_layer.py:259``: gate -> MoEScatter(global_scatter all2all)
-> experts -> MoEGather), gates ``gshard_gate.py``/``switch_gate.py``/
``naive_gate.py``, and the ``global_scatter/global_gather`` CUDA all2all ops.

TPU-native: dispatch/combine are einsums against one-hot capacity tensors
(dense, static-shaped — the GShard formulation XLA was built for). Experts are
a stacked weight tensor sharded over the "ep" mesh axis; under GSPMD the
dispatch einsum lowers to the all_to_all the reference implements by hand.
Capacity-dropped tokens pass through the residual, matching gshard semantics.
"""
from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ...nn import functional as F
from ...nn.initializer import Constant, XavierUniform
from ...nn.layer import Layer, take_rng_key
from ..mesh import get_mesh, sharding


# ------------------------------------------------------------------- gates
def top2_gating(logits, capacity: int, noise_key=None, second_policy="random"):
    """GShard top-2 gate with capacity + load-balancing aux loss.
    Returns (combine [G,S,E,C], dispatch bool [G,S,E,C], aux_loss)."""
    G, S, E = logits.shape
    raw_probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    idx1 = jnp.argmax(raw_probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)
    # aux loss (gshard): mean_prob * mean_assignment per expert
    density = jnp.mean(mask1, axis=1)
    density_proxy = jnp.mean(raw_probs, axis=1)
    aux_loss = jnp.mean(density * density_proxy) * (E * E)

    probs_wo1 = raw_probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)

    g1 = jnp.sum(raw_probs * mask1, axis=-1)
    g2 = jnp.sum(raw_probs * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    # positions within expert capacity
    pos1 = jnp.cumsum(mask1, axis=1) * mask1 - mask1
    mask1 = mask1 * (pos1 < capacity)
    pos1 = jnp.sum(pos1 * mask1, axis=-1)

    count1 = jnp.sum(mask1, axis=1, keepdims=True)
    pos2 = (jnp.cumsum(mask2, axis=1) - mask2 + count1) * mask2
    mask2 = mask2 * (pos2 < capacity)
    pos2 = jnp.sum(pos2 * mask2, axis=-1)

    keep1 = jnp.sum(mask1, axis=-1)
    keep2 = jnp.sum(mask2, axis=-1)
    g1, g2 = g1 * keep1, g2 * keep2

    c1 = jax.nn.one_hot(pos1.astype(jnp.int32), capacity, dtype=jnp.float32)
    c2 = jax.nn.one_hot(pos2.astype(jnp.int32), capacity, dtype=jnp.float32)
    e1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32) * keep1[..., None]
    e2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32) * keep2[..., None]
    combine = (g1[..., None, None] * e1[..., None] * c1[..., None, :]
               + g2[..., None, None] * e2[..., None] * c2[..., None, :])
    dispatch = combine > 0
    return combine, dispatch, aux_loss


def switch_gating(logits, capacity: int, noise_key=None, jitter_eps=0.01):
    """Switch-Transformer top-1 gate."""
    G, S, E = logits.shape
    if noise_key is not None:
        noise = jax.random.uniform(noise_key, logits.shape, minval=1 - jitter_eps,
                                   maxval=1 + jitter_eps)
        logits = logits * noise
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
    density = jnp.mean(mask, axis=1)
    density_proxy = jnp.mean(probs, axis=1)
    aux_loss = jnp.mean(density * density_proxy) * (E * E)
    g = jnp.sum(probs * mask, axis=-1)
    pos = jnp.cumsum(mask, axis=1) * mask - mask
    mask = mask * (pos < capacity)
    pos = jnp.sum(pos * mask, axis=-1)
    keep = jnp.sum(mask, axis=-1)
    g = g * keep
    c = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    e = jax.nn.one_hot(idx, E, dtype=jnp.float32) * keep[..., None]
    combine = g[..., None, None] * e[..., None] * c[..., None, :]
    return combine, combine > 0, aux_loss


GATES = {"gshard": top2_gating, "top2": top2_gating, "switch": switch_gating,
         "top1": switch_gating, "naive": switch_gating}

GATE_TOPK = {"gshard": 2, "top2": 2, "switch": 1, "top1": 1, "naive": 1}


# -------------------------------------------------- sparse (all2all) path
def _route_topk(logits, k: int, noise_key=None, jitter_eps: float = 0.01):
    """Top-k routing: renormalized gate weights + expert ids per token and
    the per-expert load statistics (density of top-1 assignments, mean
    gate probability) whose product is the gshard aux loss. ``noise_key``
    applies the switch-gate training jitter (parity with
    :func:`switch_gating`)."""
    if noise_key is not None:
        noise = jax.random.uniform(noise_key, logits.shape,
                                   minval=1 - jitter_eps,
                                   maxval=1 + jitter_eps)
        logits = logits * noise
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [S, E]
    g, e_idx = jax.lax.top_k(probs, k)                           # [S, k]
    if k > 1:  # gshard renormalizes top-k gates; switch (k=1) keeps raw prob
        g = g / jnp.maximum(jnp.sum(g, axis=-1, keepdims=True), 1e-9)
    E = logits.shape[-1]
    mask1 = jax.nn.one_hot(e_idx[:, 0], E, dtype=jnp.float32)
    density = jnp.mean(mask1, axis=0)       # [E]
    proxy = jnp.mean(probs, axis=0)         # [E]
    return g, e_idx, density, proxy


def _dispatch_buffers(tokens, e_idx, capacity: int, E: int):
    """Scatter routed tokens into per-expert capacity buffers.

    Unlike the dense GShard formulation this never materializes a
    [S, E, C] one-hot — memory is O(S*d + E*C*d), which is what lets
    E scale (reference ``global_scatter_op.cu.cc`` moves only routed
    tokens for the same reason). Slots are assigned in CHOICE-MAJOR order
    (all first choices, then all second choices), matching the dense
    gate's drop priority: under capacity pressure a token's top-1 beats
    any token's top-2 (``top2_gating``'s pos2-offset-by-count1).
    Returns (buf [E, C, d], meta); meta addresses each routed copy's slot
    for the combine gather, in the same choice-major order."""
    S, k = e_idx.shape
    d = tokens.shape[-1]
    flat_e = e_idx.T.reshape(-1)                    # [k*S], choice-major
    oh = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)
    pos = jnp.cumsum(oh, axis=0) - 1                # queue position per expert
    pos = jnp.take_along_axis(pos, flat_e[:, None], axis=1)[:, 0]
    keep = pos < capacity
    slot = jnp.where(keep, pos, capacity)           # sentinel slot for drops
    xk = jnp.tile(tokens, (k, 1))                   # [k*S, d], choice-major
    buf = jnp.zeros((E, capacity + 1, d), tokens.dtype)
    buf = buf.at[flat_e, slot].add(
        xk * keep[:, None].astype(tokens.dtype))
    return buf[:, :capacity], (flat_e, slot, keep)


def _combine_buffers(buf, g, meta, S: int, k: int):
    """Gather expert outputs back to token order, weighted by gates;
    capacity-dropped copies contribute zero (gshard residual semantics)."""
    flat_e, slot, keep = meta
    pad = jnp.pad(buf, ((0, 0), (0, 1), (0, 0)))    # restore sentinel slot
    vals = pad[flat_e, slot]                        # [k*S, d], choice-major
    w = (g.T.reshape(-1) * keep).astype(vals.dtype)
    return jnp.sum((vals * w[:, None]).reshape(k, S, -1), axis=0)


class ExpertFFN(Layer):
    """Stacked expert FFNs: weights [E, d, d_hidden] sharded over "ep"."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.w1 = self.create_parameter((num_experts, d_model, d_hidden),
                                        default_initializer=XavierUniform())
        self.b1 = self.create_parameter((num_experts, 1, d_hidden), is_bias=True)
        self.w2 = self.create_parameter((num_experts, d_hidden, d_model),
                                        default_initializer=XavierUniform())
        self.b2 = self.create_parameter((num_experts, 1, d_model), is_bias=True)
        for n in ("w1", "b1", "w2", "b2"):
            self.set_param_sharding(n, ("ep",) + (None,) * 2)
        self._activation = activation

    def forward(self, x):
        # x: [E, C_total, d]
        act = getattr(F, self._activation)
        h = act(jnp.einsum("ecd,edh->ech", x, self.w1) + self.b1)
        return jnp.einsum("ech,ehd->ecd", h, self.w2) + self.b2


class MoELayer(Layer):
    """GShard MoE layer (reference ``moe_layer.py:259``).

    Input [B, L, d] -> gate -> dispatch einsum (GSPMD all2all over "ep") ->
    experts -> combine einsum. ``aux_loss`` is stored on the layer after each
    forward (add it to the training loss, as the reference's fleet loss hooks
    do).
    """

    def __init__(self, d_model, d_hidden, num_experts, gate: str = "gshard",
                 capacity_factor: float = 1.25, eval_capacity_factor: float = 2.0,
                 activation: str = "gelu", group=None,
                 dispatch_mode: str = "dense"):
        """``dispatch_mode``: "dense" = GShard one-hot einsums (GSPMD
        derives the collective; memory scales with S*E*C — right for small
        E); "alltoall" = shard_map sparse path: per-device top-k routing,
        scatter into [E, C, d] capacity buffers, ``lax.all_to_all`` of only
        the routed tokens (reference ``global_scatter_op.cu.cc``) — right
        for large E where the one-hot would dominate HBM."""
        super().__init__()
        if dispatch_mode not in ("dense", "alltoall"):
            raise ValueError(f"unknown dispatch_mode {dispatch_mode!r}")
        if gate not in GATES:
            raise ValueError(
                f"unknown gate {gate!r}; choose from {sorted(GATES)}")
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.gate_name = gate
        self.dispatch_mode = dispatch_mode
        self.gate_weight = self.create_parameter(
            (d_model, num_experts), default_initializer=XavierUniform())
        self.experts = ExpertFFN(num_experts, d_model, d_hidden, activation)
        self.register_buffer("aux_loss", jnp.zeros((), jnp.float32), persistable=False)

    def forward(self, x):
        if self.dispatch_mode == "alltoall":
            return self._forward_a2a(x)
        return self._forward_dense(x)

    def _forward_a2a(self, x):
        """Sparse dispatch: explicit shard_map over "ep". Tokens are
        sharded over the batch dim; each device routes its S_local tokens
        into per-expert capacity buffers and all_to_all's ONLY those."""
        from ...framework.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        B, L, d = x.shape
        E = self.num_experts
        k = GATE_TOPK.get(self.gate_name, 2)
        factor = (self.capacity_factor if self.training
                  else self.eval_capacity_factor)
        mesh = get_mesh()
        ep = mesh.shape.get("ep", 1) if mesh is not None else 1
        if B % ep or E % ep:
            raise ValueError(
                f"alltoall dispatch needs batch ({B}) and num_experts "
                f"({E}) divisible by the ep axis ({ep})")
        s_local = (B // ep) * L
        # same factor semantics as the dense gate: capacity counts TOKENS
        # per expert, shared across the k choices (top2_gating seats both
        # choices in one per-expert queue)
        capacity = max(int(math.ceil(s_local / E * factor)), 4)
        gate_w = self.gate_weight.astype(x.dtype)
        ex = self.experts
        jitter_key = (take_rng_key("gumbel")
                      if self.training and self.gate_name in
                      ("switch", "top1", "naive") else None)

        def local_fn(xs, gate_w, w1, b1, w2, b2):
            # xs [B_local, L, d]; expert weights are this device's block
            tokens = xs.reshape(-1, d)
            logits = tokens @ gate_w
            nk = jitter_key
            if nk is not None and ep > 1:
                nk = jax.random.fold_in(nk, jax.lax.axis_index("ep"))
            g, e_idx, density, proxy = _route_topk(logits, k, noise_key=nk)
            buf, meta = _dispatch_buffers(tokens, e_idx, capacity, E)
            if ep > 1:
                e_loc = E // ep
                buf = jax.lax.all_to_all(buf, "ep", split_axis=0,
                                         concat_axis=0, tiled=True)
                recv = (buf.reshape(ep, e_loc, capacity, d)
                        .transpose(1, 0, 2, 3).reshape(e_loc, -1, d))
            else:
                recv = buf
            act = getattr(F, ex._activation)
            h = act(jnp.einsum("ecd,edh->ech", recv, w1) + b1)
            out = jnp.einsum("ech,ehd->ecd", h, w2) + b2
            if ep > 1:
                e_loc = E // ep
                out = (out.reshape(e_loc, ep, capacity, d)
                       .transpose(1, 0, 2, 3).reshape(E, capacity, d))
                out = jax.lax.all_to_all(out, "ep", split_axis=0,
                                         concat_axis=0, tiled=True)
                # GLOBAL load statistics (mean over all tokens, not mean of
                # per-shard aux scalars): matches the dense gate's aux
                density = jax.lax.pmean(density, "ep")
                proxy = jax.lax.pmean(proxy, "ep")
            aux = jnp.mean(density * proxy) * (E * E)
            y = _combine_buffers(out, g, meta, tokens.shape[0], k)
            return y.reshape(xs.shape), aux

        if ep == 1:
            # no mesh / single ep shard: same math, no collective
            out, aux = local_fn(x, gate_w, ex.w1, ex.b1, ex.w2, ex.b2)
        else:
            fn = shard_map(
                local_fn, mesh=mesh,
                in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
                out_specs=(P("ep"), P()), check_vma=False)
            out, aux = fn(x, gate_w, ex.w1, ex.b1, ex.w2, ex.b2)
        self.aux_loss = aux
        return out

    def _forward_dense(self, x):
        B, L, d = x.shape
        S = B * L
        E = self.num_experts
        factor = self.capacity_factor if self.training else self.eval_capacity_factor
        capacity = max(int(math.ceil(S / E * factor)), 4)

        tokens = x.reshape(1, S, d)  # single gating group
        logits = jnp.einsum("gsd,de->gse", tokens, self.gate_weight.astype(x.dtype))
        noise_key = take_rng_key("gumbel") if self.training and self.gate_name in ("switch", "top1") else None
        combine, dispatch, aux = GATES[self.gate_name](logits, capacity, noise_key)
        self.aux_loss = aux

        dtype = x.dtype
        # dispatch: [G,S,E,C] x [G,S,d] -> [E, G*C, d]  (GSPMD: all2all to "ep")
        expert_in = jnp.einsum("gsec,gsd->gecd", dispatch.astype(dtype), tokens)
        expert_in = expert_in.reshape(E, -1, d)
        expert_in = self._constrain_ep(expert_in)
        expert_out = self.experts(expert_in)
        expert_out = self._constrain_ep(expert_out)
        expert_out = expert_out.reshape(1, E, capacity, d)
        out = jnp.einsum("gsec,gecd->gsd", combine.astype(dtype), expert_out)
        return out.reshape(B, L, d)

    def _constrain_ep(self, t):
        mesh = get_mesh()
        if mesh is None or "ep" not in mesh.shape:
            return t
        return jax.lax.with_sharding_constraint(t, sharding("ep", None, None, mesh=mesh))
