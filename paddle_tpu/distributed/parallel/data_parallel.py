"""DataParallel wrapper (API parity).

Reference: dygraph ``DataParallel``
(``python/paddle/fluid/dygraph/parallel.py:457``) wraps a Layer and
installs the C++ ``Reducer`` (``paddle/fluid/imperative/reducer.h:129``)
— bucketed fused allreduce overlapped with backward.

TPU-native collapse: gradient synchronization is not a wrapper concern —
batch-sharded ``jit`` (``DistributedTrainStep`` with ``batch_axes=("dp",)``)
makes XLA insert and overlap the gradient all-reduce itself (GSPMD). This
class therefore only preserves the reference's API shape so ported
training scripts run unchanged: ``forward`` delegates, ``scale_loss`` is
identity (the mean over the global batch already includes the dp factor),
``no_sync`` is a no-op context (there is no per-step collective to
suppress; gradient merge lives in ``TrainStep(grad_accum_steps=k)``).

One reference knob survives with real meaning: ``comm_buffer_size`` (MB)
— the C++ ``Reducer``'s allreduce bucket size — is kept as the
``_comm_buffer_mb`` hint that ``DistributedTrainStep`` reads as its
default ``bucket_size_mb`` when ``overlap_grad_reduce=True``, so a
ported script's bucket tuning carries over to the GSPMD overlap
schedule (``distributed.overlap``).
"""
from __future__ import annotations

import contextlib

from ...nn.layer import Layer

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        # bucket-size hint (MB) for the overlap schedule; see module doc
        self._comm_buffer_mb = float(comm_buffer_size)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    @contextlib.contextmanager
    def no_sync(self):
        yield

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, state, *a, **kw):
        return self._layers.set_state_dict(state, *a, **kw)
