"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO long-context support (SURVEY §2.3/§5: no
sequence_parallel/ring_attention anywhere — sequence length is bounded by one
device's memory). This module is the new capability the TPU build adds:

- **Ring attention** (blockwise attention with K/V rotating around the "sp"
  mesh axis via ``ppermute`` over ICI): sequence length scales linearly with
  the axis size, communication overlaps with the blockwise compute, and the
  online-softmax accumulation matches the Pallas flash kernel's inner loop.
- **Ulysses-style all-to-all**: resharding [B, L/sp, H, D] -> [B, L, H/sp, D]
  so each device runs full-sequence attention on a head subset; two
  ``all_to_all`` ops around any attention implementation.

Both run inside ``shard_map`` over the "sp" axis.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from ...framework.jax_compat import (axis_size as _axis_size,
                                     pcast as _pcast, shard_map)
from jax.sharding import PartitionSpec as P

from ..mesh import require_mesh


def _online_block(q, k, v, m_prev, l_prev, acc, scale, mask=None):
    """One blockwise-attention accumulation step (f32 state)."""
    s = jnp.einsum("blhd,bkhd->bhlk", q, k, preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhlk,bkhd->bhld", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Runs on one shard: q,k,v are [B, L_local, H, D]; K/V blocks rotate
    around the ring while each device accumulates its queries' output."""
    B, Lq, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    n = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    qf = q.astype(jnp.float32)
    # mark the fresh accumulators as device-varying over the sp axis so the
    # scan carry types line up (shard_map VMA rule)
    _vary = lambda t: _pcast(t, (axis_name,), to="varying")  # noqa: E731
    m0 = _vary(jnp.full((B, H, Lq), -1e30, jnp.float32))
    l0 = _vary(jnp.zeros((B, H, Lq), jnp.float32))
    acc0 = _vary(jnp.zeros((B, H, Lq, D), jnp.float32))

    def body(carry, step):
        k_blk, v_blk, m, l, acc = carry
        # block currently held = originally owned by (my_idx - step) mod n
        src = (my_idx - step) % n
        if causal:
            q_pos = my_idx * Lq + jnp.arange(Lq)
            k_pos = src * Lq + jnp.arange(k_blk.shape[1])
            mask = q_pos[None, None, :, None] >= k_pos[None, None, None, :]
        else:
            mask = None
        m, l, acc = _online_block(qf, k_blk.astype(jnp.float32),
                                  v_blk.astype(jnp.float32), m, l, acc, scale, mask)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, m, l, acc), None

    (k_fin, v_fin, m, l, acc), _ = lax.scan(body, (k, v, m0, l0, acc0), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    # [B,H,L,D] -> [B,L,H,D]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis_name: str = "sp", causal: bool = True):
    """Global-view API: q,k,v are [B, L, H, D] sharded (or shardable) along L
    over ``axis_name``. Returns same-sharded output."""
    mesh = mesh or require_mesh()
    if axis_name not in mesh.shape or mesh.shape[axis_name] == 1:
        # degenerate: plain attention
        from ...nn import functional as F

        return F.scaled_dot_product_attention(q, k, v, is_causal=causal, training=False)
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


# --------------------------------------------------------- Ulysses all2all
def _ulysses_local(q, k, v, axis_name: str, causal: bool):
    n = _axis_size(axis_name)

    def seq_to_head(x):
        # [B, L/n, H, D] -> [B, L, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def head_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    from ...nn import functional as F

    out = F.scaled_dot_product_attention(qh, kh, vh, is_causal=causal, training=False)
    return head_to_seq(out)


def ulysses_attention(q, k, v, mesh=None, axis_name: str = "sp", causal: bool = True):
    """DeepSpeed-Ulysses-style sequence parallelism: all_to_all heads<->seq.
    Requires num_heads % sp == 0."""
    mesh = mesh or require_mesh()
    if axis_name not in mesh.shape or mesh.shape[axis_name] == 1:
        from ...nn import functional as F

        return F.scaled_dot_product_attention(q, k, v, is_causal=causal, training=False)
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
