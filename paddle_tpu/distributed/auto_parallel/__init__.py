"""paddle_tpu.distributed.auto_parallel — semi-automatic SPMD.

Reference parity: ``python/paddle/distributed/auto_parallel/`` —
``ProcessMesh`` (``process_mesh.py``), ``shard_tensor``/``shard_op``
annotations (``interface.py``), ``Engine`` fit/evaluate/predict
(``engine.py:60``), and the ``tuner/`` + ``cost/`` search machinery
(``Planner``, comm/comp cost model, ``cluster.py``).

TPU-native split of labor: the reference's ``Completer`` (dist-attr
propagation), ``Partitioner`` (program splitting) and ``Resharder``
(cross-mesh comm insertion) — ~40k LoC — ARE the XLA GSPMD pass, driven
here by sharding annotations. What remains framework work is (1) the
annotation surface, (2) the Engine, and (3) the *planner*: choosing mesh
shape + shardings from a cost model before compilation. That planner is
implemented in :mod:`.planner`.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..mesh import get_mesh, init_mesh
from .planner import CostModel, Planner, plan_mesh
from .tuner import (ParallelTuner, TunedPlan, calibrate_cluster,
                    measure_ici)
from .engine import Engine

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "Engine", "Planner",
           "ParallelTuner", "TunedPlan", "calibrate_cluster", "measure_ici",
           "CostModel", "plan_mesh"]


class ProcessMesh:
    """N-d logical device mesh with named dims (reference
    ``process_mesh.py``). Thin veneer over ``jax.sharding.Mesh``: the
    reference carries explicit process ids; here device order comes from
    ``jax.devices()`` (ICI-contiguous by construction)."""

    def __init__(self, mesh=None, dim_names: Optional[Sequence[str]] = None,
                 shape: Optional[Sequence[int]] = None):
        if mesh is not None and hasattr(mesh, "devices"):
            self._mesh = mesh
        else:
            # reference signature: ProcessMesh([[0,1],[2,3]], dim_names=[...])
            import numpy as np

            if shape is None:
                shape = np.asarray(mesh).shape if mesh is not None else None
            dim_names = list(dim_names or
                             [f"d{i}" for i in range(len(shape))])
            self._mesh = init_mesh(dict(zip(dim_names, shape)))
        self.dim_names = list(self._mesh.axis_names)

    @property
    def mesh(self):
        return self._mesh

    @property
    def shape(self):
        return dict(self._mesh.shape)

    def __enter__(self):
        from ..mesh import mesh_scope

        # install as the current mesh (so shard_tensor's default mesh
        # resolution sees it) AND enter the jax mesh context — both
        # constructor paths behave identically under `with pm:`
        self._scope = mesh_scope(self._mesh)
        self._scope.__enter__()
        return self

    def __exit__(self, *exc):
        self._scope.__exit__(*exc)
        return False


def _resolve_mesh(process_mesh):
    if process_mesh is None:
        return get_mesh()
    if isinstance(process_mesh, ProcessMesh):
        return process_mesh.mesh
    return process_mesh


def shard_tensor(x, process_mesh=None, shard_spec: Sequence = None):
    """Annotate a tensor's placement (reference ``interface.py``
    ``shard_tensor(x, process_mesh, shard_spec)`` where shard_spec maps
    each dim to a mesh dim name or None).

    Outside jit: materializes the sharding via ``device_put``. Inside
    jit: becomes a ``with_sharding_constraint`` — GSPMD propagates from
    these anchors exactly like the reference's Completer propagates
    dist_attrs.
    """
    mesh = _resolve_mesh(process_mesh)
    if mesh is None:
        raise ValueError("no mesh: pass process_mesh or init_mesh() first")
    spec = PartitionSpec(*(shard_spec or ()))
    sharding = NamedSharding(mesh, spec)
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(jnp.asarray(x), sharding)


def shard_op(op, process_mesh=None, in_shard_specs=None, out_shard_specs=None):
    """Annotate an op's output placements (reference ``shard_op``): wraps
    ``op`` so inputs/outputs get sharding constraints."""
    mesh = _resolve_mesh(process_mesh)

    def wrapped(*args, **kwargs):
        if in_shard_specs is not None:
            if len(in_shard_specs) != len(args):
                raise ValueError(
                    f"shard_op: {len(in_shard_specs)} in_shard_specs for "
                    f"{len(args)} positional args")
            args = tuple(
                shard_tensor(a, mesh, s) if s is not None else a
                for a, s in zip(args, in_shard_specs))
        out = op(*args, **kwargs)
        if out_shard_specs is not None:
            if isinstance(out, tuple):
                if len(out_shard_specs) != len(out):
                    raise ValueError(
                        f"shard_op: {len(out_shard_specs)} out_shard_specs "
                        f"for {len(out)} outputs")
                out = tuple(
                    shard_tensor(o, mesh, s) if s is not None else o
                    for o, s in zip(out, out_shard_specs))
            else:
                out = shard_tensor(out, mesh, out_shard_specs[0])
        return out

    return wrapped
