"""Auto-parallel Engine — fit/evaluate/predict over a planned mesh.

Reference parity: ``python/paddle/distributed/auto_parallel/engine.py:60``
(``Engine(model, loss, optimizer, metrics).fit/evaluate/predict`` running
the completed+partitioned program). TPU-native: planning picks the mesh
(:mod:`.planner`), DistributedTrainStep/GSPMD realize it; the Engine is
the thin driver loop the reference exposes.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...nn.layer import Layer, buffer_state, functional_call, param_state
from ..mesh import get_mesh, init_mesh
from ..shard import DistributedTrainStep
from .planner import ModelSpec, Planner


class Engine:
    """``auto_parallel.Engine`` analogue.

    ``mesh`` may be given explicitly, or a ``model_spec`` lets the
    planner choose (dp/mp/sdp) for the available chips. ``fit`` drives
    DistributedTrainStep; ``evaluate``/``predict`` run the sharded
    forward.
    """

    def __init__(self, model: Layer, loss_fn: Optional[Callable] = None,
                 optimizer=None, metrics=None, mesh=None,
                 model_spec: Optional[ModelSpec] = None,
                 strategy=None, batch_axes=("dp", "sdp"),
                 auto_tune: bool = False, cluster=None,
                 num_heads: Optional[int] = None):
        """``auto_tune=True`` with a ``model_spec`` runs the full 5-axis
        :class:`~.tuner.ParallelTuner` (measured-calibrated roofline) and
        adopts its best plan; the default keeps the cheaper 3-axis
        Planner (the reference's Engine -> tuner escalation)."""
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.metrics = metrics or []
        self.plan = None
        if auto_tune and (mesh is not None or model_spec is None):
            raise ValueError(
                "auto_tune=True needs a model_spec and no explicit mesh — "
                "the tuner's job is to pick the mesh")
        if not auto_tune and num_heads is not None:
            raise ValueError(
                "num_heads is a tuner constraint; pass auto_tune=True "
                "(the 3-axis planner does not consume it)")
        if mesh is None:
            if model_spec is not None:
                n = len(jax.devices())
                if auto_tune:
                    from .tuner import ParallelTuner

                    self.plan = ParallelTuner(
                        model_spec, n, cluster=cluster,
                        num_heads=num_heads).best()
                else:
                    self.plan = Planner(model_spec, n, cluster=cluster).best()
                mesh = init_mesh(self.plan.axes)
            else:
                mesh = get_mesh() or init_mesh({"dp": -1})
        self.mesh = mesh
        self.batch_axes = batch_axes
        self._train_step: Optional[DistributedTrainStep] = None
        self._eval_fn = None
        self.history: Dict[str, list] = {"loss": []}

    # ------------------------------------------------------------ training
    def _ensure_train_step(self):
        if self._train_step is None:
            if self.optimizer is None:
                raise ValueError("optimizer required for fit()")
            sharding_stage = 2 if "sdp" in self.mesh.shape else 0
            self._train_step = DistributedTrainStep(
                self.model, self.optimizer, loss_fn=self.loss_fn,
                mesh=self.mesh, batch_axes=self.batch_axes,
                sharding_stage=sharding_stage)
        return self._train_step

    def fit(self, train_data: Iterable, epochs: int = 1, steps_per_epoch=None,
            log_freq: int = 0, verbose: int = 0):
        step = self._ensure_train_step()
        for epoch in range(epochs):
            for i, batch in enumerate(train_data):
                if steps_per_epoch and i >= steps_per_epoch:
                    break
                loss = step(batch)
                self.history["loss"].append(float(loss))
                if log_freq and (i % log_freq == 0):
                    print(f"[engine] epoch {epoch} step {i} "
                          f"loss {float(loss):.4f}", flush=True)
        return self.history

    # ---------------------------------------------------------- evaluation
    def _ensure_eval_fn(self):
        if self._eval_fn is None:
            model = self.model

            @jax.jit
            def run(params, buffers, *inputs):
                out, _ = functional_call(model, params, buffers, *inputs)
                return out

            self._eval_fn = run
        return self._eval_fn

    def _state(self):
        if self._train_step is not None:
            return self._train_step.params, self._train_step.buffers
        return param_state(self.model), buffer_state(self.model)

    def evaluate(self, eval_data: Iterable) -> Dict[str, float]:
        run = self._ensure_eval_fn()
        params, buffers = self._state()
        was_training = self.model.training
        self.model.eval()
        for metric in self.metrics:
            metric.reset()
        try:
            losses = []
            for batch in eval_data:
                inputs = batch[0] if isinstance(batch, (tuple, list)) else batch
                with self.mesh:
                    out = run(params, buffers, jnp.asarray(inputs))
                if self.loss_fn is not None:
                    losses.append(float(self.loss_fn(out, batch)))
                for metric in self.metrics:
                    label = batch[1] if isinstance(batch, (tuple, list)) \
                        and len(batch) > 1 else None
                    metric.update(metric.compute(out, label))
            result = {"loss": float(np.mean(losses)) if losses
                      else float("nan")}
            for metric in self.metrics:
                names = metric.name()
                vals = metric.accumulate()
                # paddle Metric.name()/accumulate() return lists for topk
                if isinstance(names, (list, tuple)):
                    if not isinstance(vals, (list, tuple)):
                        vals = [vals]
                    result.update(zip(names, vals))
                else:
                    result[names] = vals
            return result
        finally:
            if was_training:
                self.model.train()

    def predict(self, data: Iterable):
        run = self._ensure_eval_fn()
        params, buffers = self._state()
        was_training = self.model.training
        self.model.eval()
        try:
            outs = []
            for batch in data:
                inputs = batch[0] if isinstance(batch, (tuple, list)) else batch
                with self.mesh:
                    outs.append(np.asarray(
                        run(params, buffers, jnp.asarray(inputs))))
            return outs
        finally:
            if was_training:
                self.model.train()

    # --------------------------------------------------------------- state
    def save(self, path: str):
        from ...framework.io import save as pt_save

        if self._train_step is not None:
            self._train_step.sync_to_model()
        pt_save(self.model.state_dict(), path)

    def load(self, path: str):
        from ...framework.io import load as pt_load

        self.model.set_state_dict(pt_load(path))
        if self._train_step is not None:
            self._train_step.load_from_model()
