"""Cost-model-guided mesh/sharding search.

Reference parity: ``python/paddle/distributed/auto_parallel/tuner/``
(``Planner``/``ParallelTuner`` searching dist-attr assignments) +
``cost/`` (comp/comm cost model, ``comm_op_cost.py``,
``cluster.py`` hardware model). TPU-native reformulation: instead of
scoring per-op dist_attrs over a ProgramDesc, score (dp, mp, sdp)
factorizations of the chip count with an analytic roofline model —
compute FLOPs ride the MXU, DP grad all-reduce and TP activation
collectives ride ICI — then hand the winner to DistributedTrainStep,
whose GSPMD compilation realizes it.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


@dataclass
class ClusterSpec:
    """Hardware model (reference ``cluster.py``). Defaults ~ one TPU v5p
    chip / ICI link; override per deployment."""

    peak_flops: float = 459e12          # bf16 FLOPs/s per chip
    ici_bandwidth: float = 90e9         # bytes/s per link direction
    dcn_bandwidth: float = 6.25e9       # bytes/s per host NIC
    hbm_per_chip: float = 95e9          # bytes
    mfu: float = 0.4                    # achievable fraction of peak


@dataclass
class ModelSpec:
    """What the planner needs to know about the workload."""

    n_params: float                     # total trainable params
    flops_per_token: float              # fwd+bwd FLOPs per token
    hidden_size: int
    n_layers: int
    seq_len: int
    global_batch_tokens: float          # tokens per step
    bytes_per_param: float = 2.0        # bf16 params
    optim_state_mult: float = 6.0       # adam: p32 + m32 + v32 over bf16 p
    remat: bool = True                  # activation recompute on (the
    #                                     framework default for big models):
    #                                     only layer inputs live across bwd


@dataclass
class PlanCandidate:
    dp: int
    mp: int
    sdp: int                            # ZeRO-sharded data parallel
    step_time: float
    compute_time: float
    comm_time: float
    mem_per_chip: float
    feasible: bool

    @property
    def axes(self) -> Dict[str, int]:
        out = {}
        if self.dp > 1:
            out["dp"] = self.dp
        if self.sdp > 1:
            out["sdp"] = self.sdp
        if self.mp > 1:
            out["mp"] = self.mp
        return out or {"dp": 1}


class CostModel:
    """Analytic step-time + memory estimator for a (dp, sdp, mp) plan."""

    def __init__(self, model: ModelSpec, cluster: Optional[ClusterSpec] = None):
        self.model = model
        self.cluster = cluster or ClusterSpec()

    def evaluate(self, dp: int, mp: int, sdp: int = 1) -> PlanCandidate:
        m, c = self.model, self.cluster
        n_dev = dp * mp * sdp
        data_par = dp * sdp

        # ---- compute: FLOPs spread over all chips at target MFU
        total_flops = m.flops_per_token * m.global_batch_tokens
        compute_time = total_flops / (n_dev * c.peak_flops * c.mfu)

        # ---- comm over ICI
        comm_time = 0.0
        # DP/sdp grad reduction: ring all-reduce 2*(k-1)/k of grad bytes
        # (reduce-scatter+all-gather for sdp) of the mp-sharded params
        grad_bytes = m.n_params * m.bytes_per_param / mp
        if data_par > 1:
            comm_time += 2 * (data_par - 1) / data_par * grad_bytes \
                / c.ici_bandwidth
        # TP: 2 all-reduces of activations per layer (attn out + mlp out),
        # fwd and bwd -> 4, each 2*(mp-1)/mp of activation bytes
        if mp > 1:
            act_bytes = (m.global_batch_tokens / data_par) * m.hidden_size \
                * m.bytes_per_param
            comm_time += m.n_layers * 4 * 2 * (mp - 1) / mp * act_bytes \
                / c.ici_bandwidth
        # sdp extra: parameter all-gather before use (ZeRO-3 style counted
        # only when sdp shards params; our stage2 default shards opt+grads,
        # params gather cost ~ param bytes once per step)
        if sdp > 1:
            comm_time += grad_bytes / c.ici_bandwidth

        # ---- memory per chip: params+opt state shard over mp always and
        # over sdp when ZeRO is on; dp replicates
        param_bytes = m.n_params * m.bytes_per_param
        state_bytes = param_bytes * m.optim_state_mult
        zero_shard = sdp if sdp > 1 else 1
        mem = (param_bytes + state_bytes) / mp / zero_shard
        # activations per chip: ~14 bytes/elem-layer stored without remat
        # (attn+mlp intermediates), ~2 with remat (layer inputs only; the
        # rest is recomputed in backward) — Korthikanti et al. accounting
        act_factor = 2.0 if m.remat else 14.0
        act = (m.global_batch_tokens / data_par) * m.hidden_size \
            * m.n_layers * act_factor / mp
        mem_per_chip = mem + act

        return PlanCandidate(
            dp=dp, mp=mp, sdp=sdp,
            step_time=compute_time + comm_time,
            compute_time=compute_time, comm_time=comm_time,
            mem_per_chip=mem_per_chip,
            feasible=mem_per_chip <= c.hbm_per_chip)


def _factorizations(n: int) -> List[Tuple[int, int, int]]:
    out = []
    for mp in range(1, n + 1):
        if n % mp:
            continue
        rest = n // mp
        for sdp in range(1, rest + 1):
            if rest % sdp:
                continue
            out.append((rest // sdp, mp, sdp))
    return out


class Planner:
    """Search all (dp, mp, sdp) factorizations of the device count and
    rank by modeled step time (reference ``ParallelTuner`` with the
    search space collapsed to the mesh axes GSPMD needs)."""

    def __init__(self, model: ModelSpec, n_devices: int,
                 cluster: Optional[ClusterSpec] = None,
                 max_mp: Optional[int] = None):
        self.cost = CostModel(model, cluster)
        self.n_devices = n_devices
        self.max_mp = max_mp

    def candidates(self) -> List[PlanCandidate]:
        cands = []
        for dp, mp, sdp in _factorizations(self.n_devices):
            if self.max_mp and mp > self.max_mp:
                continue
            if self.cost.model.hidden_size % mp:
                continue  # TP must divide heads/hidden
            cands.append(self.cost.evaluate(dp, mp, sdp))
        return sorted(cands, key=lambda c: (not c.feasible, c.step_time))

    def best(self) -> PlanCandidate:
        cands = self.candidates()
        if not cands:
            raise ValueError(f"no factorization of {self.n_devices} devices")
        best = cands[0]
        if not best.feasible:
            raise ValueError(
                f"no feasible plan fits HBM: best candidate needs "
                f"{best.mem_per_chip / 1e9:.1f} GB/chip")
        return best


def plan_mesh(model: ModelSpec, n_devices: Optional[int] = None,
              cluster: Optional[ClusterSpec] = None, **kw):
    """One-call planner: returns (mesh, plan). The mesh is created with
    the winning axes and can be passed straight to DistributedTrainStep /
    fleet."""
    import jax

    from ..mesh import init_mesh

    n = n_devices or len(jax.devices())
    plan = Planner(model, n, cluster, **kw).best()
    return init_mesh(plan.axes), plan
