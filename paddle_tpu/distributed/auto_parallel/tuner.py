"""Parallel-plan tuner: 5-axis search with measured cost calibration.

Reference parity: ``python/paddle/distributed/auto_parallel/tuner/``
(``parallel_tuner.py`` searching dist-attr assignments over process-mesh
shapes, ``profiler.py`` measured re-ranking, ``optimization_tuner.py``) and
``cost/`` (comp/comm cost model calibrated from a cluster description).

TPU-native reformulation: the search space is the GSPMD mesh itself —
(dp, sdp/ZeRO, mp, pp, sp) factorizations of the chip count — scored by a
roofline cost model whose constants come from MEASUREMENTS:

- achieved MFU from the recorded end-to-end bench (``bench.py`` JSON /
  ``tools/op_bench_baseline_tpu.json``),
- ICI bandwidth from a live collective micro-bench (:func:`measure_ici`)
  when a mesh is available.

``ParallelTuner.tune()`` emits ranked candidates; ``validate()`` re-ranks
the top few by actually compiling + timing a scaled-down
DistributedTrainStep on a (possibly host-simulated) mesh — the
``profiler.py`` measured pass.
"""
from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .planner import ClusterSpec, ModelSpec

__all__ = ["ParallelTuner", "TunedPlan", "calibrate_cluster", "measure_ici"]


@dataclass
class TunedPlan:
    """One (dp, sdp, mp, pp, sp) candidate with modeled costs."""

    dp: int
    sdp: int
    mp: int
    pp: int
    sp: int
    step_time: float
    compute_time: float
    comm_time: float
    bubble_time: float
    mem_per_chip: float
    feasible: bool
    measured_time: Optional[float] = None

    @property
    def n_devices(self) -> int:
        return self.dp * self.sdp * self.mp * self.pp * self.sp

    @property
    def axes(self) -> Dict[str, int]:
        out = {}
        for name in ("dp", "sdp", "mp", "pp", "sp"):
            v = getattr(self, name)
            if v > 1:
                out[name] = v
        return out or {"dp": 1}

    def describe(self) -> str:
        t = self.step_time * 1e3
        return (f"{self.axes} step={t:.1f}ms (comp={self.compute_time*1e3:.1f}"
                f" comm={self.comm_time*1e3:.1f} bubble="
                f"{self.bubble_time*1e3:.1f}) mem={self.mem_per_chip/1e9:.1f}GB"
                f"{'' if self.feasible else ' INFEASIBLE'}")


def calibrate_cluster(bench_json: Optional[Any] = None,
                      base: Optional[ClusterSpec] = None,
                      ici_bandwidth: Optional[float] = None) -> ClusterSpec:
    """Build a :class:`ClusterSpec` from measurements instead of defaults.

    ``bench_json``: a path or dict in ``bench.py`` output shape — its
    ``extra.mfu`` replaces the default achievable-MFU guess (the single
    most load-bearing constant in the roofline). ``ici_bandwidth``: from
    :func:`measure_ici` when real chips are meshed.
    """
    spec = base or ClusterSpec()
    if bench_json is not None:
        if isinstance(bench_json, str):
            with open(bench_json) as f:
                bench_json = json.load(f)
        # accept both the raw bench line and the driver's BENCH_r{N} wrapper
        payload = bench_json.get("parsed", bench_json)
        mfu = payload.get("extra", {}).get("mfu")
        if mfu:
            spec = replace(spec, mfu=float(mfu))
    if ici_bandwidth:
        spec = replace(spec, ici_bandwidth=float(ici_bandwidth))
    return spec


def measure_ici(mesh=None, size_mb: float = 64.0, iters: int = 5) -> float:
    """Measured all-reduce bandwidth (bytes/s per chip) over the mesh's
    first axis — the collectives micro-bench feeding the cost model's
    ``ici_bandwidth``. Runs a psum inside shard_map and times it."""
    import jax
    import jax.numpy as jnp
    from ...framework.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        from ..mesh import current_mesh

        mesh = current_mesh()
    axis = mesh.axis_names[0]
    k = mesh.shape[axis]
    elems = int(size_mb * 1e6 / 4)
    # (k, elems) sharded over the ring axis: each chip holds ONE row of
    # size_mb (replicated across any other mesh axes)
    x = jnp.ones((k, elems), jnp.float32)

    @jax.jit
    def allreduce(v):
        return shard_map(lambda u: jax.lax.psum(u, axis), mesh=mesh,
                         in_specs=P(axis), out_specs=P(axis))(v)

    out = allreduce(x)
    float(np.asarray(out).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(x)
    float(np.asarray(out).ravel()[0])
    dt = (time.perf_counter() - t0) / iters
    # ring all-reduce moves 2*(k-1)/k of each chip's LOCAL shard
    return (2 * (k - 1) / max(k, 1)) * (elems * 4) / dt


class ParallelTuner:
    """Search (dp, sdp, mp, pp, sp) factorizations of the device count,
    rank by a measured-calibrated roofline, optionally re-rank the top few
    by real compiled-step timings.
    """

    def __init__(self, model: ModelSpec, n_devices: int,
                 cluster: Optional[ClusterSpec] = None,
                 micro_batches: int = 8, num_heads: Optional[int] = None):
        self.model = model
        self.n_devices = int(n_devices)
        self.cluster = cluster or ClusterSpec()
        self.micro_batches = int(micro_batches)
        self.num_heads = num_heads

    # ------------------------------------------------------------- model
    def evaluate(self, dp: int, sdp: int, mp: int, pp: int,
                 sp: int) -> TunedPlan:
        m, c = self.model, self.cluster
        n_dev = dp * sdp * mp * pp * sp
        data_par = dp * sdp
        # tokens processed per (dp*sdp) replica group per step
        tokens_per_group = m.global_batch_tokens / data_par

        # ---- compute + pipeline bubble
        total_flops = m.flops_per_token * m.global_batch_tokens
        compute_time = total_flops / (n_dev * c.peak_flops * c.mfu)
        bubble_time = 0.0
        if pp > 1:
            # 1F1B bubble: (pp-1)/micro_batches of the pipeline's busy time
            bubble_time = compute_time * (pp - 1) / max(self.micro_batches, 1)

        # ---- comm over ICI
        comm_time = 0.0
        grad_bytes = m.n_params * m.bytes_per_param / (mp * pp)
        if data_par > 1:
            comm_time += 2 * (data_par - 1) / data_par * grad_bytes \
                / c.ici_bandwidth
        if sdp > 1:
            # ZeRO param all-gather once per step
            comm_time += grad_bytes / c.ici_bandwidth
        if mp > 1:
            # 2 activation all-reduces per layer fwd, 2 bwd
            act_bytes = tokens_per_group / sp * m.hidden_size \
                * m.bytes_per_param
            comm_time += m.n_layers * 4 * 2 * (mp - 1) / mp * act_bytes \
                / c.ici_bandwidth
        if sp > 1:
            # ring attention: KV blocks circulate the full ring per layer,
            # fwd + bwd (2x); each hop moves the local KV shard
            kv_bytes = tokens_per_group / sp * m.hidden_size * 2 \
                * m.bytes_per_param
            comm_time += m.n_layers * 2 * (sp - 1) * kv_bytes \
                / c.ici_bandwidth
        if pp > 1:
            # p2p activations at each stage boundary per micro-batch
            micro_act = tokens_per_group / max(self.micro_batches, 1) \
                * m.hidden_size * m.bytes_per_param / sp
            comm_time += 2 * (pp - 1) * self.micro_batches * micro_act \
                / c.ici_bandwidth

        # ---- memory per chip
        param_bytes = m.n_params * m.bytes_per_param
        state_bytes = param_bytes * m.optim_state_mult
        zero_shard = sdp if sdp > 1 else 1
        mem = (param_bytes + state_bytes) / (mp * pp) / zero_shard
        act_factor = 2.0 if m.remat else 14.0
        act = tokens_per_group / sp * m.hidden_size \
            * (m.n_layers / pp) * act_factor / mp
        if pp > 1:
            # 1F1B holds up to pp in-flight micro-batches of activations
            act = act / max(self.micro_batches, 1) * min(pp, self.micro_batches)
        mem_per_chip = mem + act

        return TunedPlan(
            dp=dp, sdp=sdp, mp=mp, pp=pp, sp=sp,
            step_time=compute_time + comm_time + bubble_time,
            compute_time=compute_time, comm_time=comm_time,
            bubble_time=bubble_time, mem_per_chip=mem_per_chip,
            feasible=mem_per_chip <= c.hbm_per_chip)

    # ------------------------------------------------------------ search
    def _valid_axes(self, dp, sdp, mp, pp, sp) -> bool:
        m = self.model
        if m.hidden_size % mp:
            return False
        if self.num_heads and self.num_heads % (mp * sp):
            return False
        if m.n_layers % pp:
            return False
        if m.seq_len % sp or (sp > 1 and m.seq_len // sp < 128):
            return False
        # batch must split over the data axes
        if (m.global_batch_tokens / m.seq_len) % (dp * sdp):
            return False
        return True

    def candidates(self) -> List[TunedPlan]:
        n = self.n_devices
        seen = set()
        out = []
        for mp in _divisors(n):
            for pp in _divisors(n // mp):
                for sp in _divisors(n // (mp * pp)):
                    rest = n // (mp * pp * sp)
                    for sdp in _divisors(rest):
                        dp = rest // sdp
                        key = (dp, sdp, mp, pp, sp)
                        if key in seen:
                            continue
                        seen.add(key)
                        if not self._valid_axes(*key):
                            continue
                        out.append(self.evaluate(*key))
        return sorted(out, key=lambda c: (not c.feasible, c.step_time))

    def tune(self, top_k: Optional[int] = None) -> List[TunedPlan]:
        cands = self.candidates()
        if not cands:
            raise ValueError(
                f"no valid plan for {self.n_devices} devices and this model")
        return cands[:top_k] if top_k else cands

    def best(self) -> TunedPlan:
        best = self.tune()[0]
        if not best.feasible:
            raise ValueError(
                f"no feasible plan fits HBM; closest: {best.describe()}")
        return best

    # ---------------------------------------------------------- measured
    def validate(self, plans: Sequence[TunedPlan],
                 step_builder: Callable[[TunedPlan], Callable[[], Any]],
                 steps: int = 3) -> List[TunedPlan]:
        """Measured re-rank (the reference tuner's ``profiler.py`` pass):
        ``step_builder(plan)`` returns a zero-arg callable running ONE
        training step under that plan's mesh; each plan is timed after a
        warmup step and returned sorted by measured time."""
        measured = []
        for plan in plans:
            run = step_builder(plan)
            run()  # compile + warm
            t0 = time.perf_counter()
            for _ in range(steps):
                out = run()
            _materialize(out)
            measured.append(replace(
                plan, measured_time=(time.perf_counter() - t0) / steps))
        return sorted(measured, key=lambda c: c.measured_time)


def _materialize(out) -> None:
    import jax

    leaves = jax.tree.leaves(out)
    if leaves:
        np.asarray(leaves[0])


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]
