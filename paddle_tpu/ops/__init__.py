"""Tensor op library.

The reference's PHI kernel library (255k LoC of per-backend CUDA/CPU kernels,
``paddle/phi/kernels/``) collapses on TPU into thin jnp/lax wrappers: XLA owns
codegen, fusion, and layout. Pallas kernels live in ``paddle_tpu.kernels`` for
the few ops where the compiler needs help (attention, embedding all2all).
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
