"""Comparison / logical ops (reference: ``python/paddle/tensor/logic.py``)."""
from __future__ import annotations

import jax.numpy as jnp


def equal(x, y, name=None):
    return jnp.equal(x, y)


def not_equal(x, y, name=None):
    return jnp.not_equal(x, y)


def greater_than(x, y, name=None):
    return jnp.greater(x, y)


def greater_equal(x, y, name=None):
    return jnp.greater_equal(x, y)


def less_than(x, y, name=None):
    return jnp.less(x, y)


def less_equal(x, y, name=None):
    return jnp.less_equal(x, y)


def equal_all(x, y, name=None):
    return jnp.array_equal(x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def logical_and(x, y, out=None, name=None):
    return jnp.logical_and(x, y)


def logical_or(x, y, out=None, name=None):
    return jnp.logical_or(x, y)


def logical_xor(x, y, out=None, name=None):
    return jnp.logical_xor(x, y)


def logical_not(x, out=None, name=None):
    return jnp.logical_not(x)


def bitwise_and(x, y, out=None, name=None):
    return jnp.bitwise_and(x, y)


def bitwise_or(x, y, out=None, name=None):
    return jnp.bitwise_or(x, y)


def bitwise_xor(x, y, out=None, name=None):
    return jnp.bitwise_xor(x, y)


def bitwise_not(x, out=None, name=None):
    return jnp.bitwise_not(x)


def bitwise_left_shift(x, y, name=None):
    return jnp.left_shift(x, y)


def bitwise_right_shift(x, y, name=None):
    return jnp.right_shift(x, y)


def is_empty(x, name=None):
    return jnp.asarray(jnp.asarray(x).size == 0)


def is_tensor(x):
    import jax

    return isinstance(x, jax.Array)


# ------------------------------------------------------ breadth additions
def is_complex(x, name=None):
    return bool(jnp.issubdtype(jnp.asarray(x).dtype, jnp.complexfloating))


def is_floating_point(x, name=None):
    return bool(jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating))


def is_integer(x, name=None):
    return bool(jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer))


def union1d(x, y, size=None, name=None):
    """Sorted union (dynamic-shaped: eager by default; pass ``size`` to use
    under jit, padded with the max element — jnp semantics)."""
    return jnp.union1d(jnp.asarray(x), jnp.asarray(y), size=size)


def intersect1d(x, y, assume_unique=False, size=None, name=None):
    return jnp.intersect1d(jnp.asarray(x), jnp.asarray(y),
                           assume_unique=assume_unique, size=size)


def setdiff1d(x, y, assume_unique=False, size=None, name=None):
    return jnp.setdiff1d(jnp.asarray(x), jnp.asarray(y),
                         assume_unique=assume_unique, size=size)
