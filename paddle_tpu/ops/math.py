"""Elementwise and reduction math ops.

Reference parity: ``python/paddle/tensor/math.py`` (5.3k LoC of per-op
dygraph/static dual paths). Here every op is a pure jnp function — XLA fuses
elementwise chains into single TPU kernels, so there is no fused-op registry
to maintain. Paddle semantics kept: ``axis``/``keepdim`` argument names,
None-axis full reduction, broadcast rules.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------- arithmetic
def add(x, y, name=None):
    return jnp.add(x, y)


def subtract(x, y, name=None):
    return jnp.subtract(x, y)


def multiply(x, y, name=None):
    return jnp.multiply(x, y)


def divide(x, y, name=None):
    return jnp.true_divide(x, y)


def floor_divide(x, y, name=None):
    return jnp.floor_divide(x, y)


def mod(x, y, name=None):
    return jnp.mod(x, y)


remainder = mod


def pow(x, y, name=None):  # noqa: A001 - paddle name
    return jnp.power(x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = jnp.asarray(x)
    s = jnp.asarray(scale, x.dtype)
    b = jnp.asarray(bias, x.dtype)
    out = x * s + b if bias_after_scale else (x + b) * s
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def maximum(x, y, name=None):
    return jnp.maximum(x, y)


def minimum(x, y, name=None):
    return jnp.minimum(x, y)


def fmax(x, y, name=None):
    return jnp.fmax(x, y)


def fmin(x, y, name=None):
    return jnp.fmin(x, y)


def abs(x, name=None):  # noqa: A001
    return jnp.abs(x)


def neg(x, name=None):
    return jnp.negative(x)


def sign(x, name=None):
    return jnp.sign(x)


def reciprocal(x, name=None):
    return jnp.reciprocal(x)


def square(x, name=None):
    return jnp.square(x)


def sqrt(x, name=None):
    return jnp.sqrt(x)


def rsqrt(x, name=None):
    return jax.lax.rsqrt(x)


def exp(x, name=None):
    return jnp.exp(x)


def expm1(x, name=None):
    return jnp.expm1(x)


def log(x, name=None):
    return jnp.log(x)


def log2(x, name=None):
    return jnp.log2(x)


def log10(x, name=None):
    return jnp.log10(x)


def log1p(x, name=None):
    return jnp.log1p(x)


def floor(x, name=None):
    return jnp.floor(x)


def ceil(x, name=None):
    return jnp.ceil(x)


def round(x, name=None):  # noqa: A001
    return jnp.round(x)


def trunc(x, name=None):
    return jnp.trunc(x)


def frac(x, name=None):
    return x - jnp.trunc(x)


# ---------------------------------------------------------------- trig
def sin(x, name=None):
    return jnp.sin(x)


def cos(x, name=None):
    return jnp.cos(x)


def tan(x, name=None):
    return jnp.tan(x)


def asin(x, name=None):
    return jnp.arcsin(x)


def acos(x, name=None):
    return jnp.arccos(x)


def atan(x, name=None):
    return jnp.arctan(x)


def atan2(x, y, name=None):
    return jnp.arctan2(x, y)


def sinh(x, name=None):
    return jnp.sinh(x)


def cosh(x, name=None):
    return jnp.cosh(x)


def tanh(x, name=None):
    return jnp.tanh(x)


def asinh(x, name=None):
    return jnp.arcsinh(x)


def acosh(x, name=None):
    return jnp.arccosh(x)


def atanh(x, name=None):
    return jnp.arctanh(x)


def deg2rad(x, name=None):
    return jnp.deg2rad(x)


def rad2deg(x, name=None):
    return jnp.rad2deg(x)


# ---------------------------------------------------------------- special
def erf(x, name=None):
    return jax.scipy.special.erf(x)


def erfinv(x, name=None):
    return jax.scipy.special.erfinv(x)


def lgamma(x, name=None):
    return jax.scipy.special.gammaln(x)


def digamma(x, name=None):
    return jax.scipy.special.digamma(x)


def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


def logit(x, eps=None, name=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x) - jnp.log1p(-x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def logaddexp(x, y, name=None):
    return jnp.logaddexp(x, y)


# ---------------------------------------------------------------- reductions
def _norm_axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return axis


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    from ..framework.dtype import convert_dtype

    return jnp.sum(x, axis=_norm_axis(axis), dtype=convert_dtype(dtype), keepdims=keepdim)


def mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(x, axis=_norm_axis(axis), keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    from ..framework.dtype import convert_dtype

    return jnp.prod(x, axis=_norm_axis(axis), dtype=convert_dtype(dtype), keepdims=keepdim)


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return jnp.max(x, axis=_norm_axis(axis), keepdims=keepdim)


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return jnp.min(x, axis=_norm_axis(axis), keepdims=keepdim)


def amax(x, axis=None, keepdim=False, name=None):
    return jnp.amax(x, axis=_norm_axis(axis), keepdims=keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return jnp.amin(x, axis=_norm_axis(axis), keepdims=keepdim)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return jnp.all(x, axis=_norm_axis(axis), keepdims=keepdim)


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return jnp.any(x, axis=_norm_axis(axis), keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return jnp.count_nonzero(x, axis=_norm_axis(axis), keepdims=keepdim)


def cumsum(x, axis=None, dtype=None, name=None):
    from ..framework.dtype import convert_dtype

    x = jnp.asarray(x)
    if axis is None:
        x, axis = x.reshape(-1), 0
    return jnp.cumsum(x, axis=axis, dtype=convert_dtype(dtype))


def cumprod(x, dim=None, dtype=None, name=None):
    from ..framework.dtype import convert_dtype

    return jnp.cumprod(x, axis=dim, dtype=convert_dtype(dtype))


def _cum_extreme(x, axis, dtype, is_max):
    from ..framework.dtype import convert_dtype

    x = jnp.asarray(x)
    if axis is None:
        x, axis = x.reshape(-1), 0
    axis = axis % x.ndim
    idx = jnp.arange(x.shape[axis]).reshape(
        [-1 if i == axis else 1 for i in range(x.ndim)]
    )
    idx = jnp.broadcast_to(idx, x.shape)

    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = (bv >= av) if is_max else (bv <= av)
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    values, ind = jax.lax.associative_scan(combine, (x, idx), axis=axis)
    return values, ind.astype(convert_dtype(dtype))


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, is_max=True)


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, is_max=False)


def clip(x, min=None, max=None, name=None):  # noqa: A002
    return jnp.clip(x, min, max)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..framework.dtype import convert_dtype

    return jnp.nansum(x, axis=_norm_axis(axis), dtype=convert_dtype(dtype), keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False, name=None):
    return jnp.nanmean(x, axis=_norm_axis(axis), keepdims=keepdim)


# ---------------------------------------------------------------- tests / misc
def isfinite(x, name=None):
    return jnp.isfinite(x)


def isinf(x, name=None):
    return jnp.isinf(x)


def isnan(x, name=None):
    return jnp.isnan(x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * x)


def inner(x, y, name=None):
    return jnp.inner(x, y)


def outer(x, y, name=None):
    return jnp.outer(x, y)


def kron(x, y, name=None):
    return jnp.kron(x, y)


def gcd(x, y, name=None):
    return jnp.gcd(x, y)


def lcm(x, y, name=None):
    return jnp.lcm(x, y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


def heaviside(x, y, name=None):
    return jnp.heaviside(x, y)


def rot90(x, k=1, axes=(0, 1), name=None):
    return jnp.rot90(x, k=k, axes=tuple(axes))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return beta * input + alpha * jnp.matmul(x, y)


def lerp(x, y, weight, name=None):
    return x + jnp.asarray(weight, jnp.asarray(x).dtype) * (y - x)


# ------------------------------------------------------ breadth additions
# (reference python/paddle/tensor/math.py — the long tail of the ~500-fn
# tensor API; each is a direct XLA-fusable jnp mapping)
def add_n(inputs, name=None):
    """Sum a list of same-shape tensors (reference ``sum_op`` / add_n)."""
    if not isinstance(inputs, (list, tuple)):
        return jnp.asarray(inputs)
    out = jnp.asarray(inputs[0])
    for t in inputs[1:]:
        out = out + jnp.asarray(t)
    return out


def angle(x, name=None):
    return jnp.angle(x)


def sgn(x, name=None):
    """sign for real; x/|x| for complex (0 where |x| == 0)."""
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, jnp.zeros_like(x), x / jnp.where(mag == 0, 1, mag))
    return jnp.sign(x)


def frexp(x, name=None):
    return jnp.frexp(x)


def ldexp(x, y, name=None):
    return jnp.ldexp(x, y)


def copysign(x, y, name=None):
    return jnp.copysign(x, y)


def hypot(x, y, name=None):
    return jnp.hypot(x, y)


def signbit(x, name=None):
    return jnp.signbit(x)


def sinc(x, name=None):
    return jnp.sinc(x)


def i0(x, name=None):
    return jax.scipy.special.i0(x)


def i0e(x, name=None):
    return jax.scipy.special.i0e(x)


def i1(x, name=None):
    return jax.scipy.special.i1(x)


def i1e(x, name=None):
    return jax.scipy.special.i1e(x)


def xlogy(x, y, name=None):
    return jax.scipy.special.xlogy(x, y)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def increment(x, value=1.0, name=None):
    x = jnp.asarray(x)
    return x + jnp.asarray(value, x.dtype)


def multiplex(inputs, index, name=None):
    """Row-wise select across candidate tensors: ``out[i] =
    inputs[index[i]][i]`` (reference ``multiplex`` op)."""
    stacked = jnp.stack([jnp.asarray(t) for t in inputs])  # [K, N, ...]
    idx = jnp.asarray(index).reshape(-1).astype(jnp.int32)
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """Running log-sum-exp (numerically stable via associative scan)."""
    x = jnp.asarray(x)
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        x = x.astype(convert_dtype(dtype))
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=axis)


def renorm(x, p, axis, max_norm, name=None):
    """Clamp the p-norm of every slice along ``axis`` to ``max_norm``."""
    x = jnp.asarray(x)
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=reduce_axes, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7),
                       jnp.ones_like(norms))
    return x * factor


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is None and dx is None:
        dx = 1.0
    return jnp.trapezoid(jnp.asarray(y), x=x, dx=dx if dx is not None else 1.0,
                         axis=axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = jnp.asarray(y)
    n = y.shape[axis]
    lo = jax.lax.slice_in_dim(y, 0, n - 1, axis=axis)
    hi = jax.lax.slice_in_dim(y, 1, n, axis=axis)
    if x is not None:
        x = jnp.asarray(x)
        if x.ndim == 1:
            shape = [1] * y.ndim
            shape[axis] = x.shape[0]
            x = x.reshape(shape)
        d = jax.lax.slice_in_dim(x, 1, n, axis=axis) - \
            jax.lax.slice_in_dim(x, 0, n - 1, axis=axis)
    else:
        d = dx if dx is not None else 1.0
    return jnp.cumsum((lo + hi) * d / 2.0, axis=axis)


def floor_mod(x, y, name=None):
    return jnp.mod(x, y)


def rank(x, name=None):
    """Tensor of the input's ndim (reference ``rank``)."""
    return jnp.asarray(jnp.asarray(x).ndim, jnp.int32)


def shape(x, name=None):
    """Shape as an int32 tensor (reference ``shape`` returns a tensor)."""
    return jnp.asarray(jnp.asarray(x).shape, jnp.int32)


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def polar(abs, angle, name=None):  # noqa: A002 - paddle names
    return jnp.asarray(abs) * jnp.exp(1j * jnp.asarray(angle))


# In-place variants. jax arrays are immutable, so these return the result
# instead of mutating — under ``paddle_tpu.eager`` the Tensor wrapper
# rebinds, giving reference-compatible ``x.add_(y)`` call sites.
def _make_inplace(fn):
    def op_(x, *args, **kwargs):
        return fn(x, *args, **kwargs)

    op_.__name__ = fn.__name__ + "_"
    op_.__doc__ = (f"Out-of-place stand-in for paddle's in-place "
                   f"``{fn.__name__}_`` (jax arrays are immutable).")
    return op_


add_ = _make_inplace(add)
subtract_ = _make_inplace(subtract)
multiply_ = _make_inplace(multiply)
scale_ = _make_inplace(scale)
remainder_ = _make_inplace(mod)
floor_mod_ = _make_inplace(mod)
lerp_ = _make_inplace(lerp)
increment_ = _make_inplace(increment)
nan_to_num_ = _make_inplace(nan_to_num)
ceil_ = _make_inplace(ceil)
exp_ = _make_inplace(exp)
floor_ = _make_inplace(floor)
round_ = _make_inplace(round)
rsqrt_ = _make_inplace(rsqrt)
sqrt_ = _make_inplace(sqrt)
tanh_ = _make_inplace(tanh)
reciprocal_ = _make_inplace(reciprocal)
clip_ = _make_inplace(clip)
erfinv_ = _make_inplace(erfinv)
abs_ = _make_inplace(abs)
sigmoid_ = _make_inplace(sigmoid)


def gammaln(x, name=None):
    return jax.scipy.special.gammaln(jnp.asarray(x, jnp.float32))


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) (reference args order:
    input x is the shape param)."""
    return jax.scipy.special.gammainc(jnp.asarray(x, jnp.float32),
                                      jnp.asarray(y, jnp.float32))


def gammaincc(x, y, name=None):
    return jax.scipy.special.gammaincc(jnp.asarray(x, jnp.float32),
                                       jnp.asarray(y, jnp.float32))


def multigammaln(x, p, name=None):
    return jax.scipy.special.multigammaln(jnp.asarray(x, jnp.float32), int(p))


def polygamma(x, n, name=None):
    return jax.scipy.special.polygamma(int(n), jnp.asarray(x, jnp.float32))


def nextafter(x, y, name=None):
    return jnp.nextafter(jnp.asarray(x), jnp.asarray(y))


def isposinf(x, name=None):
    return jnp.isposinf(jnp.asarray(x))


def isneginf(x, name=None):
    return jnp.isneginf(jnp.asarray(x))


def isreal(x, name=None):
    return jnp.isreal(jnp.asarray(x))
