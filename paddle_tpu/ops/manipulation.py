"""Shape / layout manipulation ops.

Reference parity: ``python/paddle/tensor/manipulation.py`` (4.8k LoC).
All shape arguments must be static under ``jit`` — XLA compiles per shape.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.dtype import convert_dtype


def reshape(x, shape, name=None):
    return jnp.reshape(x, tuple(shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = jnp.asarray(x)
    nd = x.ndim
    start = start_axis % nd
    stop = stop_axis % nd
    new_shape = x.shape[:start] + (-1,) + x.shape[stop + 1 :]
    return jnp.reshape(x, new_shape)


def transpose(x, perm, name=None):
    return jnp.transpose(x, tuple(perm))


def moveaxis(x, source, destination, name=None):
    return jnp.moveaxis(x, source, destination)


def swapaxes(x, axis0, axis1, name=None):
    return jnp.swapaxes(x, axis0, axis1)


def t(x, name=None):
    x = jnp.asarray(x)
    if x.ndim > 2:
        raise ValueError("paddle.t only supports tensors with ndim <= 2")
    return x.T


def concat(x, axis=0, name=None):
    return jnp.concatenate([jnp.asarray(t) for t in x], axis=axis)


def stack(x, axis=0, name=None):
    return jnp.stack([jnp.asarray(t) for t in x], axis=axis)


def unstack(x, axis=0, num=None, name=None):
    x = jnp.asarray(x)
    n = x.shape[axis] if num is None else num
    return [jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis)]


def split(x, num_or_sections, axis=0, name=None):
    x = jnp.asarray(x)
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    # sections list, possibly containing one -1
    sections = list(num_or_sections)
    total = x.shape[axis]
    if -1 in sections:
        known = sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = total - known
    offsets = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        offsets.append(acc)
    return jnp.split(x, offsets, axis=axis)


def chunk(x, chunks, axis=0, name=None):
    return jnp.array_split(jnp.asarray(x), chunks, axis=axis)


def squeeze(x, axis=None, name=None):
    x = jnp.asarray(x)
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, int):
        axis = [axis]
    # paddle ignores non-unit axes in squeeze
    axes = tuple(a % x.ndim for a in axis if x.shape[a % x.ndim] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


def unsqueeze(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    x = jnp.asarray(x)
    for a in sorted(a % (x.ndim + 1) for a in axis):
        x = jnp.expand_dims(x, a)
    return x


def expand(x, shape, name=None):
    x = jnp.asarray(x)
    shape = list(shape)
    # paddle allows -1 meaning "keep this dim"
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1:
            shape[i] = x.shape[i - offset]
    return jnp.broadcast_to(x, tuple(shape))


def expand_as(x, y, name=None):
    return jnp.broadcast_to(jnp.asarray(x), jnp.asarray(y).shape)


def broadcast_to(x, shape, name=None):
    return jnp.broadcast_to(x, tuple(shape))


def broadcast_tensors(inputs, name=None):
    return list(jnp.broadcast_arrays(*inputs))


def tile(x, repeat_times, name=None):
    return jnp.tile(jnp.asarray(x), tuple(repeat_times))


def repeat_interleave(x, repeats, axis=None, name=None):
    return jnp.repeat(jnp.asarray(x), repeats, axis=axis)


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, (list, tuple)):
        shifts = tuple(shifts)
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return jnp.roll(x, shifts, axis=axis)


def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


def cast(x, dtype):
    return jnp.asarray(x).astype(convert_dtype(dtype))


import builtins as _builtins

slice_builtin = _builtins.slice


def slice(x, axes, starts, ends):  # noqa: A001
    x = jnp.asarray(x)
    idx = [slice_builtin(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = slice_builtin(s, e)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = jnp.asarray(x)
    idx = [slice_builtin(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice_builtin(s, e, st)
    return x[tuple(idx)]


def gather(x, index, axis=0, name=None):
    return jnp.take(jnp.asarray(x), jnp.asarray(index), axis=axis)


def gather_nd(x, index, name=None):
    x, index = jnp.asarray(x), jnp.asarray(index)
    # index: [..., k] indexes first k dims of x
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = jnp.asarray(x), jnp.asarray(index), jnp.asarray(updates)
    if overwrite:
        return x.at[index].set(updates)
    # paddle overwrite=False: zero the rows then accumulate
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    x, index = jnp.asarray(x), jnp.asarray(index)
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def scatter_nd(index, updates, shape, name=None):
    zeros = jnp.zeros(tuple(shape), dtype=jnp.asarray(updates).dtype)
    return scatter_nd_add(zeros, index, updates)


def put_along_axis(arr, indices, values, axis, reduce="assign"):
    arr, indices = jnp.asarray(arr), jnp.asarray(indices)
    values = jnp.broadcast_to(jnp.asarray(values, arr.dtype), indices.shape)
    if reduce == "assign":
        return jnp.put_along_axis(arr, indices, values, axis=axis, inplace=False)
    dims = list(range(arr.ndim))
    ix = jnp.meshgrid(*[jnp.arange(s) for s in indices.shape], indexing="ij")
    ix[axis] = indices
    if reduce == "add":
        return arr.at[tuple(ix)].add(values)
    if reduce == "multiply" or reduce == "mul":
        return arr.at[tuple(ix)].multiply(values)
    raise ValueError(f"unknown reduce: {reduce}")


def take_along_axis(arr, indices, axis):
    return jnp.take_along_axis(jnp.asarray(arr), jnp.asarray(indices), axis=axis)


def index_select(x, index, axis=0, name=None):
    return jnp.take(jnp.asarray(x), jnp.asarray(index), axis=axis)


def index_sample(x, index):
    x, index = jnp.asarray(x), jnp.asarray(index)
    return jnp.take_along_axis(x, index, axis=1)


def masked_select(x, mask, name=None):
    # NOTE: output shape is data-dependent; not jittable (same caveat as
    # reference dynamic-shape ops on XLA). Use where() under jit.
    import numpy as np

    return jnp.asarray(np.asarray(x)[np.asarray(mask)])


def masked_fill(x, mask, value, name=None):
    x = jnp.asarray(x)
    return jnp.where(jnp.asarray(mask), jnp.asarray(value, x.dtype), x)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple=False):
    # data-dependent shape: eager-only (see masked_select note)
    import numpy as np

    idx = np.nonzero(np.asarray(x))
    if as_tuple:
        return tuple(jnp.asarray(i) for i in idx)
    return jnp.asarray(np.stack(idx, axis=1))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, name=None):
    import numpy as np

    res = np.unique(
        np.asarray(x),
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if isinstance(res, tuple):
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    import numpy as np

    x_np = np.asarray(x)
    if axis is None:
        x_np = x_np.reshape(-1)
        keep = np.concatenate([[True], x_np[1:] != x_np[:-1]])
    else:
        diff = (x_np.take(range(1, x_np.shape[axis]), axis=axis)
                != x_np.take(range(0, x_np.shape[axis] - 1), axis=axis))
        keep = np.concatenate([[True], diff.any(axis=tuple(i for i in range(x_np.ndim) if i != axis))])
        x_np = np.compress(keep, np.asarray(x), axis=axis)
        out = [jnp.asarray(x_np)]
        return out[0] if len(out) == 1 else tuple(out)
    out = [jnp.asarray(x_np[keep])]
    if return_inverse:
        out.append(jnp.asarray(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.concatenate([idx, [len(x_np)]]))
        out.append(jnp.asarray(counts))
    return out[0] if len(out) == 1 else tuple(out)


def tolist(x):
    return jnp.asarray(x).tolist()


def numel(x, name=None):
    return jnp.asarray(jnp.asarray(x).size)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    """TP vocab-shard index remap (reference: ``c_embedding``'s index logic)."""
    x = jnp.asarray(input)
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    in_shard = (x >= lo) & (x < hi)
    return jnp.where(in_shard, x - lo, ignore_value)


def as_real(x, name=None):
    x = jnp.asarray(x)
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def as_complex(x, name=None):
    x = jnp.asarray(x)
    return jax.lax.complex(x[..., 0], x[..., 1])


def real(x, name=None):
    return jnp.real(x)


def imag(x, name=None):
    return jnp.imag(x)


def conj(x, name=None):
    return jnp.conj(x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    """paddle.nn.functional.pad semantics: ``pad`` is per-dim (low, high) pairs.

    For len(pad) == 2*ndim the order is [d0_lo, d0_hi, d1_lo, ...]. For the
    common conv case (len 4 with 4D input), pads the spatial dims of
    ``data_format``.
    """
    x = jnp.asarray(x)
    pad = list(pad)
    if len(pad) == 2 * x.ndim:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # spatial padding: reversed per-dim pairs over trailing spatial dims
        n_spatial = len(pad) // 2
        width = [(0, 0)] * x.ndim
        if data_format.endswith("C"):  # NHWC / NLC / NDHWC
            spatial_axes = list(range(1, 1 + n_spatial))
        else:  # NCHW / NCL / NCDHW
            spatial_axes = list(range(x.ndim - n_spatial, x.ndim))
        # paddle lists pads innermost-last: [left, right, top, bottom] pairs
        for i, ax in enumerate(reversed(spatial_axes)):
            width[ax] = (pad[2 * i], pad[2 * i + 1])
    jnp_mode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    if jnp_mode == "constant":
        return jnp.pad(x, width, mode="constant", constant_values=value)
    return jnp.pad(x, width, mode=jnp_mode)


# ------------------------------------------------------ breadth additions
# (reference python/paddle/tensor/manipulation.py long tail)
def unbind(x, axis=0, name=None):
    """Split into a list of slices along ``axis`` (reference ``unbind``)."""
    x = jnp.asarray(x)
    axis = axis % x.ndim
    return [jnp.squeeze(s, axis=axis)
            for s in jnp.split(x, x.shape[axis], axis=axis)]


def vsplit(x, num_or_indices, name=None):
    return jnp.vsplit(jnp.asarray(x), num_or_indices)


def hsplit(x, num_or_indices, name=None):
    return jnp.hsplit(jnp.asarray(x), num_or_indices)


def dsplit(x, num_or_indices, name=None):
    return jnp.dsplit(jnp.asarray(x), num_or_indices)


def reverse(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(jnp.asarray(x), axis=tuple(axis))


def crop(x, shape=None, offsets=None, name=None):
    """Static crop: slice ``shape`` starting at ``offsets`` (reference
    ``crop`` op; -1 in shape means "to the end")."""
    x = jnp.asarray(x)
    offsets = list(offsets) if offsets is not None else [0] * x.ndim
    shape = list(shape) if shape is not None else list(x.shape)
    shape = [x.shape[i] - offsets[i] if s == -1 else s
             for i, s in enumerate(shape)]
    return jax.lax.slice(x, offsets, [o + s for o, s in zip(offsets, shape)])


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.diagonal(jnp.asarray(x), offset=offset, axis1=axis1,
                        axis2=axis2)


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Write ``y`` onto the (dim1, dim2) diagonal of ``x`` (reference
    ``fill_diagonal_tensor``)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y, x.dtype)
    k = jnp.diagonal(x, offset=offset, axis1=dim1, axis2=dim2).shape[-1]
    i = jnp.arange(k) + (0 if offset >= 0 else -offset)
    j = jnp.arange(k) + (offset if offset >= 0 else 0)
    # move dim1/dim2 to front, index, move back
    moved = jnp.moveaxis(x, (dim1 % x.ndim, dim2 % x.ndim), (0, 1))
    y_moved = jnp.moveaxis(y, -1, 0) if y.ndim else y
    moved = moved.at[i, j].set(y_moved)
    return jnp.moveaxis(moved, (0, 1), (dim1 % x.ndim, dim2 % x.ndim))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    return fill_diagonal_tensor(x, y, offset=offset, dim1=axis1, dim2=axis2)


def select_scatter(x, values, axis, index, name=None):
    """Embed ``values`` at position ``index`` along ``axis``."""
    x = jnp.asarray(x)
    values = jnp.asarray(values, x.dtype)
    idx = [slice_builtin(None)] * x.ndim  # `slice` is the paddle op here
    idx[axis % x.ndim] = index
    return x.at[tuple(idx)].set(values)


def index_fill(x, index, axis, value, name=None):
    x = jnp.asarray(x)
    idx = [slice_builtin(None)] * x.ndim  # `slice` is the paddle op here
    idx[axis % x.ndim] = jnp.asarray(index)
    return x.at[tuple(idx)].set(value)


def take(x, index, mode="raise", name=None):
    """Flattened-index gather (reference ``take``; ``mode`` clip/wrap —
    'raise' clamps like clip under jit, matching paddle's kernel)."""
    x = jnp.asarray(x).reshape(-1)
    index = jnp.asarray(index)
    if mode == "wrap":
        index = index % x.shape[0]
    else:  # raise/clip: no data-dependent errors under jit
        index = jnp.clip(index, -x.shape[0], x.shape[0] - 1)
    return x[index]


def unfold(x, axis, size, step, name=None):
    """Sliding windows along ``axis``: output gains a trailing [size] dim
    (reference ``unfold`` / torch.Tensor.unfold semantics)."""
    x = jnp.asarray(x)
    axis = axis % x.ndim
    n = x.shape[axis]
    starts = jnp.arange(0, n - size + 1, step)
    windows = starts[:, None] + jnp.arange(size)[None, :]  # [W, size]
    out = jnp.take(x, windows.reshape(-1), axis=axis)
    shape = list(x.shape)
    shape[axis:axis + 1] = [starts.shape[0], size]
    out = out.reshape(shape)
    # move the size dim to the end (paddle convention)
    return jnp.moveaxis(out, axis + 1, -1)


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view emulation: gathers the elements the strided view would
    alias (XLA has no aliasing views, so this materializes)."""
    x = jnp.asarray(x).reshape(-1)
    idx = jnp.asarray(offset)
    for s, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(s) * st
    return x[idx.reshape(-1)].reshape(tuple(shape))


def view(x, shape_or_dtype, name=None):
    """Reshape (list/tuple) or bitcast (dtype string) view (reference
    ``view``). Paddle's dtype-view scales the LAST dim by the itemsize
    ratio; jax's bitcast instead appends/consumes a trailing dim, so the
    result is reshaped back to paddle semantics."""
    x = jnp.asarray(x)
    if isinstance(shape_or_dtype, (list, tuple)):
        return x.reshape(tuple(shape_or_dtype))
    from ..framework.dtype import convert_dtype

    dt = convert_dtype(shape_or_dtype)
    in_size = jnp.dtype(x.dtype).itemsize
    out_size = jnp.dtype(dt).itemsize
    if out_size < in_size:  # bitcast appends (..., n, r) -> merge to (..., n*r)
        y = jax.lax.bitcast_convert_type(x, dt)
        return y.reshape(x.shape[:-1] + (x.shape[-1] * (in_size // out_size),))
    if out_size > in_size:  # reshape so bitcast consumes the trailing r
        r = out_size // in_size
        if x.shape[-1] % r:
            raise ValueError(
                f"view: last dim {x.shape[-1]} not divisible by itemsize "
                f"ratio {r}")
        return jax.lax.bitcast_convert_type(
            x.reshape(x.shape[:-1] + (x.shape[-1] // r, r)), dt)
    return jax.lax.bitcast_convert_type(x, dt)


def view_as(x, other, name=None):
    return jnp.asarray(x).reshape(jnp.asarray(other).shape)


def moveaxis_(x, source, destination, name=None):
    return moveaxis(x, source, destination)


def reshape_(x, shape, name=None):
    return jnp.asarray(x).reshape(tuple(shape))


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return flatten(x, start_axis=start_axis, stop_axis=stop_axis)


def squeeze_(x, axis=None, name=None):
    return squeeze(x, axis=axis)


def unsqueeze_(x, axis, name=None):
    return unsqueeze(x, axis)


def scatter_(x, index, updates, overwrite=True, name=None):
    return scatter(x, index, updates, overwrite=overwrite)


def put_along_axis_(arr, indices, values, axis, reduce="assign"):
    return put_along_axis(arr, indices, values, axis, reduce=reduce)


def unflatten(x, axis, shape, name=None):
    """Split one axis into the given shape (reference ``unflatten``);
    one -1 entry is inferred."""
    x = jnp.asarray(x)
    axis = axis % x.ndim
    shape = list(shape)
    if shape.count(-1) > 1:
        raise ValueError("unflatten shape can have at most one -1")
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = x.shape[axis] // known
    return x.reshape(x.shape[:axis] + tuple(shape) + x.shape[axis + 1:])


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions of ``x`` with consecutive elements of
    ``value`` (reference ``masked_scatter``). Static-shape jnp: positions
    index into the flattened value buffer by mask prefix-count."""
    x = jnp.asarray(x)
    mask = jnp.broadcast_to(jnp.asarray(mask, bool), x.shape)
    vals = jnp.asarray(value).reshape(-1).astype(x.dtype)
    try:  # eager check (skipped under tracing): reference errors on too
        # few value elements rather than silently reusing the last one
        needed = int(np.asarray(mask).sum())
        if needed > vals.size:
            raise ValueError(
                f"masked_scatter: mask selects {needed} elements but value "
                f"has only {vals.size}")
    except (TypeError, jax.errors.TracerArrayConversionError):
        pass
    # k-th True (row-major) takes vals[k]
    order = jnp.cumsum(mask.reshape(-1)) - 1
    take = vals[jnp.clip(order, 0, vals.size - 1)].reshape(x.shape)
    return jnp.where(mask, take, x)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """Embed ``value`` into the strided slice of ``x`` (reference
    ``slice_scatter``)."""
    x = jnp.asarray(x)
    # builtins.slice: this module's paddle `slice` op shadows the builtin
    idx = [_builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[int(ax)] = _builtins.slice(int(st), int(en), int(sd))
    return x.at[tuple(idx)].set(jnp.asarray(value, x.dtype))


def column_stack(x, name=None):
    return jnp.column_stack([jnp.asarray(t) for t in x])


def row_stack(x, name=None):
    return jnp.vstack([jnp.asarray(t) for t in x])


def tensor_split(x, num_or_indices, axis=0, name=None):
    """Split into (possibly uneven) sections like numpy ``array_split``
    (reference ``tensor_split``)."""
    x = jnp.asarray(x)
    return jnp.array_split(x, num_or_indices, axis=axis)


def atleast_1d(*inputs, name=None):
    out = [jnp.atleast_1d(jnp.asarray(t)) for t in inputs]
    return out[0] if len(out) == 1 else out


def atleast_2d(*inputs, name=None):
    out = [jnp.atleast_2d(jnp.asarray(t)) for t in inputs]
    return out[0] if len(out) == 1 else out


def atleast_3d(*inputs, name=None):
    out = [jnp.atleast_3d(jnp.asarray(t)) for t in inputs]
    return out[0] if len(out) == 1 else out


def block_diag(inputs, name=None):
    """Block-diagonal matrix from 2-D inputs (reference ``block_diag``)."""
    mats = [jnp.atleast_2d(jnp.asarray(t)) for t in inputs]
    rows = sum(m.shape[0] for m in mats)
    cols = sum(m.shape[1] for m in mats)
    out = jnp.zeros((rows, cols), mats[0].dtype)
    r = c = 0
    for m in mats:
        out = out.at[r:r + m.shape[0], c:c + m.shape[1]].set(m)
        r += m.shape[0]
        c += m.shape[1]
    return out


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors (reference ``cartesian_prod``)."""
    arrs = [jnp.asarray(t).reshape(-1) for t in x]
    grids = jnp.meshgrid(*arrs, indexing="ij")
    return jnp.stack([g.reshape(-1) for g in grids], axis=-1)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Embed the last axis as a diagonal plane of a new matrix pair of
    axes (reference ``diag_embed``)."""
    x = jnp.asarray(input)
    n = x.shape[-1] + abs(int(offset))
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    i = jnp.arange(x.shape[-1])
    r = i - min(int(offset), 0)
    c = i + max(int(offset), 0)
    out = base.at[..., r, c].set(x)
    dim1 = dim1 % out.ndim
    dim2 = dim2 % out.ndim
    perm = [d for d in range(out.ndim) if d not in (out.ndim - 2, out.ndim - 1)]
    # place the two new axes at dim1/dim2
    lo, hi = sorted((dim1, dim2))
    src = (out.ndim - 2, out.ndim - 1) if dim1 < dim2 else \
        (out.ndim - 1, out.ndim - 2)
    perm.insert(lo, src[0])
    perm.insert(hi, src[1])
    return jnp.transpose(out, perm)


def combinations(x, r=2, with_replacement=False, name=None):
    """All r-combinations of a 1-D tensor (reference ``combinations``)."""
    import itertools

    x = jnp.asarray(x).reshape(-1)
    n = x.shape[0]
    picker = (itertools.combinations_with_replacement if with_replacement
              else itertools.combinations)
    idx = np.asarray(list(picker(range(n), int(r))), np.int32)
    if idx.size == 0:
        return jnp.zeros((0, int(r)), x.dtype)
    return x[idx]
