"""Shape / layout manipulation ops.

Reference parity: ``python/paddle/tensor/manipulation.py`` (4.8k LoC).
All shape arguments must be static under ``jit`` — XLA compiles per shape.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.dtype import convert_dtype


def reshape(x, shape, name=None):
    return jnp.reshape(x, tuple(shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = jnp.asarray(x)
    nd = x.ndim
    start = start_axis % nd
    stop = stop_axis % nd
    new_shape = x.shape[:start] + (-1,) + x.shape[stop + 1 :]
    return jnp.reshape(x, new_shape)


def transpose(x, perm, name=None):
    return jnp.transpose(x, tuple(perm))


def moveaxis(x, source, destination, name=None):
    return jnp.moveaxis(x, source, destination)


def swapaxes(x, axis0, axis1, name=None):
    return jnp.swapaxes(x, axis0, axis1)


def t(x, name=None):
    x = jnp.asarray(x)
    if x.ndim > 2:
        raise ValueError("paddle.t only supports tensors with ndim <= 2")
    return x.T


def concat(x, axis=0, name=None):
    return jnp.concatenate([jnp.asarray(t) for t in x], axis=axis)


def stack(x, axis=0, name=None):
    return jnp.stack([jnp.asarray(t) for t in x], axis=axis)


def unstack(x, axis=0, num=None, name=None):
    x = jnp.asarray(x)
    n = x.shape[axis] if num is None else num
    return [jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis)]


def split(x, num_or_sections, axis=0, name=None):
    x = jnp.asarray(x)
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    # sections list, possibly containing one -1
    sections = list(num_or_sections)
    total = x.shape[axis]
    if -1 in sections:
        known = sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = total - known
    offsets = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        offsets.append(acc)
    return jnp.split(x, offsets, axis=axis)


def chunk(x, chunks, axis=0, name=None):
    return jnp.array_split(jnp.asarray(x), chunks, axis=axis)


def squeeze(x, axis=None, name=None):
    x = jnp.asarray(x)
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, int):
        axis = [axis]
    # paddle ignores non-unit axes in squeeze
    axes = tuple(a % x.ndim for a in axis if x.shape[a % x.ndim] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


def unsqueeze(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    x = jnp.asarray(x)
    for a in sorted(a % (x.ndim + 1) for a in axis):
        x = jnp.expand_dims(x, a)
    return x


def expand(x, shape, name=None):
    x = jnp.asarray(x)
    shape = list(shape)
    # paddle allows -1 meaning "keep this dim"
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1:
            shape[i] = x.shape[i - offset]
    return jnp.broadcast_to(x, tuple(shape))


def expand_as(x, y, name=None):
    return jnp.broadcast_to(jnp.asarray(x), jnp.asarray(y).shape)


def broadcast_to(x, shape, name=None):
    return jnp.broadcast_to(x, tuple(shape))


def broadcast_tensors(inputs, name=None):
    return list(jnp.broadcast_arrays(*inputs))


def tile(x, repeat_times, name=None):
    return jnp.tile(jnp.asarray(x), tuple(repeat_times))


def repeat_interleave(x, repeats, axis=None, name=None):
    return jnp.repeat(jnp.asarray(x), repeats, axis=axis)


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, (list, tuple)):
        shifts = tuple(shifts)
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return jnp.roll(x, shifts, axis=axis)


def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


def cast(x, dtype):
    return jnp.asarray(x).astype(convert_dtype(dtype))


import builtins as _builtins

slice_builtin = _builtins.slice


def slice(x, axes, starts, ends):  # noqa: A001
    x = jnp.asarray(x)
    idx = [slice_builtin(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = slice_builtin(s, e)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = jnp.asarray(x)
    idx = [slice_builtin(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice_builtin(s, e, st)
    return x[tuple(idx)]


def gather(x, index, axis=0, name=None):
    return jnp.take(jnp.asarray(x), jnp.asarray(index), axis=axis)


def gather_nd(x, index, name=None):
    x, index = jnp.asarray(x), jnp.asarray(index)
    # index: [..., k] indexes first k dims of x
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = jnp.asarray(x), jnp.asarray(index), jnp.asarray(updates)
    if overwrite:
        return x.at[index].set(updates)
    # paddle overwrite=False: zero the rows then accumulate
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    x, index = jnp.asarray(x), jnp.asarray(index)
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def scatter_nd(index, updates, shape, name=None):
    zeros = jnp.zeros(tuple(shape), dtype=jnp.asarray(updates).dtype)
    return scatter_nd_add(zeros, index, updates)


def put_along_axis(arr, indices, values, axis, reduce="assign"):
    arr, indices = jnp.asarray(arr), jnp.asarray(indices)
    values = jnp.broadcast_to(jnp.asarray(values, arr.dtype), indices.shape)
    if reduce == "assign":
        return jnp.put_along_axis(arr, indices, values, axis=axis, inplace=False)
    dims = list(range(arr.ndim))
    ix = jnp.meshgrid(*[jnp.arange(s) for s in indices.shape], indexing="ij")
    ix[axis] = indices
    if reduce == "add":
        return arr.at[tuple(ix)].add(values)
    if reduce == "multiply" or reduce == "mul":
        return arr.at[tuple(ix)].multiply(values)
    raise ValueError(f"unknown reduce: {reduce}")


def take_along_axis(arr, indices, axis):
    return jnp.take_along_axis(jnp.asarray(arr), jnp.asarray(indices), axis=axis)


def index_select(x, index, axis=0, name=None):
    return jnp.take(jnp.asarray(x), jnp.asarray(index), axis=axis)


def index_sample(x, index):
    x, index = jnp.asarray(x), jnp.asarray(index)
    return jnp.take_along_axis(x, index, axis=1)


def masked_select(x, mask, name=None):
    # NOTE: output shape is data-dependent; not jittable (same caveat as
    # reference dynamic-shape ops on XLA). Use where() under jit.
    import numpy as np

    return jnp.asarray(np.asarray(x)[np.asarray(mask)])


def masked_fill(x, mask, value, name=None):
    x = jnp.asarray(x)
    return jnp.where(jnp.asarray(mask), jnp.asarray(value, x.dtype), x)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple=False):
    # data-dependent shape: eager-only (see masked_select note)
    import numpy as np

    idx = np.nonzero(np.asarray(x))
    if as_tuple:
        return tuple(jnp.asarray(i) for i in idx)
    return jnp.asarray(np.stack(idx, axis=1))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, name=None):
    import numpy as np

    res = np.unique(
        np.asarray(x),
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if isinstance(res, tuple):
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    import numpy as np

    x_np = np.asarray(x)
    if axis is None:
        x_np = x_np.reshape(-1)
        keep = np.concatenate([[True], x_np[1:] != x_np[:-1]])
    else:
        diff = (x_np.take(range(1, x_np.shape[axis]), axis=axis)
                != x_np.take(range(0, x_np.shape[axis] - 1), axis=axis))
        keep = np.concatenate([[True], diff.any(axis=tuple(i for i in range(x_np.ndim) if i != axis))])
        x_np = np.compress(keep, np.asarray(x), axis=axis)
        out = [jnp.asarray(x_np)]
        return out[0] if len(out) == 1 else tuple(out)
    out = [jnp.asarray(x_np[keep])]
    if return_inverse:
        out.append(jnp.asarray(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.concatenate([idx, [len(x_np)]]))
        out.append(jnp.asarray(counts))
    return out[0] if len(out) == 1 else tuple(out)


def tolist(x):
    return jnp.asarray(x).tolist()


def numel(x, name=None):
    return jnp.asarray(jnp.asarray(x).size)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    """TP vocab-shard index remap (reference: ``c_embedding``'s index logic)."""
    x = jnp.asarray(input)
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    in_shard = (x >= lo) & (x < hi)
    return jnp.where(in_shard, x - lo, ignore_value)


def as_real(x, name=None):
    x = jnp.asarray(x)
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def as_complex(x, name=None):
    x = jnp.asarray(x)
    return jax.lax.complex(x[..., 0], x[..., 1])


def real(x, name=None):
    return jnp.real(x)


def imag(x, name=None):
    return jnp.imag(x)


def conj(x, name=None):
    return jnp.conj(x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    """paddle.nn.functional.pad semantics: ``pad`` is per-dim (low, high) pairs.

    For len(pad) == 2*ndim the order is [d0_lo, d0_hi, d1_lo, ...]. For the
    common conv case (len 4 with 4D input), pads the spatial dims of
    ``data_format``.
    """
    x = jnp.asarray(x)
    pad = list(pad)
    if len(pad) == 2 * x.ndim:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # spatial padding: reversed per-dim pairs over trailing spatial dims
        n_spatial = len(pad) // 2
        width = [(0, 0)] * x.ndim
        if data_format.endswith("C"):  # NHWC / NLC / NDHWC
            spatial_axes = list(range(1, 1 + n_spatial))
        else:  # NCHW / NCL / NCDHW
            spatial_axes = list(range(x.ndim - n_spatial, x.ndim))
        # paddle lists pads innermost-last: [left, right, top, bottom] pairs
        for i, ax in enumerate(reversed(spatial_axes)):
            width[ax] = (pad[2 * i], pad[2 * i + 1])
    jnp_mode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    if jnp_mode == "constant":
        return jnp.pad(x, width, mode="constant", constant_values=value)
    return jnp.pad(x, width, mode=jnp_mode)
