"""Random ops (reference: ``python/paddle/tensor/random.py``).

Eager calls draw subkeys from the global :class:`~paddle_tpu.framework.random.Generator`
(paddle-style statefulness). Every op also accepts ``key=`` for functional use
under ``jit`` — the TPU-native path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.dtype import convert_dtype, get_default_dtype
from ..framework.random import next_key


def _key(key):
    return next_key() if key is None else key


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, key=None, name=None):  # noqa: A002
    dtype = get_default_dtype() if dtype is None else convert_dtype(dtype)
    return jax.random.uniform(_key(key), tuple(shape), dtype=dtype, minval=min, maxval=max)


def rand(shape, dtype=None, key=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0, key=key)


def randn(shape, dtype=None, key=None, name=None):
    dtype = get_default_dtype() if dtype is None else convert_dtype(dtype)
    return jax.random.normal(_key(key), tuple(shape), dtype=dtype)


def normal(mean=0.0, std=1.0, shape=None, key=None, name=None):
    if shape is None:
        shape = jnp.shape(mean) if hasattr(mean, "shape") else ()
    out = jax.random.normal(_key(key), tuple(shape), dtype=get_default_dtype())
    return out * std + mean


def standard_normal(shape, dtype=None, key=None, name=None):
    return randn(shape, dtype=dtype, key=key)


def randint(low=0, high=None, shape=(1,), dtype="int64", key=None, name=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(_key(key), tuple(shape), low, high, dtype=convert_dtype(dtype))


def randint_like(x, low=0, high=None, dtype=None, key=None, name=None):
    x = jnp.asarray(x)
    dtype = x.dtype if dtype is None else convert_dtype(dtype)
    return randint(low, high, x.shape, dtype=dtype, key=key)


def randperm(n, dtype="int64", key=None, name=None):
    return jax.random.permutation(_key(key), n).astype(convert_dtype(dtype))


def multinomial(x, num_samples=1, replacement=False, key=None, name=None):
    x = jnp.asarray(x)
    logits = jnp.log(jnp.clip(x, 1e-30, None))
    k = _key(key)
    if replacement:
        out = jax.random.categorical(k, logits, axis=-1, shape=(num_samples, *x.shape[:-1]))
        return jnp.moveaxis(out, 0, -1).astype(jnp.int64)
    # without replacement: Gumbel top-k trick
    g = jax.random.gumbel(k, x.shape, dtype=logits.dtype)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return idx.astype(jnp.int64)


def bernoulli(x, key=None, name=None):
    x = jnp.asarray(x)
    return jax.random.bernoulli(_key(key), x, x.shape).astype(x.dtype)


def poisson(x, key=None, name=None):
    x = jnp.asarray(x)
    return jax.random.poisson(_key(key), x, x.shape).astype(x.dtype)


def exponential_(x, lam=1.0, key=None, name=None):
    x = jnp.asarray(x)
    return (jax.random.exponential(_key(key), x.shape, dtype=x.dtype) / lam).astype(x.dtype)


def uniform_(x, min=-1.0, max=1.0, key=None, name=None):  # noqa: A002
    x = jnp.asarray(x)
    return jax.random.uniform(_key(key), x.shape, dtype=x.dtype, minval=min, maxval=max)


def normal_(x, mean=0.0, std=1.0, key=None, name=None):
    x = jnp.asarray(x)
    return jax.random.normal(_key(key), x.shape, dtype=x.dtype) * std + mean
