"""Tensor creation ops (reference: ``python/paddle/tensor/creation.py``).

Tensors are plain ``jax.Array``; creation ops are thin jnp wrappers with
paddle-compatible signatures. Gradient flow in this framework is decided
by which pytree leaves are differentiated, not per-tensor flags (use
``jax.lax.stop_gradient`` for in-graph cuts) — with ONE exception:
``to_tensor(..., stop_gradient=False)`` opts into the eager tape and
returns a :class:`paddle_tpu.eager.Tensor`, so the canonical dygraph
snippet works from the front door.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import convert_dtype, get_default_dtype


def _maybe_default_float(dtype):
    return get_default_dtype() if dtype is None else convert_dtype(dtype)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """``paddle.to_tensor`` analogue: anything array-like -> jax.Array.

    ``stop_gradient=False`` — the canonical dygraph idiom
    (``x = paddle.to_tensor(d, stop_gradient=False); y.backward();
    x.grad``) — returns an EAGER tape Tensor instead, so tensor-level
    autograd works from the front door; the default returns a plain
    array (the functional fast path, where grad flow is decided by which
    pytree leaves are differentiated)."""
    del place
    dtype = convert_dtype(dtype)
    if dtype is None and isinstance(data, (list, tuple, int, float)):
        # match paddle: python floats default to the default float dtype
        probe = np.asarray(data)
        if probe.dtype == np.float64:
            dtype = get_default_dtype()
    arr = jnp.asarray(getattr(data, "_data", data), dtype=dtype)
    if not stop_gradient:
        from ..eager import Tensor

        return Tensor(arr, stop_gradient=False)
    return arr


def full(shape, fill_value, dtype=None):
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = jnp.bool_
        elif isinstance(fill_value, int):
            dtype = jnp.int64
        else:
            dtype = get_default_dtype()
    return jnp.full(tuple(shape), fill_value, dtype=convert_dtype(dtype))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=convert_dtype(dtype))


def zeros(shape, dtype=None):
    return jnp.zeros(tuple(shape), dtype=_maybe_default_float(dtype))


def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=convert_dtype(dtype))


def ones(shape, dtype=None):
    return jnp.ones(tuple(shape), dtype=_maybe_default_float(dtype))


def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=convert_dtype(dtype))


def empty(shape, dtype=None):
    return jnp.zeros(tuple(shape), dtype=_maybe_default_float(dtype))


def empty_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=convert_dtype(dtype))


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype=convert_dtype(dtype))


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=convert_dtype(dtype))


def logspace(start, stop, num, base=10.0, dtype=None):
    return jnp.logspace(start, stop, num, base=base, dtype=convert_dtype(dtype))


def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=_maybe_default_float(dtype))


def diag(x, offset=0, padding_value=0):
    x = jnp.asarray(x)
    if x.ndim == 1 and padding_value != 0:
        out = jnp.diag(x, k=offset)
        mask = jnp.diag(jnp.ones_like(x, dtype=bool), k=offset)
        return jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
    return jnp.diag(x, k=offset)


def diagflat(x, offset=0):
    return jnp.diagflat(jnp.asarray(x), k=offset)


def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def meshgrid(*args):
    return jnp.meshgrid(*args, indexing="ij")


def assign(x, output=None):
    del output
    return jnp.asarray(x)


def clone(x):
    return jnp.array(x, copy=True)


def complex(real, imag):
    return jax.lax.complex(jnp.asarray(real), jnp.asarray(imag))


def tril_indices(row, col, offset=0):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return jnp.stack([r, c])


def triu_indices(row, col=None, offset=0):
    r, c = jnp.triu_indices(row, k=offset, m=col if col is not None else row)
    return jnp.stack([r, c])


# ------------------------------------------------------ breadth additions
def vander(x, n=None, increasing=False, name=None):
    return jnp.vander(jnp.asarray(x), N=n, increasing=increasing)


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    """Gaussian-filled tensor (reference ``gaussian``; the seeded-creation
    flavor of ``normal``)."""
    from . import random as _random

    out = _random.normal(mean=mean, std=std, shape=shape,
                         key=None if seed == 0 else jax.random.key(seed))
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return out


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Configure array repr (applies to numpy and jax reprs alike)."""
    import numpy as np

    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)
