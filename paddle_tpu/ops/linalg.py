"""Linear algebra ops (reference: ``python/paddle/tensor/linalg.py``).

Matmuls are the MXU path; everything here maps to a single XLA HLO
(dot_general / triangular_solve / cholesky / ...). ``matmul`` keeps paddle's
transpose_x/transpose_y flags so layers can avoid materializing transposes —
XLA folds them into dot_general dimension numbers.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = jnp.asarray(x), jnp.asarray(y)
    if transpose_x:
        if x.ndim == 1:
            pass
        else:
            x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        if y.ndim == 1:
            pass
        else:
            y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def mm(input, mat2, name=None):  # noqa: A002
    return jnp.matmul(input, mat2)


def bmm(x, y, name=None):
    return jnp.matmul(x, y)


def dot(x, y, name=None):
    x, y = jnp.asarray(x), jnp.asarray(y)
    return jnp.sum(x * y, axis=-1)


def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


def dist(x, y, p=2, name=None):
    return norm(jnp.asarray(x) - jnp.asarray(y), p=p)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    x = jnp.asarray(x)
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.linalg.norm(x, ord="fro" if isinstance(axis, (list, tuple)) else None,
                               axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis,
                               keepdims=keepdim)
    if p == float("inf") or p == float("-inf") or isinstance(p, (int, float)):
        if axis is None:
            x = x.reshape(-1)
            axis = 0
        if isinstance(axis, (list, tuple)):
            axis = tuple(axis)
        return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)
    raise ValueError(f"unsupported norm order {p}")


def cond(x, p=None, name=None):
    return jnp.linalg.cond(x, p=p)


def cross(x, y, axis=9, name=None):
    x = jnp.asarray(x)
    if axis == 9:  # paddle default: first axis of size 3
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return jnp.cross(x, y, axis=axis)


def cholesky(x, upper=False, name=None):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


def cholesky_solve(x, y, upper=False, name=None):
    return jax.scipy.linalg.cho_solve((jnp.asarray(y), not upper), jnp.asarray(x))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return jax.scipy.linalg.solve_triangular(
        jnp.asarray(x), jnp.asarray(y), lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


def inverse(x, name=None):
    return jnp.linalg.inv(x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


def det(x, name=None):
    return jnp.linalg.det(x)


def slogdet(x, name=None):
    sign, logabsdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabsdet])


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


def qr(x, mode="reduced", name=None):
    return jnp.linalg.qr(x, mode=mode)


def svd(x, full_matrices=False, name=None):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def eig(x, name=None):
    return jnp.linalg.eig(x)


def eigh(x, UPLO="L", name=None):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvals(x, name=None):
    return jnp.linalg.eigvals(x)


def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def multi_dot(x, name=None):
    return jnp.linalg.multi_dot(x)


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    x = jnp.asarray(input).reshape(-1)
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return hist


def bincount(x, weights=None, minlength=0, name=None):
    # under jit, length must be static: use minlength as the bound
    import numpy as np

    x_np = np.asarray(x)
    return jnp.asarray(np.bincount(x_np, weights=None if weights is None else np.asarray(weights),
                                   minlength=minlength))


def einsum(equation, *operands):
    """Reference implements its own einsum planner (``einsum.py``, 1,082 LoC);
    XLA's dot_general lowering makes jnp.einsum optimal on TPU directly."""
    return jnp.einsum(equation, *operands)


# ------------------------------------------------------ breadth additions
def lu(x, pivot=True, get_infos=False, name=None):
    """Packed LU factorization with LAPACK-style 1-based pivots (reference
    ``paddle.linalg.lu``)."""
    import jax

    lu_mat, piv, _ = jax.lax.linalg.lu(jnp.asarray(x))
    piv = piv + 1  # LAPACK/paddle pivots are 1-based
    if get_infos:
        info = jnp.zeros(jnp.asarray(x).shape[:-2], jnp.int32)
        return lu_mat, piv, info
    return lu_mat, piv


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack ``lu()`` results into (P, L, U) (reference ``lu_unpack``)."""
    x = jnp.asarray(x)
    m, n = x.shape[-2], x.shape[-1]
    k = min(m, n)
    L = U = P = None
    if unpack_ludata:
        L = jnp.tril(x[..., :, :k], -1) + jnp.eye(m, k, dtype=x.dtype)
        U = jnp.triu(x[..., :k, :])
    if unpack_pivots:
        piv = jnp.asarray(y) - 1  # back to 0-based successive swaps
        perm = jnp.broadcast_to(jnp.arange(m), piv.shape[:-1] + (m,))

        def apply_swaps(perm_row, piv_row):
            def body(i, p):
                j = piv_row[i]
                pi, pj = p[i], p[j]
                return p.at[i].set(pj).at[j].set(pi)

            import jax

            return jax.lax.fori_loop(0, piv_row.shape[0], body, perm_row)

        flat_perm = perm.reshape(-1, m)
        flat_piv = jnp.asarray(piv).reshape(-1, piv.shape[-1])
        import jax

        out = jax.vmap(apply_swaps)(flat_perm, flat_piv)
        perm = out.reshape(perm.shape)
        P = jax.nn.one_hot(perm, m, dtype=x.dtype)
        # rows permuted: P[..., i, perm[i]] = 1 gives P @ A = swapped rows;
        # paddle returns P with A = P @ L @ U
        P = jnp.swapaxes(P, -1, -2)
    return P, L, U


def tensordot(x, y, axes=2, name=None):
    return jnp.tensordot(jnp.asarray(x), jnp.asarray(y), axes=axes)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(jnp.asarray(x), rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(jnp.asarray(x), rowvar=rowvar)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-norm distances between row batches (reference ``cdist``).
    For p=2 the matmul formulation keeps the FLOPs on the MXU."""
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    if p == 2.0 and compute_mode != "donot_use_mm_for_euclid_dist":
        x2 = jnp.sum(x * x, -1)[..., :, None]
        y2 = jnp.sum(y * y, -1)[..., None, :]
        d2 = x2 + y2 - 2.0 * jnp.matmul(x, jnp.swapaxes(y, -1, -2))
        return jnp.sqrt(jnp.maximum(d2, 0.0))
    diff = jnp.abs(x[..., :, None, :] - y[..., None, :, :])
    if p == float("inf"):
        return jnp.max(diff, -1)
    if p == 0:
        return jnp.sum(diff != 0, -1).astype(x.dtype)
    return jnp.sum(diff ** p, -1) ** (1.0 / p)


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of one row batch (reference ``pdist``):
    the upper-triangle (i<j) entries of ``cdist(x, x)``."""
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[0]
    full = cdist(x, x, p=p)
    iu = np.triu_indices(n, k=1)
    return full[iu]


def inv(x, name=None):
    """Matrix inverse — alias of :func:`inverse` (the reference exposes
    both ``paddle.inverse`` and ``paddle.linalg.inv``)."""
    return inverse(x)


def matrix_transpose(x, name=None):
    """Swap the last two axes (reference ``matrix_transpose``)."""
    return jnp.swapaxes(jnp.asarray(x), -1, -2)


def vecdot(x, y, axis=-1, name=None):
    return jnp.sum(jnp.asarray(x) * jnp.asarray(y), axis=axis)


def householder_product(x, tau, name=None):
    """Product of Householder reflectors H_0 ... H_{k-1} (reference
    ``householder_product`` — the orthogonal Q from a QR factorization's
    compact (v, tau) form). x: [..., m, k] reflector columns, tau: [..., k].
    """
    x = jnp.asarray(x)
    tau = jnp.asarray(tau)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        raise NotImplementedError(
            "householder_product: complex reflectors not supported")
    if not jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(jnp.float32)
    tau = tau.astype(x.dtype)
    m, k = x.shape[-2], x.shape[-1]

    def one(xm, tm):
        q = jnp.eye(m, dtype=x.dtype)
        # v_i: unit lower-trapezoidal column i (implicit leading 1)
        for i in range(k):
            v = xm[:, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v)
            v = v.at[i].set(1.0)
            q = q - tm[i] * (q @ v)[:, None] * v[None, :]
        # reference shape contract: Q has x's shape ([..., m, k])
        return q[:, :k]

    if x.ndim == 2:
        return one(x, tau)
    batch = x.reshape((-1, m, k))
    bt = tau.reshape((-1, k))
    out = jax.vmap(one)(batch, bt)
    return out.reshape(x.shape[:-2] + (m, k))
