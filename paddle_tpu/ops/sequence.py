"""Sequence-op family over padded batches + explicit lengths.

Reference parity: the ``sequence_*`` operator family
(``paddle/fluid/operators/sequence_ops/``: sequence_pad, sequence_unpad,
sequence_pool, sequence_softmax, sequence_reverse, sequence_expand, ...),
which the reference drives off LoD (level-of-detail) ragged tensors.

TPU-native shape: XLA wants static shapes, so the LoD representation
becomes the (padded dense tensor, lengths vector) pair — SURVEY §7's
"bucketing + padding designed in the data layer". ``sequence_pad`` is the
eager boundary converting ragged python/flat data into that pair; every
other op is mask arithmetic on the pair and jit-compiles.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "sequence_pad", "sequence_unpad", "sequence_pool", "sequence_softmax",
    "sequence_reverse", "sequence_expand", "sequence_expand_as",
    "sequence_first_step", "sequence_last_step", "sequence_concat",
]


def _valid_mask(lengths, maxlen: int):
    """[B, T] bool — True inside each row's valid prefix."""
    lengths = jnp.asarray(lengths)
    return jnp.arange(maxlen)[None, :] < lengths[:, None]


def sequence_pad(x, pad_value=0.0, maxlen: Optional[int] = None,
                 lengths=None, name=None):
    """Ragged -> (padded [B, T, ...], lengths [B]).

    Accepts a python list of per-sequence arrays (the eager boundary) or a
    flat [sum(L), ...] array + ``lengths`` (the LoD form).
    """
    # host-side assembly (this is the eager ragged->dense boundary):
    # one numpy buffer + one device transfer, not B jnp copies
    if lengths is not None:
        flat = np.asarray(x)
        lengths = np.asarray(lengths, np.int64).reshape(-1)
        offs = np.concatenate([[0], np.cumsum(lengths)])
        seqs = [flat[int(offs[i]):int(offs[i + 1])]
                for i in range(lengths.size)]
    else:
        seqs = [np.asarray(s) for s in x]
        lengths = np.asarray([s.shape[0] for s in seqs], np.int64)
    T = int(maxlen) if maxlen is not None else int(lengths.max(initial=0))
    feat = seqs[0].shape[1:] if seqs else ()
    out = np.full((len(seqs), T) + feat, pad_value,
                  seqs[0].dtype if seqs else np.float32)
    for i, s in enumerate(seqs):
        n = min(int(lengths[i]), T)
        out[i, :n] = s[:n]
    return jnp.asarray(out), jnp.asarray(np.minimum(lengths, T))


def sequence_unpad(x, lengths, name=None) -> List[jnp.ndarray]:
    """(padded, lengths) -> list of per-sequence arrays (eager: output
    shapes are data-dependent)."""
    x = jnp.asarray(x)
    lengths = np.asarray(lengths).reshape(-1)
    return [x[i, :int(n)] for i, n in enumerate(lengths)]


def sequence_pool(x, lengths, pool_type: str = "sum", name=None):
    """Masked pooling over the time axis — the ``sequence_pool`` op. All
    flavors jit-compile (mask arithmetic, no ragged shapes)."""
    x = jnp.asarray(x)
    B, T = x.shape[0], x.shape[1]
    mask = _valid_mask(lengths, T)
    fmask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    pool_type = pool_type.lower()
    if pool_type == "sum":
        return jnp.sum(jnp.where(fmask, x, 0), axis=1)
    if pool_type == "average" or pool_type == "mean":
        denom = jnp.maximum(jnp.asarray(lengths), 1)
        denom = denom.reshape((B,) + (1,) * (x.ndim - 2))
        return jnp.sum(jnp.where(fmask, x, 0), axis=1) / denom
    if pool_type == "sqrt":
        denom = jnp.sqrt(jnp.maximum(jnp.asarray(lengths), 1).astype(x.dtype))
        denom = denom.reshape((B,) + (1,) * (x.ndim - 2))
        return jnp.sum(jnp.where(fmask, x, 0), axis=1) / denom
    if pool_type == "max":
        neg = jnp.asarray(-jnp.inf, x.dtype) if jnp.issubdtype(
            x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return jnp.max(jnp.where(fmask, x, neg), axis=1)
    if pool_type == "first":
        return sequence_first_step(x, lengths)
    if pool_type == "last":
        return sequence_last_step(x, lengths)
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(x, lengths=None, name=None):
    return jnp.asarray(x)[:, 0]


def sequence_last_step(x, lengths, name=None):
    x = jnp.asarray(x)
    idx = jnp.maximum(jnp.asarray(lengths) - 1, 0)
    return jnp.take_along_axis(
        x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
    ).squeeze(1)


def sequence_softmax(x, lengths, name=None):
    """Per-row softmax over the valid prefix; padding gets probability 0."""
    x = jnp.asarray(x, jnp.float32) if not jnp.issubdtype(
        jnp.asarray(x).dtype, jnp.floating) else jnp.asarray(x)
    mask = _valid_mask(lengths, x.shape[1])
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    neg = jnp.asarray(-jnp.inf, x.dtype)
    z = jnp.where(mask, x, neg)
    p = jax.nn.softmax(z, axis=1)
    return jnp.where(mask, p, 0)


def sequence_reverse(x, lengths, name=None):
    """Reverse each row's valid prefix in place; padding stays put (the
    ``sequence_reverse`` op, the bidirectional-RNN building block)."""
    x = jnp.asarray(x)
    T = x.shape[1]
    lengths = jnp.asarray(lengths)
    pos = jnp.arange(T)[None, :]
    src = jnp.where(pos < lengths[:, None], lengths[:, None] - 1 - pos, pos)
    return jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
        axis=1)


def sequence_expand(x, ref_lengths, name=None):
    """Repeat row i of ``x`` ``ref_lengths[i]`` times along a new flat axis
    (the ``sequence_expand`` broadcast join). Eager: output length is
    data-dependent."""
    x = np.asarray(x)
    ref_lengths = np.asarray(ref_lengths).reshape(-1)
    return jnp.asarray(np.repeat(x, ref_lengths, axis=0))


def sequence_expand_as(x, y_lengths, name=None):
    return sequence_expand(x, y_lengths)


def sequence_concat(inputs: Sequence, lengths_list: Sequence, name=None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Row-wise concatenation of several (padded, lengths) pairs: row b of
    the result is input0[b][:l0] ++ input1[b][:l1] ++ ... (the
    ``sequence_concat`` op joining LoD tensors per sequence)."""
    arrs = [np.asarray(a) for a in inputs]
    lens = [np.asarray(l).reshape(-1) for l in lengths_list]
    B = arrs[0].shape[0]
    total = sum(l.astype(np.int64) for l in lens)
    T = int(total.max(initial=0))
    feat = arrs[0].shape[2:]
    out = np.zeros((B, T) + feat, arrs[0].dtype)
    for b in range(B):
        pos = 0
        for a, l in zip(arrs, lens):
            n = int(l[b])
            out[b, pos:pos + n] = a[b, :n]
            pos += n
    return jnp.asarray(out), jnp.asarray(total)
