"""Statistics ops (reference: ``python/paddle/tensor/stat.py``)."""
from __future__ import annotations

import jax.numpy as jnp


def _norm_axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return axis


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.var(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.std(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


def median(x, axis=None, keepdim=False, name=None):
    return jnp.median(x, axis=_norm_axis(axis), keepdims=keepdim)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(x, axis=_norm_axis(axis), keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return jnp.quantile(jnp.asarray(x), jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return jnp.nanquantile(jnp.asarray(x), jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim)


# ------------------------------------------------------ breadth additions
def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """N-dimensional histogram (reference ``histogramdd``)."""
    hist, edges = jnp.histogramdd(jnp.asarray(x), bins=bins, range=ranges,
                                  density=density, weights=weights)
    return hist, list(edges)
