"""Search / sort ops (reference: ``python/paddle/tensor/search.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.dtype import convert_dtype


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = jnp.asarray(x)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    out = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return out.astype(convert_dtype(dtype))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = jnp.asarray(x)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    out = jnp.argmin(x, axis=axis, keepdims=keepdim)
    return out.astype(convert_dtype(dtype))


def argsort(x, axis=-1, descending=False, name=None):
    out = jnp.argsort(jnp.asarray(x), axis=axis, descending=descending)
    return out.astype(jnp.int64)


def sort(x, axis=-1, descending=False, name=None):
    return jnp.sort(jnp.asarray(x), axis=axis, descending=descending)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):  # noqa: A002
    x = jnp.asarray(x)
    if axis is None:
        axis = -1
    axis = axis % x.ndim
    moved = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(moved, k)
    else:
        vals, idx = jax.lax.top_k(-moved, k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx.astype(jnp.int64), -1, axis)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = jnp.asarray(x)
    axis = axis % x.ndim
    sorted_vals = jnp.sort(x, axis=axis)
    sorted_idx = jnp.argsort(x, axis=axis)
    vals = jnp.take(sorted_vals, k - 1, axis=axis)
    idx = jnp.take(sorted_idx, k - 1, axis=axis).astype(jnp.int64)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx


def mode(x, axis=-1, keepdim=False, name=None):
    x = jnp.asarray(x)
    axis = axis % x.ndim
    moved = jnp.moveaxis(x, axis, -1)
    s = jnp.sort(moved, axis=-1)
    n = s.shape[-1]
    # count run lengths in the sorted array
    eq = (s[..., :, None] == s[..., None, :])
    counts = eq.sum(-1)
    best = jnp.argmax(counts, axis=-1)
    vals = jnp.take_along_axis(s, best[..., None], axis=-1)[..., 0]
    # paddle returns the *last* index of the mode value in the original array
    match = moved == vals[..., None]
    pos = jnp.arange(n)
    idx = jnp.max(jnp.where(match, pos, -1), axis=-1).astype(jnp.int64)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    ss, v = jnp.asarray(sorted_sequence), jnp.asarray(values)
    if ss.ndim == 1:
        out = jnp.searchsorted(ss, v, side=side)
    else:
        out = jax.vmap(lambda s, q: jnp.searchsorted(s, q, side=side))(
            ss.reshape(-1, ss.shape[-1]), v.reshape(-1, v.shape[-1])
        ).reshape(v.shape)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def index_add(x, index, axis, value, name=None):
    x = jnp.asarray(x)
    axis = axis % x.ndim
    moved = jnp.moveaxis(x, axis, 0)
    v = jnp.moveaxis(jnp.asarray(value, x.dtype), axis, 0)
    out = moved.at[jnp.asarray(index)].add(v)
    return jnp.moveaxis(out, 0, axis)


def index_put(x, indices, value, accumulate=False, name=None):
    x = jnp.asarray(x)
    idx = tuple(jnp.asarray(i) for i in indices)
    if accumulate:
        return x.at[idx].add(jnp.asarray(value, x.dtype))
    return x.at[idx].set(jnp.asarray(value, x.dtype))


# ------------------------------------------------------ breadth additions
def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return jnp.isin(jnp.asarray(x), jnp.asarray(test_x),
                    assume_unique=assume_unique, invert=invert)


def digitize(x, bins, right=False, name=None):
    return jnp.digitize(jnp.asarray(x), jnp.asarray(bins), right=right)
