"""ASP — 2:4 structured sparsity (automatic sparsity pruning).

Reference parity: ``python/paddle/incubate/asp/`` (``calculate_density``,
``prune_model`` computing 2:4 masks per FC/conv weight, mask checking
``utils.py``). TPU-native: masks are plain arrays applied by elementwise
multiply — XLA fuses the mask into the producing op. (The reference
targets Ampere sparse tensor cores; on TPU the win is model compression
semantics, kept for parity.)
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

__all__ = ["calculate_density", "create_mask", "check_mask_2_4",
           "prune_model", "ASPHelper"]


def calculate_density(x) -> float:
    x = np.asarray(x)
    return float((x != 0).sum() / x.size)


def create_mask(weight, n: int = 2, m: int = 4, axis: int = -1) -> np.ndarray:
    """n:m mask along ``axis``: keep the n largest-|w| of every m."""
    w = np.asarray(weight)
    w_moved = np.moveaxis(w, axis, -1)
    if w_moved.shape[-1] % m != 0:
        raise ValueError(
            f"axis {axis} size {w_moved.shape[-1]} not divisible by m={m}")
    groups = np.abs(w_moved).reshape(-1, m)
    keep = np.argsort(-groups, axis=1)[:, :n]
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, keep, 1.0, axis=1)
    return np.moveaxis(mask.reshape(w_moved.shape), -1,
                       axis).astype(w.dtype)


def check_mask_2_4(x, n: int = 2, m: int = 4, axis: int = -1) -> bool:
    """True iff every group of m along ``axis`` has <= n nonzeros."""
    w = np.moveaxis(np.asarray(x), axis, -1)
    if w.shape[-1] % m != 0:
        return False
    nz = (w.reshape(-1, m) != 0).sum(1)
    return bool((nz <= n).all())


def _iter_layers(layer, prefix: str = ""):
    yield prefix, layer
    for name, sub in layer._sub_layers.items():
        if sub is not None:
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from _iter_layers(sub, sub_prefix)


def prune_model(model, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d") -> Dict[str, np.ndarray]:
    """Apply n:m masks along the reduction axis of every FC/conv weight of
    a Layer in place; returns the masks (reference ``prune_model``, which
    likewise restricts to FC/Conv — embeddings and norm scales are never
    pruned)."""
    import paddle_tpu.nn as nn

    masks = {}
    for path, layer in _iter_layers(model):
        if isinstance(layer, nn.Linear):
            reduction_ok = layer.weight.shape[0] % m == 0
            kind = "linear"
        elif isinstance(layer, nn.Conv2D):
            reduction_ok = int(np.prod(layer.weight.shape[1:])) % m == 0
            kind = "conv"
        else:
            continue
        if not reduction_ok:
            continue
        name = f"{path}.weight" if path else "weight"
        w = np.asarray(layer.weight)
        if kind == "linear":                 # [in, out]: reduction axis 0
            mask = create_mask(w, n, m, axis=0)
        else:                                # [out, in/g, kh, kw]
            flat = w.reshape(w.shape[0], -1)
            mask = create_mask(flat, n, m, axis=-1).reshape(w.shape)
        model._set_by_path(name, jnp.asarray(w * mask))
        masks[name] = mask
    return masks


class ASPHelper:
    """Keeps masks and re-applies them after optimizer steps (the
    reference hooks ``optimizer.step``; here call ``apply_masks`` after
    each update or use it as a TrainStep ``grad_transform``)."""

    def __init__(self, model, n: int = 2, m: int = 4):
        self.model = model
        self.masks = prune_model(model, n, m)

    def apply_masks(self, params: Dict[str, jnp.ndarray]):
        out = dict(params)
        for name, mask in self.masks.items():
            if name in out:
                out[name] = out[name] * jnp.asarray(mask)
        return out

    def mask_grads(self, grads):
        """grad_transform hook: masked weights receive no gradient, so
        pruned entries stay zero through training."""
        return self.apply_masks(grads)
