"""ASP — 2:4 structured sparsity (automatic sparsity pruning).

Reference parity: ``python/paddle/incubate/asp/`` (``calculate_density``,
``prune_model`` computing 2:4 masks per FC/conv weight, mask checking
``utils.py``). TPU-native: masks are plain arrays applied by elementwise
multiply — XLA fuses the mask into the producing op. (The reference
targets Ampere sparse tensor cores; on TPU the win is model compression
semantics, kept for parity.)
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

__all__ = ["calculate_density", "create_mask", "check_mask_2_4",
           "prune_model", "ASPHelper"]


def calculate_density(x) -> float:
    x = np.asarray(x)
    return float((x != 0).sum() / x.size)


def create_mask(weight, n: int = 2, m: int = 4, axis: int = -1) -> np.ndarray:
    """n:m mask along ``axis``: keep the n largest-|w| of every m."""
    w = np.asarray(weight)
    w_moved = np.moveaxis(w, axis, -1)
    if w_moved.shape[-1] % m != 0:
        raise ValueError(
            f"axis {axis} size {w_moved.shape[-1]} not divisible by m={m}")
    groups = np.abs(w_moved).reshape(-1, m)
    keep = np.argsort(-groups, axis=1)[:, :n]
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, keep, 1.0, axis=1)
    return np.moveaxis(mask.reshape(w_moved.shape), -1,
                       axis).astype(w.dtype)


def check_mask_2_4(x, n: int = 2, m: int = 4, axis: int = -1) -> bool:
    """True iff every group of m along ``axis`` has <= n nonzeros."""
    w = np.moveaxis(np.asarray(x), axis, -1)
    if w.shape[-1] % m != 0:
        return False
    nz = (w.reshape(-1, m) != 0).sum(1)
    return bool((nz <= n).all())


def _prunable(name: str, arr, m: int) -> bool:
    if not name.endswith("weight") or arr.ndim < 2:
        return False
    if arr.ndim == 2:
        return arr.shape[0] % m == 0
    if arr.ndim == 4:
        return (int(np.prod(arr.shape[1:]))) % m == 0
    return False


def prune_model(model, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d") -> Dict[str, np.ndarray]:
    """Apply n:m masks along the reduction axis of every prunable weight of
    a Layer in place; returns the masks (reference ``prune_model``)."""
    from ..nn.layer import param_state

    masks = {}
    for name, value in param_state(model).items():
        if not _prunable(name, value, m):
            continue
        w = np.asarray(value)
        if w.ndim == 2:                      # Linear [in, out]
            mask = create_mask(w, n, m, axis=0)
        else:                                # Conv [out, in/g, kh, kw]
            flat = w.reshape(w.shape[0], -1)
            mask = create_mask(flat, n, m, axis=-1).reshape(w.shape)
        model._set_by_path(name, jnp.asarray(w * mask))
        masks[name] = mask
    return masks


class ASPHelper:
    """Keeps masks and re-applies them after optimizer steps (the
    reference hooks ``optimizer.step``; here call ``apply_masks`` after
    each update or use it as a TrainStep ``grad_transform``)."""

    def __init__(self, model, n: int = 2, m: int = 4):
        self.model = model
        self.masks = prune_model(model, n, m)

    def apply_masks(self, params: Dict[str, jnp.ndarray]):
        out = dict(params)
        for name, mask in self.masks.items():
            if name in out:
                out[name] = out[name] * jnp.asarray(mask)
        return out

    def mask_grads(self, grads):
        """grad_transform hook: masked weights receive no gradient, so
        pruned entries stay zero through training."""
        return self.apply_masks(grads)
