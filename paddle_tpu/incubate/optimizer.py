"""Incubate optimizers: LookAhead, ModelAverage.

Reference parity: ``python/paddle/incubate/optimizer/{lookahead,
modelaverage}.py``. Both wrap an inner optimizer's functional
``init``/``update`` contract, so they compose with TrainStep /
DistributedTrainStep unchanged.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k steps forward, one step back (Zhang et al. 2019; reference
    ``lookahead.py``): slow weights interpolate toward fast weights every
    ``k`` inner steps."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5,
                 name=None):
        self.inner = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)

    def init(self, params) -> Dict[str, Any]:
        return {
            "inner": self.inner.init(params),
            # copy: slow weights must not alias params (TrainStep donates
            # both pytrees — aliased buffers would be donated twice)
            "slow": jax.tree.map(lambda p: jnp.array(p, copy=True), params),
            "la_step": jnp.zeros((), jnp.int32),
        }

    def update(self, grads, state, params):
        new_params, inner_state = self.inner.update(grads, state["inner"],
                                                    params)
        step = state["la_step"] + 1
        sync = (step % self.k) == 0

        def blend(slow, fast):
            merged = slow + self.alpha * (fast - slow)
            return jnp.where(sync, merged, slow)

        new_slow = jax.tree.map(blend, state["slow"], new_params)
        # on sync steps the fast weights jump to the slow weights
        new_params = jax.tree.map(
            lambda slow, fast: jnp.where(sync, slow, fast),
            new_slow, new_params)
        return new_params, {"inner": inner_state, "slow": new_slow,
                            "la_step": step}

    # passthrough for LR scheduling APIs
    def get_lr(self, step=None):
        return self.inner.get_lr(step)

    def set_lr(self, value):
        self.inner.set_lr(value)


class ModelAverage:
    """Maintains a running average of parameters for evaluation
    (reference ``modelaverage.py``: EMA-style with min/max average
    window). ``apply(state)`` yields the averaged params; training
    continues on the raw ones."""

    def __init__(self, inner_optimizer, average_window_rate: float = 0.15,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        self.inner = inner_optimizer
        self.rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)

    def init(self, params) -> Dict[str, Any]:
        return {
            "inner": self.inner.init(params),
            "sum": jax.tree.map(jnp.zeros_like, params),
            "count": jnp.zeros((), jnp.int32),
            "num_updates": jnp.zeros((), jnp.int32),
        }

    def update(self, grads, state, params):
        new_params, inner_state = self.inner.update(grads, state["inner"],
                                                    params)
        num_updates = state["num_updates"] + 1
        count = state["count"] + 1
        # reference windowing: the average window grows with training
        # (rate * num_updates), clamped to [min_window, max_window]; when
        # the accumulator exceeds it, restart the window from the current
        # params (the reference's sum_1/sum_2/sum_3 block rotation,
        # modelaverage.py, collapsed to a single-block restart)
        window = jnp.clip(
            (self.rate * num_updates.astype(jnp.float32)).astype(jnp.int32),
            self.min_window, self.max_window)
        overflow = count > window
        new_sum = jax.tree.map(
            lambda s, p: jnp.where(overflow, p, s + p),
            state["sum"], new_params)
        count = jnp.where(overflow, jnp.ones((), jnp.int32), count)
        return new_params, {"inner": inner_state, "sum": new_sum,
                            "count": count, "num_updates": num_updates}

    def apply(self, state):
        """Averaged parameters for eval."""
        c = jnp.maximum(state["count"], 1).astype(jnp.float32)
        return jax.tree.map(lambda s: s / c, state["sum"])

    def get_lr(self, step=None):
        return self.inner.get_lr(step)

    def set_lr(self, value):
        self.inner.set_lr(value)
