"""incubate.nn — "fused" transformer building blocks.

Reference parity: ``python/paddle/incubate/nn/`` (FusedMultiHeadAttention,
FusedFeedForward, FusedTransformerEncoderLayer, FusedMoELayer — python
wrappers over hand-fused CUDA megakernels). TPU-native: XLA performs the
same fusions automatically from the unfused graph, so these classes are
API-compatible shells over the standard layers — kept so ported scripts
importing ``paddle.incubate.nn`` run unchanged, with the same constructor
signatures.
"""
from __future__ import annotations

from ..nn import MultiHeadAttention, TransformerEncoderLayer
from ..nn.layer import Layer

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMoELayer"]


class FusedMultiHeadAttention(Layer):
    """Reference fused-MHA SEMANTICS, not just attention: the fused op is
    (pre-/post-)LayerNorm + attention + output dropout + residual add in
    one kernel (``incubate/nn/layer/fused_transformer.py``), so the shell
    must compute the same function — XLA re-fuses the chain anyway."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ..nn import Dropout, LayerNorm

        self.attn = MultiHeadAttention(embed_dim, num_heads,
                                       dropout=attn_dropout_rate,
                                       kdim=kdim, vdim=vdim,
                                       need_weights=need_weights)
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.normalize_before = normalize_before

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        # the fused op computes qkv from ONE input (self-attention); the
        # reference likewise requires key/value to be the query
        residual = query
        if self.normalize_before:
            query = self.norm(query)
        out = self.attn(query, query, query, attn_mask=attn_mask,
                        cache=cache)
        if cache is not None:  # incremental decoding: (out, new_cache)
            out, new_cache = out
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return (out, new_cache) if cache is not None else out


class FusedFeedForward(Layer):
    """position-wise FFN (linear -> act -> dropout -> linear) matching the
    reference's fused kernel signature."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-05, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ..nn import Dropout, LayerNorm, Linear

        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm = LayerNorm(d_model, epsilon=epsilon)
        self.dropout1 = Dropout(act_dropout_rate
                                if act_dropout_rate is not None
                                else dropout_rate)
        self.dropout2 = Dropout(dropout_rate)
        self.activation = activation

    def forward(self, src):
        from ..nn import functional as F

        residual = src
        if self.normalize_before:
            src = self.norm(src)
        act = getattr(F, self.activation)
        src = self.linear2(self.dropout1(act(self.linear1(src))))
        out = residual + self.dropout2(src)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(TransformerEncoderLayer):
    """Reference fused encoder layer — same graph, XLA-fused. Keeps the
    reference's ``*_rate`` kwarg names (the base layer uses paddle.nn's
    ``dropout``/``attn_dropout``/``act_dropout`` spelling)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__(d_model, nhead, dim_feedforward,
                         dropout=dropout_rate, activation=activation,
                         attn_dropout=attn_dropout_rate,
                         act_dropout=act_dropout_rate,
                         normalize_before=normalize_before,
                         weight_attr=weight_attr, bias_attr=bias_attr)


def FusedMoELayer(*args, **kwargs):
    """The reference's fused MoE — delegates to the EP-sharded MoELayer."""
    from ..distributed.parallel.moe import MoELayer

    return MoELayer(*args, **kwargs)

