"""paddle_tpu.incubate — experimental APIs.

Reference parity: ``python/paddle/incubate/`` — ``autograd/`` (functional
jvp/vjp/Jacobian/Hessian), ``asp/`` (2:4 structured sparsity),
``optimizer/`` (LookAhead, ModelAverage). The MoE layers live in
``paddle_tpu.distributed.parallel.moe`` (already first-class here).
"""
from . import asp, autograd, nn
from .optimizer import LookAhead, ModelAverage

__all__ = ["autograd", "asp", "nn", "LookAhead", "ModelAverage"]
