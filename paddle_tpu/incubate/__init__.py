"""paddle_tpu.incubate — experimental APIs.

Reference parity: ``python/paddle/incubate/`` — ``autograd/`` (functional
jvp/vjp/Jacobian/Hessian), ``asp/`` (2:4 structured sparsity),
``optimizer/`` (LookAhead, ModelAverage). The MoE layers live in
``paddle_tpu.distributed.parallel.moe`` (already first-class here).
"""
from . import asp, autograd, nn
from .operators import (  # noqa: F401
    graph_khop_sampler, graph_reindex, graph_sample_neighbors,
    graph_send_recv, identity_loss, segment_max, segment_mean, segment_min,
    segment_sum, softmax_mask_fuse, softmax_mask_fuse_upper_triangle,
)
from .optimizer import LookAhead, ModelAverage

__all__ = ["autograd", "asp", "nn", "LookAhead", "ModelAverage",
           "graph_khop_sampler", "graph_reindex", "graph_sample_neighbors",
           "graph_send_recv", "identity_loss", "segment_max",
           "segment_mean", "segment_min", "segment_sum",
           "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle"]
