"""Functional autodiff — jvp/vjp/Jacobian/Hessian.

Reference parity: ``python/paddle/incubate/autograd/functional.py`` (jvp,
vjp, Jacobian, Hessian over the primitive-lowering engine, ~5k LoC of
transform machinery). TPU-native: jax's transforms ARE this engine; the
wrappers only adapt the calling convention (paddle returns
``(outputs, results)`` pairs and matrix-shaped Jacobian/Hessian views).
"""
from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "jacobian", "hessian"]


def _tuplify(x):
    return x if isinstance(x, (tuple, list)) else (x,)


def jvp(func: Callable, xs, v=None):
    """Forward-mode: returns ``(func(xs), J @ v)``; v defaults to ones."""
    xs = _tuplify(xs)
    if v is None:
        v = tuple(jnp.ones_like(x) for x in xs)
    else:
        v = _tuplify(v)
    out, tangent = jax.jvp(func, tuple(jnp.asarray(x) for x in xs),
                           tuple(jnp.asarray(t) for t in v))
    return out, tangent


def vjp(func: Callable, xs, v=None):
    """Reverse-mode: returns ``(func(xs), v @ J)``; v defaults to ones."""
    xs = _tuplify(xs)
    out, pullback = jax.vjp(func, *(jnp.asarray(x) for x in xs))
    if v is None:
        v = jax.tree.map(jnp.ones_like, out)
    grads = pullback(v)
    if len(xs) == 1:
        grads = grads[0]
    return out, grads


class Jacobian:
    """Lazy matrix view of d(func)/d(xs) (reference ``Jacobian``: index
    ``J[:]``/rows/cols; computed via vmapped reverse-mode)."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self.func = func
        self.xs = jnp.asarray(xs)
        self.is_batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is None:
            if self.is_batched:
                jac = jax.vmap(jax.jacrev(self.func))(self.xs)
                b = self.xs.shape[0]
                self._mat = jac.reshape(b, -1, int(
                    jnp.prod(jnp.asarray(self.xs.shape[1:]))))
            else:
                jac = jax.jacrev(self.func)(self.xs)
                out_sz = int(jnp.asarray(jac).size // self.xs.size)
                self._mat = jnp.asarray(jac).reshape(out_sz, self.xs.size)
        return self._mat

    def __getitem__(self, idx):
        return self._compute()[idx]

    @property
    def shape(self):
        return self._compute().shape


class Hessian:
    """Matrix view of d²(scalar func)/dx² (reference ``Hessian``)."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self.func = func
        self.xs = jnp.asarray(xs)
        self.is_batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is None:
            if self.is_batched:
                h = jax.vmap(jax.hessian(self.func))(self.xs)
                b = self.xs.shape[0]
                n = int(jnp.prod(jnp.asarray(self.xs.shape[1:])))
                self._mat = h.reshape(b, n, n)
            else:
                h = jax.hessian(self.func)(self.xs)
                n = self.xs.size
                self._mat = jnp.asarray(h).reshape(n, n)
        return self._mat

    def __getitem__(self, idx):
        return self._compute()[idx]

    @property
    def shape(self):
        return self._compute().shape


def jacobian(func: Callable, xs):
    """Eager full Jacobian (paddle 2.x ``paddle.autograd.jacobian``)."""
    return Jacobian(func, xs)[:]


def hessian(func: Callable, xs):
    return Hessian(func, xs)[:]
