"""incubate op wrappers: graph sampling + segment + fused-softmax names.

Reference parity: ``python/paddle/incubate/__init__.py`` exports —
``graph_khop_sampler``/``graph_sample_neighbors``/``graph_reindex``/
``graph_send_recv`` (``incubate/operators/graph_*.py``, deprecated
aliases of the ``paddle.geometric`` API, kept because ported code still
imports them), ``segment_{sum,mean,min,max}``
(``incubate/tensor/math.py``), ``identity_loss``, and
``softmax_mask_fuse(_upper_triangle)``
(``incubate/operators/softmax_mask_fuse*.py`` — hand-fused CUDA in the
reference; a plain composition here, XLA fuses it).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import geometric as G

__all__ = ["graph_khop_sampler", "graph_sample_neighbors", "graph_reindex",
           "graph_send_recv", "segment_sum", "segment_mean", "segment_min",
           "segment_max", "identity_loss", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle"]


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    if return_eids:
        raise NotImplementedError("edge-id return is not tracked by the "
                                  "geometric sampler")
    return G.sample_neighbors(row, colptr, input_nodes,
                              sample_size=sample_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    if return_eids:
        raise NotImplementedError("edge-id return is not tracked by the "
                                  "geometric sampler")
    return G.khop_sampler(row, colptr, input_nodes, sample_sizes)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    return G.reindex_graph(x, neighbors, count)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    return G.send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                         out_size=out_size)


def _num_segments(seg, num_segments):
    """Eager default: max(seg)+1. Under jit, ids are traced and the
    output shape must be static — pass ``num_segments`` explicitly."""
    if num_segments is not None:
        return int(num_segments)
    return int(np.asarray(seg).max()) + 1 if seg.size else 0


def _segment(reduce_fn):
    def apply(data, segment_ids, num_segments=None, name=None):
        data = jnp.asarray(data)
        seg = jnp.asarray(segment_ids)
        return reduce_fn(data, seg,
                         num_segments=_num_segments(seg, num_segments))

    return apply


segment_sum = _segment(jax.ops.segment_sum)
segment_max = _segment(jax.ops.segment_max)
segment_min = _segment(jax.ops.segment_min)


def segment_mean(data, segment_ids, num_segments=None, name=None):
    data = jnp.asarray(data)
    seg = jnp.asarray(segment_ids)
    num = _num_segments(seg, num_segments)
    s = jax.ops.segment_sum(data, seg, num_segments=num)
    cnt = jax.ops.segment_sum(jnp.ones_like(seg, data.dtype), seg,
                              num_segments=num)
    shape = (-1,) + (1,) * (data.ndim - 1)
    return s / jnp.maximum(cnt.reshape(shape), 1)


def identity_loss(x, reduction="none", name=None):
    """Reference ``identity_loss``: marks a tensor as the loss for IPU
    pipelining; numerically reduce-or-identity."""
    x = jnp.asarray(x)
    if reduction in ("mean", 1):
        return jnp.mean(x)
    if reduction in ("sum", 0):
        return jnp.sum(x)
    return x


def softmax_mask_fuse(x, mask, name=None):
    """Reference fused softmax(x + mask) (CUDA kernel
    ``fused_softmax_mask_op``); XLA fuses the composition."""
    return jax.nn.softmax(jnp.asarray(x) + jnp.asarray(mask), axis=-1)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with the causal (upper-triangle masked) pattern fused."""
    x = jnp.asarray(x)
    L = x.shape[-1]
    mask = jnp.tril(jnp.ones((x.shape[-2], L), bool), k=L - x.shape[-2])
    return jax.nn.softmax(jnp.where(mask, x, jnp.finfo(x.dtype).min),
                          axis=-1)
