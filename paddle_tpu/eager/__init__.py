"""Eager dygraph ergonomics: ``Tensor`` with ``.backward()`` / ``.grad``.

Reference parity: the eager autograd engine —
``paddle/fluid/eager/backward.cc:393`` (``egr::Backward`` queue-based topo
traversal over ``GradNodeBase``) and the python ``Tensor.backward`` patch
(``python/paddle/fluid/dygraph/varbase_patch_methods.py:224``).

TPU-native redesign: instead of 21k LoC of per-op GradNode classes, every
eager op executes through ``jax.vjp`` — the op IS its own grad node. A
:class:`Tensor` wraps a ``jax.Array`` plus a tape node (the vjp closure and
its parent tensors); ``backward()`` runs the same reverse topological
accumulation the reference does, seeding with ones. A whole ``nn.Layer``
call is ONE tape node (vjp over ``functional_call``), so layer parameters
get ``.grad``-style accumulation without per-op Python dispatch overhead —
the eager path stays usable while ``jit``/TrainStep remains the perf path.

Usage (ported paddle script shape)::

    import paddle_tpu as pt
    pt.eager.enable()                 # install Tensor-aware dispatch
    model = MyNet()
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=model)
    for x, y in loader:
        out = model(pt.eager.to_tensor(x))
        loss = F.cross_entropy(out, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Tensor", "to_tensor", "enable", "enabled", "no_grad", "grads_of",
    "clear_grads", "apply_op", "run_backward", "PyLayer", "PyLayerContext",
    "saved_tensors_hooks", "set_strict", "strict_enabled",
]

_state = threading.local()


def _grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


@contextlib.contextmanager
def no_grad():
    """``paddle.no_grad`` analogue for the eager tape."""
    prev = _grad_enabled()
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


# Strict tape mode (default ON): converting a grad-requiring Tensor to a raw
# numpy/jax array while recording silently detaches it from the tape — the
# classic silent-wrong-grads bug (reference guards the analogous leak via
# inplace-version checks, eager/tensor_wrapper.h). The guard raises instead;
# convert deliberately with .detach()/.numpy() or under no_grad().
_strict = [True]


def set_strict(flag: bool) -> bool:
    """Toggle the Tensor→array leak guard; returns the previous value."""
    prev, _strict[0] = _strict[0], bool(flag)
    return prev


def strict_enabled() -> bool:
    return _strict[0]


class _HookHandle:
    """Removable handle returned by :meth:`Tensor.register_hook`."""

    _next_id = [0]

    def __init__(self, hooks: Dict[int, Callable]):
        self._hooks = hooks
        self._id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1

    def remove(self) -> None:
        self._hooks.pop(self._id, None)


class _Node:
    """One tape entry: a vjp closure + the tensors/param-sinks it feeds.

    Multi-output ops share ONE node across their output tensors; backward
    gathers the cotangents of every output and calls ``vjp_fn`` once with
    the full tuple — the reference's single-``GradNode``-per-op contract
    (a PyLayer backward must see all its output grads in one call)."""

    __slots__ = ("vjp_fn", "parents", "outputs", "out_avals", "multi",
                 "materialize")

    def __init__(self, vjp_fn, parents):
        self.vjp_fn = vjp_fn
        self.parents = parents  # list of Tensor | _ParamSink
        self.outputs = []       # weakrefs to output Tensors (set by _wrap_out)
        self.out_avals = []     # (shape, dtype) per output, for zero cts
        self.multi = False      # True when the op returned a tuple/list
        self.materialize = True  # zero-fill missing output cts (jax vjp
        # closures need full tuples; PyLayer manages its own per ctx)


class _ParamSink:
    """Grad destination for a Layer's parameter pytree (one per layer call)."""

    __slots__ = ("layer", )

    def __init__(self, layer):
        self.layer = layer

    def deposit(self, grads: Dict[str, Any]):
        store = getattr(self.layer, "_eager_grads", None)
        if store is None:
            store = {}
            object.__setattr__(self.layer, "_eager_grads", store)
        for k, g in grads.items():
            if g is None:
                continue
            store[k] = g if k not in store else store[k] + g


class Tensor:
    """Eager tensor: a ``jax.Array`` + autograd metadata.

    ``stop_gradient`` follows paddle semantics (True by default for data;
    ops that depend on a grad-requiring input produce grad-requiring
    outputs)."""

    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_hooks",
                 "__weakref__")

    def __init__(self, data, stop_gradient: bool = True, _node: Optional[_Node] = None):
        self._data = data if isinstance(data, jax.Array) else jnp.asarray(data)
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = _node

    # ------------------------------------------------------------- basics
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.ndim else 1

    def numpy(self):
        return np.asarray(self._data)

    def item(self):
        # tpu-lint: disable=R1(eager-mode API — .item() IS the documented sync point; never trace-reachable)
        return self._data.item()

    def detach(self) -> "Tensor":
        return Tensor(self._data, stop_gradient=True)

    def register_hook(self, hook: Callable) -> "_HookHandle":
        """Register ``hook(grad) -> grad | None`` fired when this tensor's
        gradient is computed during ``backward()``; a non-None return
        replaces the gradient both for ``.grad`` and for further backprop
        (reference ``Tensor.register_hook``). Returns a removable handle."""
        if not _requires_grad(self):
            raise RuntimeError(
                "cannot register a backward hook on a tensor that stops "
                "gradient (set stop_gradient=False first)")
        hooks = getattr(self, "_hooks", None)
        if hooks is None:
            hooks = {}
            self._hooks = hooks
        handle = _HookHandle(hooks)
        hooks[handle._id] = hook
        return handle

    def _run_hooks(self, ct):
        hooks = getattr(self, "_hooks", None)
        if not hooks:
            return ct
        for hook in list(hooks.values()):
            r = hook(Tensor(ct, stop_gradient=True))
            if r is not None:
                ct = _unwrap(r)
        return ct

    # -- raw-array conversion (strict-mode leak guard) --------------------
    def _guard_convert(self):
        if _strict[0] and _grad_enabled() and _requires_grad(self):
            raise RuntimeError(
                "converting a grad-requiring eager Tensor to a raw array "
                "would silently detach it from the autograd tape; call "
                ".detach() / .numpy() explicitly or convert under "
                "eager.no_grad() (or eager.set_strict(False))")

    def __array__(self, dtype=None):
        self._guard_convert()
        arr = np.asarray(self._data)
        return arr.astype(dtype) if dtype is not None else arr

    def __jax_array__(self):
        self._guard_convert()
        return self._data

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        """numpy ufuncs on Tensors (``np.exp(t)``, ``arr + t``): every
        Tensor input passes the leak guard (grad-requiring ones raise
        under strict mode — the result would be a detached ndarray),
        data tensors compute through numpy and return an ndarray."""
        arrays = []
        for x in inputs:
            if isinstance(x, Tensor):
                x._guard_convert()
                arrays.append(np.asarray(x._data))
            else:
                arrays.append(x)
        return getattr(ufunc, method)(*arrays, **kwargs)

    def clone(self) -> "Tensor":
        return apply_op(lambda x: x * 1, self)

    def clear_grad(self):
        self.grad = None

    # -- in-place variants (reference fill_/zero_ Tensor methods). JAX
    # arrays are immutable, so "in-place" means swapping the wrapped
    # buffer; only allowed off the tape (paddle similarly forbids inplace
    # on grad-tracked leaves).
    def _inplace_guard(self, opname: str):
        if _grad_enabled() and _requires_grad(self):
            raise RuntimeError(
                f"{opname} on a grad-requiring tensor would invalidate the "
                "tape; detach() first or run under eager.no_grad()")

    def fill_(self, value) -> "Tensor":
        self._inplace_guard("fill_")
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self) -> "Tensor":
        return self.fill_(0)

    def fill_diagonal_(self, value, offset: int = 0, wrap: bool = False) -> "Tensor":
        self._inplace_guard("fill_diagonal_")
        h, w = self._data.shape[-2], self._data.shape[-1]
        # diagonal length differs for rectangular matrices by offset sign
        if offset >= 0:
            n = max(0, min(h, w - offset))
            r0, c0 = 0, offset
        else:
            n = max(0, min(h + offset, w))
            r0, c0 = -offset, 0
        rows = list(range(r0, r0 + n))
        cols = list(range(c0, c0 + n))
        if wrap and h > w and offset == 0:
            # tall matrices restart the diagonal every w+1 rows
            r = w + 1
            while r + 0 < h:
                k = min(w, h - r)
                rows += list(range(r, r + k))
                cols += list(range(0, k))
                r += w + 1
        if rows:
            self._data = self._data.at[..., jnp.asarray(rows),
                                       jnp.asarray(cols)].set(value)
        return self

    def astype(self, dtype) -> "Tensor":
        from ..framework.dtype import convert_dtype

        return apply_op(lambda x: x.astype(convert_dtype(dtype)), self)

    def __repr__(self):
        return (f"Tensor(shape={self.shape}, dtype={self._data.dtype}, "
                f"stop_gradient={self.stop_gradient},\n{np.asarray(self._data)})")

    def __len__(self):
        return self._data.shape[0]

    def __float__(self):
        return float(self._data)

    def __int__(self):
        return int(self._data)

    def __bool__(self):
        return bool(self._data)

    # ------------------------------------------------------------ backward
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        """Reverse accumulation from this tensor (reference
        ``egr::Backward``): topological walk over tape nodes, cotangent
        accumulation per tensor, leaf grads deposited on ``.grad`` /
        layer parameter stores."""
        if self._node is None and self.stop_gradient:
            raise RuntimeError("backward() on a tensor with no grad history")
        seed = (jnp.ones_like(self._data) if grad_tensor is None
                else jnp.asarray(getattr(grad_tensor, "_data", grad_tensor)))
        run_backward([(self, seed)], retain_graph=retain_graph)

    # ---------------------------------------------------------- operators
    def _binop(self, other, fn):
        return apply_op(fn, self, other)

    def __add__(self, o):
        return self._binop(o, jnp.add)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, jnp.subtract)

    def __rsub__(self, o):
        return apply_op(jnp.subtract, o, self)

    def __mul__(self, o):
        return self._binop(o, jnp.multiply)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, jnp.divide)

    def __rtruediv__(self, o):
        return apply_op(jnp.divide, o, self)

    def __matmul__(self, o):
        return self._binop(o, jnp.matmul)

    def __pow__(self, o):
        return self._binop(o, jnp.power)

    def __neg__(self):
        return apply_op(jnp.negative, self)

    def __getitem__(self, idx):
        return apply_op(lambda x: x[idx], self)

    def __eq__(self, o):  # noqa: E501 comparison returns data tensor (no grad)
        return Tensor(self._data == _unwrap(o))

    def __ne__(self, o):
        return Tensor(self._data != _unwrap(o))

    def __lt__(self, o):
        return Tensor(self._data < _unwrap(o))

    def __le__(self, o):
        return Tensor(self._data <= _unwrap(o))

    def __gt__(self, o):
        return Tensor(self._data > _unwrap(o))

    def __ge__(self, o):
        return Tensor(self._data >= _unwrap(o))

    def __hash__(self):
        return id(self)

    # common methods routed through the tape
    def reshape(self, shape):
        return apply_op(lambda x: jnp.reshape(x, shape), self)

    def transpose(self, perm=None):
        return apply_op(lambda x: jnp.transpose(x, perm), self)

    def flatten(self, start_axis=0, stop_axis=-1):
        from .. import ops

        return apply_op(lambda x: ops.flatten(x, start_axis, stop_axis), self)

    def sum(self, axis=None, keepdim=False):
        return apply_op(lambda x: jnp.sum(x, axis=axis, keepdims=keepdim), self)

    def mean(self, axis=None, keepdim=False):
        return apply_op(lambda x: jnp.mean(x, axis=axis, keepdims=keepdim), self)

    def max(self, axis=None, keepdim=False):
        return apply_op(lambda x: jnp.max(x, axis=axis, keepdims=keepdim), self)

    def min(self, axis=None, keepdim=False):
        return apply_op(lambda x: jnp.min(x, axis=axis, keepdims=keepdim), self)

    def matmul(self, other):
        return self.__matmul__(other)

    def __getattr__(self, name):
        # delegate unknown methods to paddle_tpu.ops through the tape
        from .. import ops

        fn = getattr(ops, name, None)
        if fn is None or not callable(fn):
            raise AttributeError(name)

        def method(*args, **kwargs):
            return apply_op(fn, self, *args, **kwargs)

        return method


def _reverse_walk(roots_and_seeds, retain_graph: bool,
                  write_grads: bool, targets=None):
    """Shared reverse pass over the tape: joint multi-root cotangent
    accumulation in one topological sweep. ``write_grads=True`` deposits
    ``.grad`` on every reached non-root tensor (``backward`` semantics);
    ``targets`` (a dict ``id -> Tensor``) collects the accumulated
    cotangent of those tensors instead (``paddle.grad`` partial-grad
    semantics). Returns the collected ``{id: cotangent}``."""
    # topo order over tape NODES (a multi-output op is one node whose vjp
    # runs once with all of its outputs' cotangents)
    order: List[_Node] = []
    seen = set()

    def visit(node: _Node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for p in node.parents:
            if isinstance(p, Tensor) and p._node is not None:
                visit(p._node)
        order.append(node)

    cotangents: Dict[int, Any] = {}
    root_ids = set()
    for t, seed in roots_and_seeds:
        if t._node is not None:
            visit(t._node)
        root_ids.add(id(t))
        cur = cotangents.get(id(t))
        cotangents[id(t)] = seed if cur is None else cur + seed
    targets = targets or {}
    collected: Dict[int, Any] = {}
    leaves: Dict[int, "Tensor"] = {}
    for node in reversed(order):
        outs = [(r() if r is not None else None) for r in node.outputs]
        cts, any_ct = [], False
        for tout, aval in zip(outs, node.out_avals):
            ct = cotangents.pop(id(tout), None) if tout is not None else None
            if ct is not None:
                any_ct = True
                # hooks fire once per tensor with the FULLY accumulated
                # grad (all consumer + root contributions merged)
                ct = tout._run_hooks(ct)
                if id(tout) in targets:
                    cur = collected.get(id(tout))
                    collected[id(tout)] = ct if cur is None else cur + ct
                if write_grads and id(tout) not in root_ids \
                        and not tout.stop_gradient:
                    tout.grad = (ct if tout.grad is None
                                 else tout.grad + ct)
            cts.append(ct)
        if not any_ct:
            continue
        if node.multi:
            full = tuple(
                (jnp.zeros(a[0], a[1])
                 if ct is None and a is not None and node.materialize
                 else ct)
                for ct, a in zip(cts, node.out_avals))
            parent_cts = node.vjp_fn(full)
        else:
            parent_cts = node.vjp_fn(cts[0])
        for p, pct in zip(node.parents, parent_cts):
            if pct is None:
                continue
            if isinstance(p, _ParamSink):
                if write_grads:
                    p.deposit(pct)
            elif isinstance(p, Tensor):
                if p._node is not None:
                    cur = cotangents.get(id(p))
                    cotangents[id(p)] = pct if cur is None else cur + pct
                elif not p.stop_gradient or id(p) in targets:
                    cur = cotangents.get(id(p))
                    cotangents[id(p)] = pct if cur is None else cur + pct
                    leaves[id(p)] = p
        if not retain_graph:
            for tout in outs:
                if tout is not None:
                    tout._node = None
    for pid, p in leaves.items():
        ct = cotangents.pop(pid, None)
        if ct is None:
            continue
        ct = p._run_hooks(ct)
        if pid in targets:
            cur = collected.get(pid)
            collected[pid] = ct if cur is None else cur + ct
        if write_grads and not p.stop_gradient:
            p.grad = ct if p.grad is None else p.grad + ct
    # a target that is itself a node-less root (grad([x], [x])) was seeded
    # but never popped at a node or as a leaf parent: its cotangent is the
    # seed — the reference returns ones for an output differentiated
    # w.r.t. itself
    for tid, ct in cotangents.items():
        if tid in targets and tid not in collected:
            # hooks fire on this path like every other collection path
            collected[tid] = targets[tid]._run_hooks(ct)
    return collected


def run_backward(roots_and_seeds, retain_graph: bool = False) -> None:
    """Joint reverse pass from one or more roots (reference
    ``egr::RunBackward``): all seeds are planted up front, so a tensor
    reachable from several roots accumulates its FULL cotangent before its
    hooks fire and its vjp runs once — the multi-root semantics
    ``paddle.autograd.backward`` promises (sequential per-root passes would
    fire hooks with partial gradients).

    Roots themselves do not receive ``.grad`` (they are seeded, not
    computed); every other non-stop-gradient tensor does.
    """
    _reverse_walk(roots_and_seeds, retain_graph, write_grads=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """Partial gradients of ``outputs`` w.r.t. ``inputs`` (reference
    ``python/paddle/fluid/dygraph/base.py:468`` ``paddle.grad``, the
    ``GeneralGrad`` engine entry): returns the grads as a list WITHOUT
    touching any tensor's ``.grad``. ``create_graph`` (higher-order via
    taping the backward itself) is not supported on this tape — use the
    functional transforms (``paddle_tpu.incubate.autograd`` jvp/vjp/
    Hessian), which compose arbitrarily."""
    if create_graph:
        raise NotImplementedError(
            "create_graph=True is not supported on the eager tape; use the "
            "functional autodiff in paddle_tpu.incubate.autograd "
            "(jvp/vjp/Hessian) for higher-order gradients")
    if not only_inputs:
        raise NotImplementedError(
            "only_inputs=False is deprecated in the reference and "
            "unsupported here")
    if no_grad_vars is not None:
        raise NotImplementedError(
            "no_grad_vars is unsupported; mark tensors stop_gradient "
            "before building the graph instead")
    single_out = isinstance(outputs, Tensor)
    outputs = [outputs] if single_out else list(outputs)
    single_in = isinstance(inputs, Tensor)
    inputs = [inputs] if single_in else list(inputs)
    for t in outputs + inputs:
        if not isinstance(t, Tensor):
            raise TypeError("grad() outputs/inputs must be eager Tensors")
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    grad_outputs = list(grad_outputs)
    if len(grad_outputs) != len(outputs):
        raise ValueError("grad_outputs must match outputs in length")
    roots = []
    for t, g in zip(outputs, grad_outputs):
        seed = (jnp.ones_like(t._data) if g is None
                else jnp.asarray(_unwrap(g)))
        roots.append((t, seed))
    targets = {id(t): t for t in inputs}
    retain = bool(retain_graph) if retain_graph is not None else False
    collected = _reverse_walk(roots, retain, write_grads=False,
                              targets=targets)
    results = []
    for t in inputs:
        ct = collected.get(id(t))
        if ct is None and not allow_unused:
            raise RuntimeError(
                "one of the inputs is unreachable from outputs; pass "
                "allow_unused=True to get None for it")
        results.append(None if ct is None else Tensor(ct))
    return results


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _requires_grad(t: Tensor) -> bool:
    return (not t.stop_gradient) or t._node is not None


def to_tensor(data, dtype=None, stop_gradient: bool = True) -> Tensor:
    from ..framework.dtype import convert_dtype

    arr = jnp.asarray(_unwrap(data))
    if dtype is not None:
        arr = arr.astype(convert_dtype(dtype))
    return Tensor(arr, stop_gradient=stop_gradient)


def apply_op(fn: Callable, *args, **kwargs) -> Any:
    """Execute ``fn`` on unwrapped arrays, recording a tape node when any
    Tensor argument requires grad. Non-Tensor args pass through; Tensor
    kwargs are unwrapped without grad tracking."""
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    diff_pos = [i for i in tensor_pos
                if _grad_enabled() and _requires_grad(args[i])]
    kw = {k: _unwrap(v) for k, v in kwargs.items()}

    if not diff_pos:
        out = fn(*[_unwrap(a) for a in args], **kw)
        return _wrap_out(out, node=None)

    fixed = list(args)

    def call(*diff_vals):
        xs = list(fixed)
        for i, v in zip(diff_pos, diff_vals):
            xs[i] = v
        return fn(*[_unwrap(a) for a in xs], **kw)

    primals = tuple(args[i]._data for i in diff_pos)
    out, vjp_fn = jax.vjp(call, *primals)
    node = _Node(vjp_fn, [args[i] for i in diff_pos])
    return _wrap_out(out, node)


def _wrap_out(out, node):
    import weakref

    if isinstance(out, (tuple, list)):
        if node is None:
            return type(out)(Tensor(o) if hasattr(o, "ndim") else o
                             for o in out)
        # multi-output: every element shares the node; backward collects
        # all elements' cotangents and calls the vjp ONCE
        node.multi = True
        wrapped = []
        for o in out:
            if hasattr(o, "ndim"):
                t = Tensor(o, stop_gradient=False, _node=node)
                node.outputs.append(weakref.ref(t))
                node.out_avals.append((o.shape, o.dtype))
                wrapped.append(t)
            else:
                # non-array element: no cotangent slot
                node.outputs.append(None)
                node.out_avals.append(None)
                wrapped.append(o)
        return type(out)(wrapped)
    if not hasattr(out, "ndim"):
        return out
    if node is None:
        return Tensor(out)
    t = Tensor(out, stop_gradient=False, _node=node)
    node.outputs.append(weakref.ref(t))
    node.out_avals.append((out.shape, out.dtype))
    return t


# --------------------------------------------------------- layer integration
def eager_layer_call(layer, *args, **kwargs):
    """Run a whole Layer as ONE tape op: vjp over functional_call. Buffers
    (BN stats...) update eagerly on the layer, matching dygraph."""
    from ..nn.layer import buffer_state, functional_call, param_state

    params = param_state(layer)
    buffers = buffer_state(layer)
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    diff_pos = [i for i in tensor_pos
                if _grad_enabled() and _requires_grad(args[i])]
    track_params = _grad_enabled() and not getattr(layer, "stop_gradient", False)

    if not track_params and not diff_pos:
        out, new_buf = functional_call(
            layer, params, buffers,
            *[_unwrap(a) for a in args], **{k: _unwrap(v) for k, v in kwargs.items()})
        _write_buffers(layer, new_buf)
        return _wrap_out(out, None)

    fixed = list(args)
    kw = {k: _unwrap(v) for k, v in kwargs.items()}

    def call(p, *diff_vals):
        xs = list(fixed)
        for i, v in zip(diff_pos, diff_vals):
            xs[i] = v
        out, new_buf = functional_call(layer, p, buffers,
                                       *[_unwrap(a) for a in xs], **kw)
        return out, new_buf

    primals = (params,) + tuple(args[i]._data for i in diff_pos)
    (out, new_buf), vjp_fn = jax.vjp(call, *primals, has_aux=False)

    # vjp over (out, new_buf): cotangent for new_buf is zeros
    def out_vjp(ct, _vjp=vjp_fn, _buf=new_buf):
        zeros_buf = jax.tree.map(jnp.zeros_like, _buf)
        cts = _vjp((ct, zeros_buf))
        return cts

    _write_buffers(layer, new_buf)
    parents = [_ParamSink(layer)] + [args[i] for i in diff_pos]
    return _wrap_out(out, _Node(out_vjp, parents))


def _write_buffers(layer, new_buf: Dict[str, Any]):
    for name, v in new_buf.items():
        layer._set_by_path(name, v)


def grads_of(layer) -> Dict[str, Any]:
    """Accumulated eager grads for a layer's parameters (path -> array)."""
    return dict(getattr(layer, "_eager_grads", {}) or {})


def clear_grads(layer):
    if getattr(layer, "_eager_grads", None):
        layer._eager_grads.clear()


# --------------------------------------------------------------- dispatch
_enabled = [False]


def enabled() -> bool:
    return _enabled[0]


def enable():
    """Install eager dispatch: Layer.__call__ becomes Tensor-aware and the
    stateful Optimizer step consumes layer grads. Idempotent. The jit /
    TrainStep path is untouched (it never sees Tensor wrappers)."""
    if _enabled[0]:
        return
    from ..nn import layer as layer_mod
    from ..optimizer import optimizer as opt_mod

    orig_call = layer_mod.Layer.__call__

    def call(self, *args, **kwargs):
        if any(isinstance(a, Tensor) for a in args) or \
           any(isinstance(v, Tensor) for v in kwargs.values()):
            for hook in self._forward_pre_hooks.values():
                res = hook(self, args)
                if res is not None:
                    args = res if isinstance(res, tuple) else (res,)
            out = eager_layer_call(self, *args, **kwargs)
            for hook in self._forward_post_hooks.values():
                res = hook(self, args, out)
                if res is not None:
                    out = res
            return out
        return orig_call(self, *args, **kwargs)

    layer_mod.Layer.__call__ = call

    # optimizer: step() over a bound Layer pulls eager grads
    orig_step = opt_mod.Optimizer.step

    def step(self, params=None, grads=None):
        target = self._parameters
        if params is None and grads is None and isinstance(target, layer_mod.Layer):
            from ..nn.layer import param_state

            model = target
            params = param_state(model)
            grads = {k: getattr(model, "_eager_grads", {}).get(k) for k in params}
            grads = {k: (g if g is not None else jnp.zeros_like(params[k]))
                     for k, g in grads.items()}
            if self._state is None:
                self._state = self.init(params)
            new_params, self._state = self.update(grads, self._state, params)
            for k, v in new_params.items():
                model._set_by_path(k, v)
            clear_grads(model)
            return new_params
        return orig_step(self, params=params, grads=grads)

    opt_mod.Optimizer.step = step

    orig_clear = opt_mod.Optimizer.clear_grad

    def clear_grad(self, set_to_zero=True):
        if isinstance(self._parameters, layer_mod.Layer):
            clear_grads(self._parameters)
        return orig_clear(self, set_to_zero=set_to_zero)

    opt_mod.Optimizer.clear_grad = clear_grad

    # nn.functional + ops become Tensor-aware
    from .. import ops as ops_pkg
    from ..nn import functional as F

    _wrap_module(F)
    _wrap_module(ops_pkg)
    _enabled[0] = True


def _wrap_module(mod):
    """Wrap a module's public callables with Tensor-aware dispatch (original
    behavior preserved when no Tensor is passed)."""
    for name in dir(mod):
        if name.startswith("_"):
            continue
        fn = getattr(mod, name)
        if not callable(fn) or isinstance(fn, type) or hasattr(fn, "__eager_wrapped__"):
            continue

        def make(fn):
            def wrapped(*args, **kwargs):
                if any(isinstance(a, Tensor) for a in args) or \
                   any(isinstance(v, Tensor) for v in kwargs.values()):
                    return apply_op(fn, *args, **kwargs)
                return fn(*args, **kwargs)

            wrapped.__eager_wrapped__ = True
            wrapped.__name__ = getattr(fn, "__name__", "op")
            wrapped.__doc__ = fn.__doc__
            return wrapped

        try:
            setattr(mod, name, make(fn))
        except (AttributeError, TypeError):
            pass


from .py_layer import (PyLayer, PyLayerContext,  # noqa: E402
                       saved_tensors_hooks)
