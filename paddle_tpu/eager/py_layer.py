"""User-defined autograd ops for the eager tape: ``PyLayer``.

Reference parity: ``python/paddle/autograd/py_layer.py`` (``PyLayer`` with
``forward(ctx, ...)`` / ``backward(ctx, ...)`` staticmethods, ``ctx.
save_for_backward``/``saved_tensor``, ``mark_non_differentiable``,
``set_materialize_grads``) and ``python/paddle/autograd/
saved_tensors_hooks.py`` (pack/unpack hooks over saved tensors).

TPU-native shape: the eager engine records one tape node per op whose
"grad node" is a ``jax.vjp`` closure (see ``eager/__init__.py``); a
``PyLayer.apply`` records one node whose closure is the user's
``backward`` instead. ``forward`` runs under ``no_grad`` — the custom
backward *replaces* the traced one, exactly the reference's graph-cut
semantics. Inside jit, prefer ``jax.custom_vjp`` (this class is the
dygraph ergonomics layer over the same idea).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Tuple

import jax.numpy as jnp

from . import Tensor, _Node, _unwrap, _wrap_out, _requires_grad, \
    _grad_enabled, no_grad

__all__ = ["PyLayer", "PyLayerContext", "saved_tensors_hooks"]

_hooks_state = threading.local()


def _current_pack_unpack():
    stack = getattr(_hooks_state, "stack", None)
    return stack[-1] if stack else (None, None)


class saved_tensors_hooks:
    """Register a pack/unpack hook pair applied to every tensor stashed by
    ``ctx.save_for_backward`` while the context is active (reference
    ``paddle.autograd.saved_tensors_hooks``): ``pack_hook(tensor) ->
    anything`` runs at save time (offload to host/disk, quantize, ...);
    ``unpack_hook(packed) -> tensor`` runs when backward retrieves it.
    """

    def __init__(self, pack_hook: Callable, unpack_hook: Callable):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        if not hasattr(_hooks_state, "stack"):
            _hooks_state.stack = []
        _hooks_state.stack.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _hooks_state.stack.pop()
        return False


class PyLayerContext:
    """Forward/backward bridge object (the reference's ``PyLayerContext``):
    holds saved tensors plus any attributes the user assigns."""

    def __init__(self):
        self._saved: List[Tuple[Any, Optional[Callable]]] = []
        self._non_differentiable: List[int] = []  # ids of marked outputs
        self._materialize_grads = True

    def save_for_backward(self, *tensors) -> None:
        """Stash tensors for ``backward``; pack hooks (if a
        ``saved_tensors_hooks`` scope is active) run here."""
        pack, unpack = _current_pack_unpack()
        for t in tensors:
            if pack is not None:
                self._saved.append((pack(t), unpack))
            else:
                self._saved.append((t, None))

    def saved_tensor(self):
        """Retrieve saved tensors (unpack hooks run here), as a list —
        matching the reference's ``ctx.saved_tensor()``."""
        out = []
        for packed, unpack in self._saved:
            out.append(unpack(packed) if unpack is not None else packed)
        return out

    def mark_non_differentiable(self, *tensors) -> None:
        """Declare some forward outputs non-differentiable: their incoming
        cotangents are dropped before ``backward`` is called."""
        self._non_differentiable.extend(id(_unwrap(t)) for t in tensors)

    def set_materialize_grads(self, value: bool) -> None:
        """If False, outputs that received no gradient pass ``None`` to
        ``backward`` instead of a zeros tensor."""
        self._materialize_grads = bool(value)


class PyLayer:
    """Custom autograd op: subclass, define ``forward(ctx, *args)`` and
    ``backward(ctx, *grads)`` staticmethods, call ``YourOp.apply(...)``.

    ``backward`` must return one gradient per *Tensor* positional input of
    ``forward``, in order (grads for inputs with ``stop_gradient=True`` are
    discarded). Matches ``python/paddle/autograd/py_layer.py`` semantics.
    """

    @staticmethod
    def forward(ctx: PyLayerContext, *args, **kwargs):
        raise NotImplementedError(
            "PyLayer subclasses must implement forward(ctx, ...)")

    @staticmethod
    def backward(ctx: PyLayerContext, *grads):
        raise NotImplementedError(
            "PyLayer subclasses must implement backward(ctx, ...)")

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        # forward runs outside the tape: the user's backward replaces
        # whatever ops forward executes (the graph-cut PyLayer contract)
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)

        tensor_args = [a for a in args if isinstance(a, Tensor)]
        needs_grad = _grad_enabled() and any(
            _requires_grad(a) for a in tensor_args)
        if not needs_grad:
            return _wrap_out(_unwrap_tree(out), None)

        multi = isinstance(out, (tuple, list))
        outs = [_unwrap(o) for o in out] if multi else [_unwrap(out)]
        out_ids = [id(o) for o in outs]

        def vjp_fn(ct):
            cts = list(ct) if isinstance(ct, (tuple, list)) else [ct]
            if len(cts) != len(outs):
                raise RuntimeError(
                    f"PyLayer backward got {len(cts)} output grads for "
                    f"{len(outs)} outputs")
            grads_in = []
            for g, oid, o in zip(cts, out_ids, outs):
                if oid in ctx._non_differentiable:
                    g = None  # positional slot kept, grad dropped
                elif g is None and ctx._materialize_grads:
                    g = jnp.zeros_like(o)
                grads_in.append(None if g is None
                                else Tensor(g, stop_gradient=True))
            with no_grad():
                res = cls.backward(ctx, *grads_in)
            res = res if isinstance(res, (tuple, list)) else (res,)
            if len(res) != len(tensor_args):
                raise RuntimeError(
                    f"PyLayer backward returned {len(res)} gradients but "
                    f"forward has {len(tensor_args)} Tensor inputs")
            return tuple(None if r is None else _unwrap(r) for r in res)

        node = _Node(vjp_fn, tensor_args)
        # the tape must NOT zero-fill missing output grads: ctx's
        # set_materialize_grads decides that inside vjp_fn itself
        node.materialize = False
        return _wrap_out(_unwrap_tree(out), node)


def _unwrap_tree(out):
    if isinstance(out, (tuple, list)):
        return type(out)(_unwrap(o) for o in out)
    return _unwrap(out)
