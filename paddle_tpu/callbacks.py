"""``paddle.callbacks`` namespace (alias of :mod:`paddle_tpu.hapi.callbacks`,
as the reference aliases ``python/paddle/hapi/callbacks.py``)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, CallbackList, EarlyStopping, History, LRScheduler,
    ModelCheckpoint, ProgBarLogger, ScalarLogger,
)
