"""Graph sampling + reindex (host-side, static-shape outputs).

Reference parity: ``python/paddle/geometric/sampling/neighbors.py``
(``sample_neighbors`` over CSC ``row``/``colptr`` tensors; CUDA kernel
``paddle/phi/kernels/gpu/graph_sample_neighbors_kernel.cu``),
``graph_reindex.py:28`` and ``graph_khop_sampler.py:21``. TPU-native:
sampling is host work feeding padded batches to the chip (SURVEY.md §7);
the heavy store lives in C++ (:class:`paddle_tpu.distributed.ps.graph.GraphTable`),
while this module also accepts plain CSC numpy arrays for API parity.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def _sample_from_csc(row: np.ndarray, colptr: np.ndarray, node: int,
                     k: int, rng: np.random.Generator,
                     replace: bool) -> np.ndarray:
    beg, end = int(colptr[node]), int(colptr[node + 1])
    neigh = row[beg:end]
    # k <= 0 is the "take all neighbors" sentinel regardless of `replace`.
    if neigh.size == 0 or k <= 0 or (not replace and neigh.size <= k):
        return neigh.copy()
    return rng.choice(neigh, size=k, replace=replace)


def sample_neighbors(row, colptr, input_nodes, sample_size: int = -1,
                     replace: bool = False, seed: Optional[int] = None,
                     return_eids: bool = False):
    """Sample neighbors of ``input_nodes`` from a CSC graph.

    Returns ``(out_neighbors, out_count)`` as int64/int32 numpy arrays —
    ``out_neighbors`` is the concatenation of each node's sampled
    neighbors (ragged, like the reference), ``out_count[i]`` its length.
    """
    row = np.asarray(row, np.int64).reshape(-1)
    colptr = np.asarray(colptr, np.int64).reshape(-1)
    nodes = np.asarray(input_nodes, np.int64).reshape(-1)
    rng = np.random.default_rng(seed)
    k = int(sample_size)
    outs, counts = [], np.empty(nodes.size, np.int32)
    for i, u in enumerate(nodes):
        s = _sample_from_csc(row, colptr, int(u), k, rng, replace)
        outs.append(s)
        counts[i] = s.size
    out = (np.concatenate(outs) if outs else np.empty(0, np.int64))
    if return_eids:
        raise NotImplementedError("eids not tracked; store edge ids as "
                                  "features if needed")
    return out.astype(np.int64), counts


def _intern_ids(x):
    """First-seen interning table seeded with ``x``: returns
    ``(local: dict id->idx, out_nodes: list, map_ids)`` where
    ``map_ids(ids) -> np.ndarray`` maps (and interns) a flat id array.
    Shared by reindex_graph / reindex_heter_graph / _khop_core — the
    single definition of the "x first, then first-seen" ordering."""
    local = {int(v): i for i, v in enumerate(x)}
    out_nodes = list(x)

    def map_ids(ids):
        out = np.empty(len(ids), np.int64)
        for i, v in enumerate(ids):
            vi = int(v)
            idx = local.get(vi)
            if idx is None:
                idx = len(out_nodes)
                local[vi] = idx
                out_nodes.append(vi)
            out[i] = idx
        return out

    return local, out_nodes, map_ids


def reindex_graph(x, neighbors, count) -> Tuple[np.ndarray, np.ndarray,
                                                np.ndarray]:
    """Relabel global ids to a compact local space.

    Returns ``(reindex_src, reindex_dst, out_nodes)`` where ``out_nodes``
    starts with ``x`` then first-seen new neighbor ids;
    ``reindex_src[i]`` is the local id of ``neighbors[i]`` and
    ``reindex_dst`` repeats each center's local id ``count[i]`` times —
    exactly the reference's ``graph_reindex`` contract.
    """
    x = np.asarray(x, np.int64).reshape(-1)
    neighbors = np.asarray(neighbors, np.int64).reshape(-1)
    count = np.asarray(count, np.int64).reshape(-1)
    _, out_nodes, map_ids = _intern_ids(x)
    src = map_ids(neighbors)
    dst = np.repeat(np.arange(x.size, dtype=np.int64), count)
    return src, dst, np.asarray(out_nodes, np.int64)


def _khop_core(fetch, input_nodes, sample_sizes):
    """Shared khop mechanics: hop loop + local-id interning + edge
    accumulation. ``fetch(frontier, hop, k) -> list of per-node neighbor
    id-lists`` abstracts the backing store (CSC arrays or a graph
    service)."""
    nodes = np.asarray(input_nodes, np.int64).reshape(-1)
    local = {}
    table = []

    def intern(v: int) -> int:
        idx = local.get(v)
        if idx is None:
            idx = len(table)
            local[v] = idx
            table.append(v)
        return idx

    for u in nodes:
        intern(int(u))
    all_src, all_dst = [], []
    frontier = nodes
    for hop, k in enumerate(sample_sizes):
        if frontier.size == 0:
            break
        per_node = fetch(frontier, hop, int(k))
        nxt = []
        for u, neigh in zip(frontier, per_node):
            du = intern(int(u))
            for v in neigh:
                v = int(v)
                all_src.append(intern(v))
                all_dst.append(du)
                nxt.append(v)
        frontier = np.unique(np.asarray(nxt, np.int64)) if nxt else \
            np.empty(0, np.int64)
    return (np.asarray(all_src, np.int64), np.asarray(all_dst, np.int64),
            np.asarray(table, np.int64))


def khop_sampler(row, colptr, input_nodes, sample_sizes,
                 seed: Optional[int] = None):
    """Multi-hop neighborhood sampling (reference ``graph_khop_sampler``).

    Returns ``(edge_src, edge_dst, sample_index)``: local-id edges over the
    union frontier and the global ids backing each local id.
    """

    def fetch(frontier, hop, k):
        neigh, cnt = sample_neighbors(
            row, colptr, frontier, k,
            seed=None if seed is None else seed + hop)
        out, pos = [], 0
        for c in cnt:
            out.append(neigh[pos:pos + c])
            pos += c
        return out

    return _khop_core(fetch, input_nodes, sample_sizes)


def khop_sampler_from_store(store, input_nodes, sample_sizes,
                            seed: int = 0, with_features: bool = False):
    """Multi-hop sampling over a graph STORE — single-host
    :class:`~paddle_tpu.distributed.ps.graph.GraphTable` or the sharded
    :class:`~paddle_tpu.distributed.ps.graph.DistGraphClient` — the GNN
    minibatch feed of the reference's GpuPs khop path
    (``graph_khop_sampler.py`` over ``GpuPsGraphTable``).

    Because per-node sampling is deterministic in (seed, node), the
    subgraph is IDENTICAL whether the store is local or sharded across
    servers. Returns ``(edge_src, edge_dst, sample_index)`` in local ids
    (edges point neighbor -> center, khop_sampler convention), plus the
    node-feature matrix for ``sample_index`` when ``with_features``.
    """
    if any(int(k) <= 0 for k in sample_sizes):
        raise ValueError(
            "store-backed khop needs sample sizes > 0: the padded "
            "static-shape store sampler has no take-all sentinel")

    def fetch(frontier, hop, k):
        nb, cnt = store.sample_neighbors(frontier, k, seed=seed + hop)
        return [[v for v in nb[i][:int(cnt[i])] if v >= 0]
                for i in range(len(frontier))]

    out = _khop_core(fetch, input_nodes, sample_sizes)
    if with_features:
        feats = store.get_features(out[2])
        return out + (feats,)
    return out


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Multi-edge-type reindex (reference ``geometric/reindex.py:138``):
    one shared local-id table over all graphs — ``x`` first, then new ids
    in first-seen order across the graphs' neighbor lists — returning the
    concatenated ``(reindex_src, reindex_dst, out_nodes)``. The optional
    hash buffers of the reference's GPU kernel have no host-side meaning
    and are accepted for signature parity."""
    del value_buffer, index_buffer
    x = np.asarray(x, np.int64).reshape(-1)
    _, out_nodes, map_ids = _intern_ids(x)
    srcs, dsts = [], []
    for nb, ct in zip(neighbors, count):
        nb = np.asarray(nb, np.int64).reshape(-1)
        ct = np.asarray(ct, np.int64).reshape(-1)
        srcs.append(map_ids(nb))
        dsts.append(np.repeat(np.arange(x.size, dtype=np.int64), ct))
    return (np.concatenate(srcs) if srcs else np.empty(0, np.int64),
            np.concatenate(dsts) if dsts else np.empty(0, np.int64),
            np.asarray(out_nodes, np.int64))
