"""Message-passing aggregation primitives.

Reference parity: ``python/paddle/geometric/message_passing/send_recv.py``
(``send_u_recv``/``send_ue_recv``/``send_uv``) whose CUDA kernels are
``paddle/phi/kernels/gpu/graph_send_recv_kernel.cu`` (atomic scatter-reduce).
TPU-native: XLA ``segment_*`` reductions — sorted-or-not scatter lowers to
efficient one-pass reduction on TPU and is differentiable for free, so the
hand-written backward kernels (`graph_send_recv_grad_kernel.cu`) vanish.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_COMBINE = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
}


def _segment_reduce(msg, dst, num_segments, pool_type):
    if pool_type in ("sum", "add"):
        return jax.ops.segment_sum(msg, dst, num_segments)
    if pool_type not in ("mean", "max", "min"):
        raise ValueError(f"unknown pool_type {pool_type!r}")
    # count per segment to mask empties (dtype-agnostic: segment_max fills
    # empty int segments with INT_MIN, float with -inf — both masked here).
    cnt = jax.ops.segment_sum(jnp.ones((msg.shape[0],), jnp.float32), dst,
                              num_segments)
    nonempty = (cnt > 0).reshape((-1,) + (1,) * (msg.ndim - 1))
    if pool_type == "mean":
        tot = jax.ops.segment_sum(msg, dst, num_segments)
        denom = jnp.maximum(cnt, 1.0).reshape(nonempty.shape).astype(tot.dtype)
        return tot / denom
    red = jax.ops.segment_max if pool_type == "max" else jax.ops.segment_min
    out = red(msg, dst, num_segments)
    return jnp.where(nonempty, out, jnp.zeros((), out.dtype))


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size=None):
    """Gather ``x[src]``, scatter-reduce onto ``dst`` — one GNN hop.

    Empty destination segments yield 0 (matching the reference's
    ``graph_send_recv`` semantics for max/min too).
    """
    x = jnp.asarray(x)
    src_index = jnp.asarray(src_index)
    dst_index = jnp.asarray(dst_index)
    n = int(out_size) if out_size is not None else x.shape[0]
    return _segment_reduce(x[src_index], dst_index, n, reduce_op)


def send_ue_recv(x, e, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size=None):
    """Like :func:`send_u_recv` but combines node features with edge
    features first: ``combine(x[src], e)`` then reduce onto dst."""
    x = jnp.asarray(x)
    e = jnp.asarray(e)
    src_index = jnp.asarray(src_index)
    dst_index = jnp.asarray(dst_index)
    if message_op not in _COMBINE:
        raise ValueError(f"unknown message_op {message_op!r}")
    msg = _COMBINE[message_op](x[src_index], e)
    n = int(out_size) if out_size is not None else x.shape[0]
    return _segment_reduce(msg, dst_index, n, reduce_op)


def send_uv(x, y, src_index, dst_index, message_op: str = "add"):
    """Edge-wise message ``combine(x[src], y[dst])`` (no reduction) —
    reference ``paddle.geometric.send_uv``."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if message_op not in _COMBINE:
        raise ValueError(f"unknown message_op {message_op!r}")
    return _COMBINE[message_op](x[jnp.asarray(src_index)],
                                y[jnp.asarray(dst_index)])


def segment_pool(x, segment_ids, pool_type: str = "sum", num_segments=None):
    """Segment reduction over already-grouped rows (reference
    ``paddle.incubate.segment_sum``/``segment_mean``/...)."""
    x = jnp.asarray(x)
    segment_ids = jnp.asarray(segment_ids)
    if num_segments is not None:
        n = int(num_segments)
    else:
        try:
            n = int(segment_ids.max()) + 1
        except jax.errors.ConcretizationTypeError as e:
            raise ValueError(
                "segment_pool: num_segments must be passed explicitly "
                "inside jit (segment_ids is traced, so its max is not "
                "static)") from e
    return _segment_reduce(x, segment_ids, n, pool_type)


def segment_sum(data, segment_ids, name=None):
    """``paddle.geometric.segment_sum`` (reference ``geometric/math.py:23``):
    out[i] = sum of rows whose segment id == i; result length is
    ``max(segment_ids) + 1`` (pass through :func:`segment_pool` with an
    explicit ``num_segments`` under jit)."""
    return segment_pool(data, segment_ids, "sum")


def segment_mean(data, segment_ids, name=None):
    """``paddle.geometric.segment_mean``; empty segments yield 0."""
    return segment_pool(data, segment_ids, "mean")


def segment_min(data, segment_ids, name=None):
    """``paddle.geometric.segment_min``; empty segments yield 0."""
    return segment_pool(data, segment_ids, "min")


def segment_max(data, segment_ids, name=None):
    """``paddle.geometric.segment_max``; empty segments yield 0."""
    return segment_pool(data, segment_ids, "max")
