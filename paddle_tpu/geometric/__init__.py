"""paddle_tpu.geometric — GNN message passing + graph sampling.

Reference parity: ``python/paddle/geometric/`` (``message_passing/send_recv.py``,
``sampling/neighbors.py``) and the incubate wrappers
(``python/paddle/incubate/operators/graph_send_recv.py``,
``graph_sample_neighbors.py:28``, ``graph_reindex.py:28``,
``graph_khop_sampler.py:21``). TPU-native: aggregation lowers to XLA
``segment_sum``-family ops (device-side, differentiable); samplers run in
the native C++ CSR store or over in-memory CSC arrays, returning padded
static shapes.
"""
from .message_passing import (segment_max, segment_mean, segment_min,
                              segment_pool, segment_sum, send_u_recv,
                              send_ue_recv, send_uv)
from .sampling import (reindex_heter_graph,  # noqa: F401
                       khop_sampler, khop_sampler_from_store,
                       reindex_graph, sample_neighbors)

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv", "segment_pool",
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "sample_neighbors", "reindex_graph", "reindex_heter_graph",
    "khop_sampler", "khop_sampler_from_store",
]
