from . import compile_cache, dtype, io, jit, random  # noqa: F401
