from . import dtype, io, jit, random  # noqa: F401
