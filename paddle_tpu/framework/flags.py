"""Global flag registry — ``paddle.set_flags``/``get_flags`` analogue.

Reference parity: the 72 exported gflags in
``paddle/fluid/platform/flags.cc`` surfaced to Python through
``global_value_getter_setter.cc``. TPU-native: flags that exist to steer
hand-managed CUDA memory/streams are accepted but inert (XLA owns those
decisions); the live ones gate framework behavior (nan/inf checking, log
verbosity, deterministic ops). Flags initialize from ``FLAGS_*`` env vars,
same as the reference.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable, Union

_DEFAULTS: Dict[str, Any] = {
    # live flags (consumed by the framework)
    "FLAGS_check_nan_inf": False,          # per-step numeric checks (TrainStep)
    "FLAGS_profile_host_events": True,     # host RecordEvent capture (profiler)
    # persistent XLA compile cache (framework/compile_cache.py): warm
    # processes skip backend compilation for programs already on disk
    "FLAGS_persistent_compile_cache": False,
    "FLAGS_compile_cache_dir": "",         # "" -> ~/.cache/paddle_tpu/xla
    "FLAGS_persistent_cache_min_compile_secs": 0.0,
    # accepted-but-inert (XLA/jax own these concerns on TPU; XLA:TPU is
    # deterministic by default, verbosity goes through absl/glog env)
    "FLAGS_v": 0,
    "FLAGS_deterministic": False,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_use_autotune": True,
    "FLAGS_sync_nccl_allreduce": False,
    "FLAGS_cudnn_deterministic": False,
}

_flags: Dict[str, Any] = {}


def _coerce(default: Any, raw: str) -> Any:
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def _init() -> None:
    for name, default in _DEFAULTS.items():
        raw = os.environ.get(name)
        _flags[name] = _coerce(default, raw) if raw is not None else default


_init()


def set_flags(flags: Dict[str, Any]) -> None:
    """``paddle.set_flags({'FLAGS_check_nan_inf': 1})``."""
    for name, value in flags.items():
        if name not in _flags:
            raise ValueError(f"unknown flag {name!r}; known: {sorted(_flags)}")
        default = _DEFAULTS[name]
        if isinstance(default, bool) and not isinstance(value, bool):
            value = bool(value)
        _flags[name] = value


def get_flags(flags: Union[str, Iterable[str], None] = None) -> Dict[str, Any]:
    if flags is None:
        return dict(_flags)
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        if name not in _flags:
            raise ValueError(f"unknown flag {name!r}")
        out[name] = _flags[name]
    return out


def flag(name: str) -> Any:
    """Fast internal accessor."""
    return _flags[name]
