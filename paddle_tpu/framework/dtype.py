"""Dtype registry and helpers.

Reference parity: paddle exposes string/VarType dtypes
(``paddle/phi/common/data_type.h``); here dtypes are plain
``jnp.dtype`` objects with paddle-style string aliases. TPU-first choices:
bfloat16 is the preferred half precision (MXU native), float64 is discouraged
(TPU emulates it) but supported for CPU testing.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# canonical dtype table: paddle name -> jnp dtype
_DTYPE_ALIASES = {
    "float32": jnp.float32,
    "float64": jnp.float64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint8": jnp.uint8,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "uint64": jnp.uint64,
    "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
    # fp8 for quantized serving paths
    "float8_e4m3fn": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
}

float32 = jnp.float32
float64 = jnp.float64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_DEFAULT_DTYPE = [jnp.float32]


def convert_dtype(dtype):
    """Normalize a user-provided dtype (string / np / jnp) to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _DTYPE_ALIASES:
            raise ValueError(f"unknown dtype string: {dtype!r}")
        return _DTYPE_ALIASES[dtype]
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    """Inverse of convert_dtype: jnp dtype -> paddle-style name."""
    d = jnp.dtype(dtype)
    for name, alias in _DTYPE_ALIASES.items():
        if jnp.dtype(alias) == d:
            return name
    return d.name


def set_default_dtype(dtype):
    d = convert_dtype(dtype)
    if not jnp.issubdtype(d, np.floating):
        raise ValueError("default dtype must be floating point")
    _DEFAULT_DTYPE[0] = d


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def is_floating_point(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, np.floating)


def is_integer(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, np.integer)


def is_complex(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, np.complexfloating)


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return jnp.iinfo(convert_dtype(dtype))
