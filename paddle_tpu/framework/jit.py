"""Compiled execution.

The reference runs programs through ``InterpreterCore`` (instruction list +
threadpool, ``paddle/fluid/framework/new_executor/interpretercore.cc``). On
TPU the executor *is* XLA: a train/eval step is traced once, compiled, and
cached keyed on shapes/shardings. This module packages that as:

- :func:`jit` — paddle.jit.to_static analogue for plain functions/Layers.
- :class:`TrainStep` — whole-step compilation: forward + loss + backward +
  optimizer update in ONE XLA program with donated buffers (the analogue of
  the reference's fused optimizer pass + executor pipeline).
- :class:`EvalStep` — inference-only compiled step.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import compile_cache
from . import random as framework_random
from ..nn.layer import Layer, buffer_state, functional_call, param_state


DEFAULT_RNG_STREAMS = ("dropout", "rrelu", "gumbel", "default")


def _grad_dtype(dtype):
    """Accumulate low-precision grads in f32 (gradient-merge accumulators)."""
    return jnp.float32 if dtype in (jnp.bfloat16, jnp.float16) else dtype


def accumulate_grads(accum, grads):
    """Gradient-merge accumulate (no-op when accumulation is off)."""
    if accum is None:
        return None
    return jax.tree.map(lambda a, g: a + g.astype(a.dtype), accum, grads)


def merge_accumulated(accum, grads, k_steps, avg):
    """Finish a gradient-merge window: returns (grads_for_update,
    reset_accum). ``grads`` supplies the target dtypes."""
    if accum is None:
        return grads, None
    k = float(k_steps)
    merged = jax.tree.map(
        lambda a, g: (a / k if avg else a).astype(g.dtype), accum, grads)
    return merged, jax.tree.map(jnp.zeros_like, accum)


def resolve_inputs_fn(inputs_fn, loss_fn):
    """Default batch->model-inputs mapping shared by TrainStep and
    DistributedTrainStep: with a loss_fn, (inputs, labels) tuples feed the
    model their first element; otherwise the whole batch is the input."""
    if inputs_fn is not None:
        return inputs_fn
    if loss_fn is not None:
        return lambda b: b[0] if isinstance(b, (tuple, list)) else b
    return lambda b: b


def split_rng_streams(key, streams=DEFAULT_RNG_STREAMS):
    return dict(zip(streams, jax.random.split(key, len(streams))))


def jit(fn=None, *, static_argnums=(), static_argnames=(), donate_argnums=()):
    """``paddle.jit.to_static`` analogue. Accepts a function or a Layer.

    For a Layer, returns a compiled callable closed over the layer's current
    state (params become compile-time constants refreshed per call via
    functional_call — use TrainStep for training).
    """
    if fn is None:
        return functools.partial(jit, static_argnums=static_argnums,
                                 static_argnames=static_argnames,
                                 donate_argnums=donate_argnums)
    if isinstance(fn, Layer):
        layer = fn
        cc_name = compile_cache.register_name(
            f"jit:{type(layer).__name__}")

        def _run(p, b, *args, **kwargs):
            out, _ = functional_call(layer, p, b, *args, **kwargs)
            return out

        _compiled = jax.jit(compile_cache.instrument(_run, cc_name))

        def wrapped(*args, **kwargs):
            compile_cache.record_call(cc_name)
            return _compiled(param_state(layer), buffer_state(layer),
                             *args, **kwargs)

        wrapped.__wrapped_layer__ = layer
        wrapped.__cc_name__ = cc_name
        wrapped.cache_stats = lambda: compile_cache.cache_stats(cc_name)
        return wrapped
    cc_name = compile_cache.register_name(
        f"jit:{getattr(fn, '__name__', 'fn')}")
    compiled = jax.jit(compile_cache.instrument(fn, cc_name),
                       static_argnums=static_argnums,
                       static_argnames=static_argnames,
                       donate_argnums=donate_argnums)

    @functools.wraps(fn)
    def dispatch(*args, **kwargs):
        compile_cache.record_call(cc_name)
        return dispatch.__jit__(*args, **kwargs)

    dispatch.__jit__ = compiled   # escape hatch: .lower()/.eval_shape()
    dispatch.__cc_name__ = cc_name
    dispatch.cache_stats = lambda: compile_cache.cache_stats(cc_name)
    return dispatch


def finite_guard(grads, new_state, old_state, extra_ok=None):
    """In-graph NaN/Inf gate for FLAGS_check_nan_inf: returns
    ``(ok, selected_state)`` where each leaf of ``new_state`` is kept only
    if every grad and every updated param is finite — otherwise the old
    leaf survives. Keeping the selection in-graph means a bad batch can be
    caught *without* corrupting donated buffers (the reference's per-op
    scan aborts before the update; here the update is predicated instead).

    ``new_state``/``old_state`` are matching tuples of pytrees; the first
    tree is the params (checked), the rest (buffers/opt state) are selected
    alongside. ``extra_ok`` folds an additional scalar condition (e.g. a
    finite loss) into the gate.
    """
    from .debugging import tree_all_finite

    ok = tree_all_finite(grads) & tree_all_finite(new_state[0])
    if extra_ok is not None:
        ok = ok & extra_ok

    def sel(n, o):
        return jnp.where(ok, n, o)

    selected = tuple(jax.tree.map(sel, n, o)
                     for n, o in zip(new_state, old_state))
    return ok, selected


def raise_if_bad_step(ok, loss) -> None:
    """Host-side companion to :func:`finite_guard`."""
    if not bool(ok):
        raise FloatingPointError(
            f"NaN/Inf detected in gradients or updated parameters "
            f"(FLAGS_check_nan_inf); update skipped, state preserved. "
            f"loss={float(loss)}")


def scaler_guard(loss, found, scaler_state, new_state, old_state):
    """In-graph GradScaler epilogue shared by TrainStep and
    DistributedTrainStep (ONE implementation, so the sharded and
    single-device skip/grow semantics cannot drift). ``found`` is
    ``unscale_and_check``'s nonfinite-grads flag; this classifies the
    step, predicates the update, and advances the scale.

    Classification: a nonfinite *loss* — or nonfinite UPDATED params under
    finite grads (optimizer-side blowup) — is a data/numerics **anomaly**;
    nonfinite grads under a finite loss are ordinary scale-overflow, but
    only while ``scale > 1``: at scale 1 there is no scaling left to blame,
    so persistent NaN grads escalate to the watchdog instead of silently
    skipping updates forever. Both cases keep the old state, and ONLY the
    benign overflow drives the backoff schedule — a poisoned batch must
    not walk the scale down.

    Returns ``(selected_state, new_scaler_state, ok, found_inf)`` where
    ``ok = ~anomaly`` and ``found_inf`` flags benign scaler skips only.
    """
    from ..amp.grad_scaler import update_scale
    from .debugging import tree_all_finite

    # the params term applies only under FINITE grads: overflowed grads
    # trivially produce nonfinite candidate params, and that case is the
    # ordinary overflow being classified right above it
    anomaly = (~jnp.isfinite(loss)
               | (found & (scaler_state["scale"] <= 1.0))
               | (~found & ~tree_all_finite(new_state[0])))
    bad = found | anomaly
    found_inf = found & ~anomaly

    def keep_old(n, o):
        return jax.tree.map(lambda a, b: jnp.where(bad, b, a), n, o)

    selected = tuple(keep_old(n, o) for n, o in zip(new_state, old_state))
    return selected, update_scale(scaler_state, found_inf), ~anomaly, \
        found_inf


class StepSeams:
    """Host-side seams shared by TrainStep and DistributedTrainStep: the
    step counter / gradient-accumulation window, the traced NaN-poison
    input, and GradScaler resolution — one implementation so the sharded
    and single-device paths cannot drift."""

    def _init_seams(self, scaler, grad_accum_steps: int) -> None:
        self.scaler = scaler if (scaler is not None
                                 and getattr(scaler, "enable", True)) else None
        if self.scaler is not None and grad_accum_steps > 1:
            raise ValueError(
                "GradScaler with grad_accum_steps > 1 is not supported: the "
                "scale could change mid-accumulation window")
        # deterministic numerics-fault seam: the NEXT step's loss is
        # multiplied by this traced scalar (1.0 = no-op; NaN = poisoned
        # batch). Being a regular input, flipping it never retraces — the
        # chaos harness drives it through fault_point("train.data").
        self._pending_poison = np.float32(1.0)

    def inject_anomaly(self):
        """Poison the NEXT step's loss (and hence grads) with NaN — the
        deterministic fault-injection seam the chaos harness drives through
        ``fault_point("train.data")``. The in-graph guard still protects
        the state; the watchdog observes the anomaly. (Distributed: the
        poison scalar is replicated, so every host sees the same anomaly
        at the same step.)"""
        self._pending_poison = np.float32("nan")

    def _take_poison(self):
        p, self._pending_poison = self._pending_poison, np.float32(1.0)
        return p

    def _next_count(self):
        count = np.uint32(self._count)
        self._count += 1
        do_update = (self.grad_accum_steps <= 1
                     or self._count % self.grad_accum_steps == 0)
        return count, do_update

    def _step_span(self):
        """The per-step host span both step classes dispatch under — ONE
        name ("step"), because ``tools/bench_profile.py``'s overlap
        breakdown classifies recorder spans by it; a drifted name would
        silently empty the breakdown."""
        from ..profiler import RecordEvent

        return RecordEvent("step")


class TrainStep(StepSeams):
    """One-call training: ``loss = step(batch)``.

    ``loss_fn(outputs, batch) -> scalar`` or pass ``model_loss=True`` when the
    model's forward already returns the loss. The compiled program:
    forward -> grad -> (optional grad transforms) -> optimizer update,
    with params/buffers/opt_state donated (in-place buffer reuse in HBM).

    With ``scaler`` (an :class:`paddle_tpu.amp.GradScaler`), dynamic loss
    scaling is fused into the program: the loss is scaled before the
    backward pass, grads unscaled, the update skipped in-graph on overflow
    and the scale grown/backed off — no per-step host sync. Overflow flags
    surface lazily and are pulled into the scaler's host counters
    (``skipped_step_count``/``last_overflow_step``) on read.
    """

    # hapi's step also returns the model outputs for train-time metrics;
    # the flag keeps one _step body for both (the extra output would pin an
    # extra HBM buffer for callers that never read it)
    _return_out = False

    def __init__(self, model: Layer, optimizer, loss_fn: Optional[Callable] = None,
                 inputs_fn: Optional[Callable] = None,
                 grad_transform: Optional[Callable] = None, donate: bool = True,
                 rng_streams=DEFAULT_RNG_STREAMS, grad_accum_steps: int = 1,
                 grad_accum_avg: bool = True, scaler=None,
                 trainable: Optional[Callable[[str], bool]] = None):
        """``grad_accum_steps`` (k>1) enables gradient merge (reference
        ``fleet/meta_optimizers/gradient_merge_optimizer.py``): each call
        accumulates grads; every k-th call applies one optimizer update with
        the sum (mean when ``grad_accum_avg``). k calls on batch B equal one
        k=1 call on batch k*B.

        ``trainable`` (a predicate on parameter paths) freezes everything
        it rejects: frozen params ride the BUFFERS pytree — still explicit
        jit inputs (a base-weight reload never serves stale compile-time
        constants), still donated, still in ``state_dict()`` for
        crash-resume — but excluded from grad and from ``optimizer.init``,
        so optimizer state scales with the trainable subset (the
        ``Model.fit(lora=...)`` adapter path: rank-sized, not
        model-sized)."""
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.inputs_fn = resolve_inputs_fn(inputs_fn, loss_fn)
        self.grad_transform = grad_transform
        self._trainable = trainable
        # copy: the step donates its buffers; the Layer must keep valid arrays
        all_params = jax.tree.map(lambda x: jnp.array(x, copy=True), param_state(model))
        self.params, frozen = self._split_trainable(all_params)
        self.buffers = jax.tree.map(lambda x: jnp.array(x, copy=True), buffer_state(model))
        self.buffers.update(frozen)
        self.opt_state = optimizer.init(self.params)
        self._rng_streams = tuple(rng_streams)
        # materialized once: a lazy key input would trip the tunnel
        # slow path documented in _step
        # tpu-lint: disable=R1(one-time construction readback; keeps every later step dispatch on the tunnel fast path)
        self._base_key = jax.block_until_ready(framework_random.next_key())
        self._count = 0
        self.grad_accum_steps = int(grad_accum_steps)
        self.grad_accum_avg = grad_accum_avg
        self._grad_accum = None
        if self.grad_accum_steps > 1:
            self._grad_accum = jax.tree.map(
                lambda x: jnp.zeros(x.shape, _grad_dtype(x.dtype)), self.params)
        self._init_seams(scaler, self.grad_accum_steps)
        self.scaler_state = (jax.tree.map(jnp.asarray, dict(self.scaler.state))
                             if self.scaler is not None else None)
        donate_argnums = (0, 1, 2, 3) if donate else ()
        # retrace accounting: every new shape specialization of the step is
        # recorded under this key (see framework/compile_cache.py)
        self._cc_name = compile_cache.register_name(
            f"{type(self).__name__}:{type(model).__name__}")
        self._traced = compile_cache.instrument(self._step, self._cc_name)
        # two specializations when accumulating: accumulate-only / apply
        self._compiled = jax.jit(self._traced, donate_argnums=donate_argnums,
                                 static_argnames=("do_update",))
        # FLAGS_check_nan_inf / watchdog variant: also reduces grads/params
        # finiteness in-graph (framework/debugging.py) — compiled on first use
        self._compiled_checked = None
        self._donate_argnums = donate_argnums

    def _split_trainable(self, all_params):
        """``(trainable, frozen)`` split of a flat param dict per the
        ``trainable`` predicate (everything/nothing when None)."""
        if self._trainable is None:
            return all_params, {}
        params = {k: v for k, v in all_params.items() if self._trainable(k)}
        frozen = {k: v for k, v in all_params.items()
                  if not self._trainable(k)}
        if not params:
            raise ValueError(
                "the trainable= predicate selected no parameters — "
                "nothing to optimize (for LoRA: apply_lora(model, config) "
                "before building the step)")
        return params, frozen

    def _step(self, params, buffers, opt_state, accum, scaler_state, batch,
              key, count, poison, with_check=False, do_update=True):
        # fold_in runs INSIDE the compiled step: computing the per-step key
        # as a separate tiny dispatch and feeding its (lazy) result into
        # this call knocks the TPU-tunnel runtime off its fast path —
        # measured 1.68s vs 0.12s per ResNet-50 step. `count` arrives as a
        # host numpy scalar, so every input is already materialized.
        rngs = split_rng_streams(jax.random.fold_in(key, count),
                                 self._rng_streams)
        use_scaler = scaler_state is not None

        def compute_loss(p):
            inputs = self.inputs_fn(batch)
            if not isinstance(inputs, (tuple, list)):
                inputs = (inputs,)
            out, new_buf = functional_call(self.model, p, buffers, *inputs, rngs=rngs)
            raw = out if self.loss_fn is None else self.loss_fn(out, batch)
            loss = jnp.asarray(raw, jnp.float32) * poison
            scaled = loss * scaler_state["scale"] if use_scaler else loss
            return scaled, (new_buf, out, loss)

        (_, (new_buffers, out, loss)), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        extras = (out,) if self._return_out else ()
        accum = accumulate_grads(accum, grads)
        if not do_update:
            return (loss, *extras, params, new_buffers, opt_state, accum,
                    scaler_state)
        grads, accum = merge_accumulated(accum, grads, self.grad_accum_steps,
                                         self.grad_accum_avg)
        if self.grad_transform is not None:
            grads = self.grad_transform(grads)
        if use_scaler:
            from ..amp.grad_scaler import unscale_and_check

            grads, found = unscale_and_check(grads, scaler_state)
            new_params, new_opt_state = self.optimizer.update(
                grads, opt_state, params)
            (new_params, new_buffers, new_opt_state), new_scaler_state, \
                ok, found_inf = scaler_guard(
                    loss, found, scaler_state,
                    (new_params, new_buffers, new_opt_state),
                    (params, buffers, opt_state))
            return (loss, *extras, new_params, new_buffers, new_opt_state,
                    accum, new_scaler_state, ok, found_inf)
        new_params, new_opt_state = self.optimizer.update(grads, opt_state, params)
        if with_check:
            ok, (new_params, new_buffers, new_opt_state) = finite_guard(
                grads, (new_params, new_buffers, new_opt_state),
                (params, buffers, opt_state), extra_ok=jnp.isfinite(loss))
            return (loss, *extras, new_params, new_buffers, new_opt_state,
                    accum, scaler_state, ok, jnp.zeros((), jnp.bool_))
        return (loss, *extras, new_params, new_buffers, new_opt_state, accum,
                scaler_state)

    def _checked_compiled(self):
        if self._compiled_checked is None:
            self._compiled_checked = jax.jit(
                functools.partial(self._traced, with_check=True),
                donate_argnums=self._donate_argnums)
        return self._compiled_checked

    def cache_stats(self) -> dict:
        """Compile/call counters for this step's program: ``{"compiles",
        "calls", "cache_hits", "signatures", "last_trace_signature"}``."""
        return compile_cache.cache_stats(self._cc_name)

    def _checked_call(self, batch, count, poison):
        """Dispatch one update step through the flag-returning program.
        Returns ``(loss, *extras, ok, found_inf)`` with flags LAZY (device
        scalars, no host sync) and state stored back on self."""
        n = 1 + len(("out",) if self._return_out else ())
        if self.scaler_state is not None:
            outs = self._compiled(self.params, self.buffers, self.opt_state,
                                  self._grad_accum, self.scaler_state, batch,
                                  self._base_key, count, poison)
            (self.params, self.buffers, self.opt_state, self._grad_accum,
             self.scaler_state) = outs[n:n + 5]
            ok, found = outs[n + 5], outs[n + 6]
            if self.scaler is not None:
                self.scaler._note_step(found)
                # mirror the (lazy) updated scale so get_loss_scaling() and
                # state_dict() on the scaler object stay truthful
                self.scaler.state = dict(self.scaler_state)
        else:
            outs = self._checked_compiled()(
                self.params, self.buffers, self.opt_state, self._grad_accum,
                None, batch, self._base_key, count, poison)
            (self.params, self.buffers, self.opt_state,
             self._grad_accum) = outs[n:n + 4]
            ok, found = outs[n + 5], outs[n + 6]
        return (*outs[:n], ok, found)

    def _plain_call(self, batch, count, poison, do_update):
        n = 1 + len(("out",) if self._return_out else ())
        outs = self._compiled(self.params, self.buffers, self.opt_state,
                              self._grad_accum, None, batch, self._base_key,
                              count, poison, do_update=do_update)
        (self.params, self.buffers, self.opt_state,
         self._grad_accum) = outs[n:n + 4]
        return outs[:n]

    def watchdog_call(self, batch):
        """One step through the checked program: ``(loss, ok, found_inf)``
        with all three LAZY (the numerics watchdog batches the host sync
        every ``check_interval`` steps). ``ok``/``found_inf`` are ``None``
        on accumulate-only calls (no update happened to check)."""
        count, do_update = self._next_count()
        compile_cache.record_call(self._cc_name)
        poison = self._take_poison()
        with self._step_span():
            if not do_update:
                (loss,) = self._plain_call(batch, count, poison, False)
                return loss, None, None
            loss, ok, found = self._checked_call(batch, count, poison)
            return loss, ok, found

    def __call__(self, batch):
        from . import flags

        count, do_update = self._next_count()
        compile_cache.record_call(self._cc_name)
        poison = self._take_poison()
        with self._step_span():
            if do_update and (self.scaler_state is not None
                              or flags.flag("FLAGS_check_nan_inf")):
                loss, ok, found = self._checked_call(batch, count, poison)
                if flags.flag("FLAGS_check_nan_inf"):
                    raise_if_bad_step(ok, loss)
                return loss
            (loss,) = self._plain_call(batch, count, poison, do_update)
            return loss

    # ----------------------------------------------------------- state sync
    def sync_to_model(self):
        """Write the step's current params/buffers back into the Layer
        (for checkpointing / eval through the eager path)."""
        for name, v in self.params.items():
            self.model._set_by_path(name, v)
        for name, v in self.buffers.items():
            self.model._set_by_path(name, v)
        return self.model

    def load_from_model(self):
        self.params, frozen = self._split_trainable(param_state(self.model))
        self.buffers = buffer_state(self.model)
        self.buffers.update(frozen)
        return self

    def state_dict(self):
        sd = {"params": self.params, "buffers": self.buffers,
              "opt_state": self.opt_state, "count": self._count,
              # the per-step RNG is fold_in(base_key, count): restoring BOTH
              # makes a resumed run's dropout streams bit-identical
              "base_key": np.asarray(jax.random.key_data(self._base_key))}
        if self._grad_accum is not None:
            sd["grad_accum"] = self._grad_accum
        if self.scaler_state is not None:
            sd["scaler_state"] = self.scaler_state
        return sd

    def set_state_dict(self, sd):
        # restored leaves are often host numpy (framework_io / checkpoint
        # load): move them to device arrays so the donated dispatch path
        # sees the same avals as a live run (no donation warnings/copies)
        def dev(tree):
            return jax.tree.map(jnp.asarray, tree)

        self.params = dev(sd["params"])
        self.buffers = dev(sd["buffers"])
        self.opt_state = dev(sd["opt_state"])
        self._count = int(sd.get("count", 0))
        if sd.get("base_key") is not None:
            self._base_key = jax.random.wrap_key_data(
                jnp.asarray(np.asarray(sd["base_key"]), jnp.uint32))
        if "grad_accum" in sd:
            self._grad_accum = dev(sd["grad_accum"])
        if "scaler_state" in sd and self.scaler_state is not None:
            self.scaler_state = dev(sd["scaler_state"])


class EvalStep:
    def __init__(self, model: Layer):
        self.model = model
        self._cc_name = compile_cache.register_name(
            f"EvalStep:{type(model).__name__}")

        def _run(params, buffers, *args):
            out, _ = functional_call(model, params, buffers, *args)
            return out

        self._compiled = jax.jit(
            compile_cache.instrument(_run, self._cc_name))

    def cache_stats(self) -> dict:
        return compile_cache.cache_stats(self._cc_name)

    def __call__(self, *args):
        compile_cache.record_call(self._cc_name)
        return self._compiled(param_state(self.model), buffer_state(self.model), *args)
