"""Compiled execution.

The reference runs programs through ``InterpreterCore`` (instruction list +
threadpool, ``paddle/fluid/framework/new_executor/interpretercore.cc``). On
TPU the executor *is* XLA: a train/eval step is traced once, compiled, and
cached keyed on shapes/shardings. This module packages that as:

- :func:`jit` — paddle.jit.to_static analogue for plain functions/Layers.
- :class:`TrainStep` — whole-step compilation: forward + loss + backward +
  optimizer update in ONE XLA program with donated buffers (the analogue of
  the reference's fused optimizer pass + executor pipeline).
- :class:`EvalStep` — inference-only compiled step.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from . import compile_cache
from . import random as framework_random
from ..nn.layer import Layer, buffer_state, functional_call, param_state


DEFAULT_RNG_STREAMS = ("dropout", "rrelu", "gumbel", "default")


def _grad_dtype(dtype):
    """Accumulate low-precision grads in f32 (gradient-merge accumulators)."""
    return jnp.float32 if dtype in (jnp.bfloat16, jnp.float16) else dtype


def accumulate_grads(accum, grads):
    """Gradient-merge accumulate (no-op when accumulation is off)."""
    if accum is None:
        return None
    return jax.tree.map(lambda a, g: a + g.astype(a.dtype), accum, grads)


def merge_accumulated(accum, grads, k_steps, avg):
    """Finish a gradient-merge window: returns (grads_for_update,
    reset_accum). ``grads`` supplies the target dtypes."""
    if accum is None:
        return grads, None
    k = float(k_steps)
    merged = jax.tree.map(
        lambda a, g: (a / k if avg else a).astype(g.dtype), accum, grads)
    return merged, jax.tree.map(jnp.zeros_like, accum)


def resolve_inputs_fn(inputs_fn, loss_fn):
    """Default batch->model-inputs mapping shared by TrainStep and
    DistributedTrainStep: with a loss_fn, (inputs, labels) tuples feed the
    model their first element; otherwise the whole batch is the input."""
    if inputs_fn is not None:
        return inputs_fn
    if loss_fn is not None:
        return lambda b: b[0] if isinstance(b, (tuple, list)) else b
    return lambda b: b


def split_rng_streams(key, streams=DEFAULT_RNG_STREAMS):
    return dict(zip(streams, jax.random.split(key, len(streams))))


def jit(fn=None, *, static_argnums=(), static_argnames=(), donate_argnums=()):
    """``paddle.jit.to_static`` analogue. Accepts a function or a Layer.

    For a Layer, returns a compiled callable closed over the layer's current
    state (params become compile-time constants refreshed per call via
    functional_call — use TrainStep for training).
    """
    if fn is None:
        return functools.partial(jit, static_argnums=static_argnums,
                                 static_argnames=static_argnames,
                                 donate_argnums=donate_argnums)
    if isinstance(fn, Layer):
        layer = fn
        cc_name = compile_cache.register_name(
            f"jit:{type(layer).__name__}")

        def _run(p, b, *args, **kwargs):
            out, _ = functional_call(layer, p, b, *args, **kwargs)
            return out

        _compiled = jax.jit(compile_cache.instrument(_run, cc_name))

        def wrapped(*args, **kwargs):
            compile_cache.record_call(cc_name)
            return _compiled(param_state(layer), buffer_state(layer),
                             *args, **kwargs)

        wrapped.__wrapped_layer__ = layer
        wrapped.__cc_name__ = cc_name
        wrapped.cache_stats = lambda: compile_cache.cache_stats(cc_name)
        return wrapped
    cc_name = compile_cache.register_name(
        f"jit:{getattr(fn, '__name__', 'fn')}")
    compiled = jax.jit(compile_cache.instrument(fn, cc_name),
                       static_argnums=static_argnums,
                       static_argnames=static_argnames,
                       donate_argnums=donate_argnums)

    @functools.wraps(fn)
    def dispatch(*args, **kwargs):
        compile_cache.record_call(cc_name)
        return dispatch.__jit__(*args, **kwargs)

    dispatch.__jit__ = compiled   # escape hatch: .lower()/.eval_shape()
    dispatch.__cc_name__ = cc_name
    dispatch.cache_stats = lambda: compile_cache.cache_stats(cc_name)
    return dispatch


def finite_guard(grads, new_state, old_state):
    """In-graph NaN/Inf gate for FLAGS_check_nan_inf: returns
    ``(ok, selected_state)`` where each leaf of ``new_state`` is kept only
    if every grad and every updated param is finite — otherwise the old
    leaf survives. Keeping the selection in-graph means a bad batch can be
    caught *without* corrupting donated buffers (the reference's per-op
    scan aborts before the update; here the update is predicated instead).

    ``new_state``/``old_state`` are matching tuples of pytrees; the first
    tree is the params (checked), the rest (buffers/opt state) are selected
    alongside.
    """
    from .debugging import tree_all_finite

    ok = tree_all_finite(grads) & tree_all_finite(new_state[0])

    def sel(n, o):
        return jnp.where(ok, n, o)

    selected = tuple(jax.tree.map(sel, n, o)
                     for n, o in zip(new_state, old_state))
    return ok, selected


def raise_if_bad_step(ok, loss) -> None:
    """Host-side companion to :func:`finite_guard`."""
    if not bool(ok):
        raise FloatingPointError(
            f"NaN/Inf detected in gradients or updated parameters "
            f"(FLAGS_check_nan_inf); update skipped, state preserved. "
            f"loss={float(loss)}")


class TrainStep:
    """One-call training: ``loss = step(batch)``.

    ``loss_fn(outputs, batch) -> scalar`` or pass ``model_loss=True`` when the
    model's forward already returns the loss. The compiled program:
    forward -> grad -> (optional grad transforms) -> optimizer update,
    with params/buffers/opt_state donated (in-place buffer reuse in HBM).
    """

    def __init__(self, model: Layer, optimizer, loss_fn: Optional[Callable] = None,
                 inputs_fn: Optional[Callable] = None,
                 grad_transform: Optional[Callable] = None, donate: bool = True,
                 rng_streams=DEFAULT_RNG_STREAMS, grad_accum_steps: int = 1,
                 grad_accum_avg: bool = True):
        """``grad_accum_steps`` (k>1) enables gradient merge (reference
        ``fleet/meta_optimizers/gradient_merge_optimizer.py``): each call
        accumulates grads; every k-th call applies one optimizer update with
        the sum (mean when ``grad_accum_avg``). k calls on batch B equal one
        k=1 call on batch k*B."""
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.inputs_fn = resolve_inputs_fn(inputs_fn, loss_fn)
        self.grad_transform = grad_transform
        # copy: the step donates its buffers; the Layer must keep valid arrays
        self.params = jax.tree.map(lambda x: jnp.array(x, copy=True), param_state(model))
        self.buffers = jax.tree.map(lambda x: jnp.array(x, copy=True), buffer_state(model))
        self.opt_state = optimizer.init(self.params)
        self._rng_streams = tuple(rng_streams)
        # materialized once: a lazy key input would trip the tunnel
        # slow path documented in _step
        self._base_key = jax.block_until_ready(framework_random.next_key())
        self._count = 0
        self.grad_accum_steps = int(grad_accum_steps)
        self.grad_accum_avg = grad_accum_avg
        self._grad_accum = None
        if self.grad_accum_steps > 1:
            self._grad_accum = jax.tree.map(
                lambda x: jnp.zeros(x.shape, _grad_dtype(x.dtype)), self.params)
        donate_argnums = (0, 1, 2, 3) if donate else ()
        # retrace accounting: every new shape specialization of the step is
        # recorded under this key (see framework/compile_cache.py)
        self._cc_name = compile_cache.register_name(
            f"{type(self).__name__}:{type(model).__name__}")
        self._traced = compile_cache.instrument(self._step, self._cc_name)
        # two specializations when accumulating: accumulate-only / apply
        self._compiled = jax.jit(self._traced, donate_argnums=donate_argnums,
                                 static_argnames=("do_update",))
        # FLAGS_check_nan_inf variant: also reduces grads/params finiteness
        # in-graph (framework/debugging.py) — compiled on first use
        self._compiled_checked = None
        self._donate_argnums = donate_argnums

    def _step(self, params, buffers, opt_state, accum, batch, key, count,
              with_check=False, do_update=True):
        # fold_in runs INSIDE the compiled step: computing the per-step key
        # as a separate tiny dispatch and feeding its (lazy) result into
        # this call knocks the TPU-tunnel runtime off its fast path —
        # measured 1.68s vs 0.12s per ResNet-50 step. `count` arrives as a
        # host numpy scalar, so every input is already materialized.
        rngs = split_rng_streams(jax.random.fold_in(key, count),
                                 self._rng_streams)

        def compute_loss(p):
            inputs = self.inputs_fn(batch)
            if not isinstance(inputs, (tuple, list)):
                inputs = (inputs,)
            out, new_buf = functional_call(self.model, p, buffers, *inputs, rngs=rngs)
            loss = out if self.loss_fn is None else self.loss_fn(out, batch)
            return jnp.asarray(loss, jnp.float32), (new_buf, out)

        (loss, (new_buffers, _)), grads = jax.value_and_grad(compute_loss, has_aux=True)(params)
        accum = accumulate_grads(accum, grads)
        if not do_update:
            return loss, params, new_buffers, opt_state, accum
        grads, accum = merge_accumulated(accum, grads, self.grad_accum_steps,
                                         self.grad_accum_avg)
        if self.grad_transform is not None:
            grads = self.grad_transform(grads)
        new_params, new_opt_state = self.optimizer.update(grads, opt_state, params)
        if with_check:
            ok, (new_params, new_buffers, new_opt_state) = finite_guard(
                grads, (new_params, new_buffers, new_opt_state),
                (params, buffers, opt_state))
            return loss, new_params, new_buffers, new_opt_state, accum, ok
        return loss, new_params, new_buffers, new_opt_state, accum

    def _checked_compiled(self):
        if self._compiled_checked is None:
            self._compiled_checked = jax.jit(
                functools.partial(self._traced, with_check=True),
                donate_argnums=self._donate_argnums)
        return self._compiled_checked

    def cache_stats(self) -> dict:
        """Compile/call counters for this step's program: ``{"compiles",
        "calls", "cache_hits", "signatures", "last_trace_signature"}``."""
        return compile_cache.cache_stats(self._cc_name)

    def __call__(self, batch):
        import numpy as np

        from . import flags
        from ..profiler import RecordEvent

        count = np.uint32(self._count)
        self._count += 1
        do_update = (self.grad_accum_steps <= 1
                     or self._count % self.grad_accum_steps == 0)
        compile_cache.record_call(self._cc_name)
        with RecordEvent("step"):
            if flags.flag("FLAGS_check_nan_inf") and do_update:
                loss, self.params, self.buffers, self.opt_state, \
                    self._grad_accum, ok = \
                    self._checked_compiled()(self.params, self.buffers,
                                             self.opt_state, self._grad_accum,
                                             batch, self._base_key, count)
                raise_if_bad_step(ok, loss)
                return loss
            loss, self.params, self.buffers, self.opt_state, self._grad_accum = \
                self._compiled(self.params, self.buffers, self.opt_state,
                               self._grad_accum, batch, self._base_key, count,
                               do_update=do_update)
            return loss

    # ----------------------------------------------------------- state sync
    def sync_to_model(self):
        """Write the step's current params/buffers back into the Layer
        (for checkpointing / eval through the eager path)."""
        for name, v in self.params.items():
            self.model._set_by_path(name, v)
        for name, v in self.buffers.items():
            self.model._set_by_path(name, v)
        return self.model

    def load_from_model(self):
        self.params = param_state(self.model)
        self.buffers = buffer_state(self.model)
        return self

    def state_dict(self):
        sd = {"params": self.params, "buffers": self.buffers,
              "opt_state": self.opt_state, "count": self._count}
        if self._grad_accum is not None:
            sd["grad_accum"] = self._grad_accum
        return sd

    def set_state_dict(self, sd):
        self.params = sd["params"]
        self.buffers = sd["buffers"]
        self.opt_state = sd["opt_state"]
        self._count = sd.get("count", 0)
        if "grad_accum" in sd:
            self._grad_accum = sd["grad_accum"]


class EvalStep:
    def __init__(self, model: Layer):
        self.model = model
        self._cc_name = compile_cache.register_name(
            f"EvalStep:{type(model).__name__}")

        def _run(params, buffers, *args):
            out, _ = functional_call(model, params, buffers, *args)
            return out

        self._compiled = jax.jit(
            compile_cache.instrument(_run, self._cc_name))

    def cache_stats(self) -> dict:
        return compile_cache.cache_stats(self._cc_name)

    def __call__(self, *args):
        compile_cache.record_call(self._cc_name)
        return self._compiled(param_state(self.model), buffer_state(self.model), *args)
