"""Global RNG state.

The reference keeps per-device cuRAND generators behind ``paddle.seed``
(``paddle/fluid/framework/generator.cc``). JAX randomness is functional, so the
framework keeps one global :class:`Generator` that hands out fresh subkeys by
splitting. Outside ``jit`` this gives paddle-style "stateful" randomness; code
that runs under ``jit`` must thread keys explicitly (see
``paddle_tpu.nn.layer.RNGContext`` which supplies named key streams to layers
during a functional call, the analogue of the reference's
``RNGStatesTracker``, ``python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py:32``).
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp


class Generator:
    """Splittable stateful PRNG wrapper around ``jax.random.key``.

    Key creation is lazy: importing the framework must not initialize a
    backend (set_device("cpu") must still be able to flip platforms)."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = int(seed)
        self._key = None

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.key(self._seed)
        return self

    def _ensure(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)

    def next_key(self):
        """Return a fresh subkey; mutates internal state."""
        with self._lock:
            self._ensure()
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        with self._lock:
            self._ensure()
            return self._key

    def set_state(self, key):
        # only accept real PRNG key data: silently storing junk would
        # poison every later random op with a confusing error far from
        # the cause (typed keys pass; raw arrays must be uint32 key data)
        arr = jnp.asarray(key)
        if not (jnp.issubdtype(arr.dtype, jax.dtypes.prng_key)
                or arr.dtype == jnp.uint32):
            raise TypeError(
                "rng state must be PRNG key data (a key from "
                "get_rng_state()/jax.random.key, or uint32 key data); "
                f"got dtype {arr.dtype}")
        with self._lock:
            self._key = key


_default_generator = Generator(0)


def seed(value: int) -> Generator:
    """Set the global seed (``paddle.seed`` analogue)."""
    return _default_generator.manual_seed(value)


def default_generator() -> Generator:
    return _default_generator


def next_key():
    """Fresh subkey from the global generator (eager-mode randomness)."""
    return _default_generator.next_key()


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)
