"""Compile accounting + persistent XLA compilation cache.

On TPU the executor is XLA, so the silent killer of steady-state
throughput is the *retrace*: a novel input shape/dtype re-runs tracing and
backend compilation (seconds to minutes) in the middle of what should be a
microseconds dispatch. This module makes that cost visible and bounded:

- every ``framework.jit`` / ``TrainStep`` / ``EvalStep`` program is
  *instrumented*: each trace (== each distinct compiled specialization)
  bumps a counter keyed by the function's registered name and records the
  abstract ``(shape, dtype)`` signature that caused it;
- :func:`cache_stats` exposes compiles / calls / cache hits / the last
  trace signature, per function and in aggregate — the number BENCH and
  the tier-1 tests assert on;
- :func:`retrace_guard` is a context manager for the steady state: after
  warmup, wrap the training loop and any recompile beyond the declared
  budget warns or raises :class:`RetraceError` *at trace time*, naming the
  offending function and signature;
- :func:`enable_persistent_cache` wires jax's persistent compilation cache
  (``FLAGS_persistent_compile_cache`` / ``FLAGS_compile_cache_dir``), so
  a restarted process pays tracing but not backend compilation.

Trace count is the retrace signal, not XLA's internal executable cache:
a trace is exactly one new specialization from the framework's point of
view, and it is observable portably (the Python body runs once per trace).
"""
from __future__ import annotations

import contextlib
import functools
import itertools
import os
import threading
import warnings
from typing import Any, Callable, Dict, Optional

__all__ = [
    "RetraceError", "cache_stats", "reset_stats", "instrument",
    "register_name", "retrace_guard", "enable_persistent_cache",
    "initialize_from_flags",
]


class RetraceError(RuntimeError):
    """An XLA recompile happened inside a :func:`retrace_guard` window."""


class _Entry:
    __slots__ = ("compiles", "calls", "signatures", "last_trace_signature")

    def __init__(self):
        self.compiles = 0
        self.calls = 0
        self.signatures: Dict[str, int] = {}
        self.last_trace_signature: Optional[str] = None

    def as_dict(self) -> dict:
        return {"compiles": self.compiles, "calls": self.calls,
                "cache_hits": max(self.calls - self.compiles, 0),
                "signatures": dict(self.signatures),
                "last_trace_signature": self.last_trace_signature}


_lock = threading.RLock()
_entries: Dict[str, _Entry] = {}
_name_serial = itertools.count()
_guards: list = []  # active retrace_guard frames (innermost last)
_last_trace_signature: Optional[str] = None


def register_name(base: str) -> str:
    """A unique stats key (``base`` + serial) for per-instance tracking."""
    return f"{base}#{next(_name_serial)}"


def _leaf_sig(x: Any) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}{tuple(shape)}"
    return repr(x)


def abstract_signature(args, kwargs) -> str:
    """shape/dtype signature of a call — stable across values, sensitive to
    exactly what forces a retrace (shapes, dtypes, static values)."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs))
    return "(" + ", ".join(_leaf_sig(leaf) for leaf in leaves) + ")"


def _entry(name: str) -> _Entry:
    with _lock:
        e = _entries.get(name)
        if e is None:
            e = _entries[name] = _Entry()
        return e


def record_trace(name: str, signature: str) -> None:
    """Called from inside a traced body: one new specialization exists."""
    global _last_trace_signature
    with _lock:
        e = _entry(name)
        e.compiles += 1
        e.signatures[signature] = e.signatures.get(signature, 0) + 1
        e.last_trace_signature = signature
        _last_trace_signature = signature
        guards = list(_guards)
    try:
        # compiles are rare and exactly what a crash postmortem wants:
        # land each one in the flight-recorder ring and the trace buffer
        # (host-side bookkeeping only — the trace itself is already paying
        # seconds; telemetry failures must never break it)
        from ..observability import flight as _flight
        from ..observability import tracing as _tracing

        _flight.note("compile", corr=_tracing.current(), program=name,
                     signature=signature[:200])
        _tracing.record_event("compile", program=name)
    except Exception:
        pass
    for g in guards:
        g._on_trace(name, signature)


def record_call(name: str) -> None:
    with _lock:
        _entry(name).calls += 1


def cache_stats(name: Optional[str] = None) -> dict:
    """Compile/call counters.

    ``cache_stats()`` aggregates every instrumented program:
    ``{"compiles", "calls", "cache_hits", "last_trace_signature",
    "functions": {name: per-function dict}}``. ``cache_stats(name)``
    returns one function's dict (zeros if it never ran).
    """
    with _lock:
        if name is not None:
            e = _entries.get(name)
            return e.as_dict() if e is not None else _Entry().as_dict()
        compiles = sum(e.compiles for e in _entries.values())
        calls = sum(e.calls for e in _entries.values())
        return {"compiles": compiles, "calls": calls,
                "cache_hits": max(calls - compiles, 0),
                "last_trace_signature": _last_trace_signature,
                "functions": {n: e.as_dict() for n, e in _entries.items()}}


def reset_stats() -> None:
    with _lock:
        _entries.clear()
        global _last_trace_signature
        _last_trace_signature = None


def instrument(fn: Callable, name: Optional[str] = None) -> Callable:
    """Wrap ``fn`` for ``jax.jit`` so each TRACE is recorded.

    The wrapper's body executes exactly once per specialization (that is
    what tracing is), so it is the portable retrace probe. The trace runs
    under a ``compile`` profiler span; pair with :func:`record_call` at the
    dispatch site for hit-rate accounting. The stats key is attached as
    ``wrapped.__cc_name__``.
    """
    key = name or register_name(getattr(fn, "__name__", "jit_fn"))

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        from ..profiler import RecordEvent

        record_trace(key, abstract_signature(args, kwargs))
        with RecordEvent("compile"):
            return fn(*args, **kwargs)

    wrapped.__cc_name__ = key
    return wrapped


class _Guard:
    def __init__(self, max_compiles: int, action: str, label: str):
        self.max_compiles = int(max_compiles)
        self.action = action
        self.label = label
        self.seen: list = []  # (name, signature) of traces in the window

    def _on_trace(self, name: str, signature: str):
        self.seen.append((name, signature))
        if len(self.seen) <= self.max_compiles:
            return
        msg = (f"retrace_guard({self.label}): {len(self.seen)} compile(s) "
               f"inside a window budgeted for {self.max_compiles}; "
               f"latest: {name} traced for {signature}. An unstable input "
               f"shape is recompiling the step — pad/bucket the pipeline "
               f"(DataLoader(pad_batches=..., length_buckets=...)).")
        if self.action == "raise":
            raise RetraceError(msg)
        warnings.warn(msg, RuntimeWarning, stacklevel=3)


@contextlib.contextmanager
def retrace_guard(max_compiles: int = 0, action: str = "raise",
                  label: str = "steady-state"):
    """Bound compiles inside the ``with`` block.

    Enter it AFTER warmup: any trace of an instrumented program beyond
    ``max_compiles`` raises :class:`RetraceError` (``action="raise"``) or
    emits a ``RuntimeWarning`` (``action="warn"``) the moment it happens,
    naming the function and the shape signature that caused it.
    """
    if action not in ("raise", "warn"):
        raise ValueError(f"action must be 'raise' or 'warn', got {action!r}")
    g = _Guard(max_compiles, action, label)
    with _lock:
        _guards.append(g)
    try:
        yield g
    finally:
        with _lock:
            _guards.remove(g)


# ------------------------------------------------- persistent XLA cache
_persistent_dir: Optional[str] = None


def enable_persistent_cache(cache_dir: Optional[str] = None,
                            min_compile_secs: Optional[float] = None) -> str:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Subsequent processes that compile an identical program (same HLO,
    flags, backend) load the executable from disk instead of recompiling.
    Returns the directory in use. Safe to call repeatedly.
    """
    global _persistent_dir
    from . import flags

    import jax

    cache_dir = (cache_dir or flags.flag("FLAGS_compile_cache_dir")
                 or os.path.join(os.path.expanduser("~"), ".cache",
                                 "paddle_tpu", "xla"))
    cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
    os.makedirs(cache_dir, exist_ok=True)
    if min_compile_secs is None:
        min_compile_secs = flags.flag(
            "FLAGS_persistent_cache_min_compile_secs")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for opt, val in (
            ("jax_persistent_cache_min_compile_time_secs",
             float(min_compile_secs)),
            ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(opt, val)
        except AttributeError:  # knob not present on this jax
            pass
    try:  # older jax needs the explicit initializer as well
        from jax.experimental.compilation_cache import compilation_cache as cc

        if hasattr(cc, "set_cache_dir"):
            cc.set_cache_dir(cache_dir)
    except Exception:
        pass
    _persistent_dir = cache_dir
    return cache_dir


def persistent_cache_dir() -> Optional[str]:
    """The directory wired by :func:`enable_persistent_cache`, else None."""
    return _persistent_dir


def initialize_from_flags() -> None:
    """Honor ``FLAGS_persistent_compile_cache`` at import (env-settable:
    ``FLAGS_persistent_compile_cache=1 python train.py``)."""
    from . import flags

    if flags.flag("FLAGS_persistent_compile_cache"):
        enable_persistent_cache()


initialize_from_flags()
