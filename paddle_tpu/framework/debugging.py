"""NaN/Inf detection — the ``FLAGS_check_nan_inf`` machinery.

Reference parity: per-op NaN/Inf scans under ``FLAGS_check_nan_inf``
(``paddle/fluid/framework/details/nan_inf_utils_detail.cu``, eager variant
``paddle/fluid/eager/nan_inf_utils.cc``). TPU-native: instead of scanning
after every kernel (which would force host syncs inside the XLA program),
finite-ness is reduced *in-graph* to one scalar per checked tree and
inspected at step boundaries — one cheap all-finite AND fused into the
step, no extra host round-trips beyond the loss fetch itself.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def tree_all_finite(tree: Any) -> jax.Array:
    """In-graph: scalar bool, True iff every float leaf is finite.
    Usable inside jit (the reference's per-op scan collapses to this)."""
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)]
    if not leaves:
        return jnp.asarray(True)
    oks = [jnp.isfinite(x).all() for x in leaves]
    return jnp.stack(oks).all()


def find_nonfinite(tree: Any) -> List[Tuple[str, int, int]]:
    """Host-side: list of (path, n_nan, n_inf) for offending leaves —
    the debugging companion to :func:`tree_all_finite`."""
    bad = []
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for kp, leaf in flat:
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        n_nan = int(np.isnan(arr).sum())
        n_inf = int(np.isinf(arr).sum())
        if n_nan or n_inf:
            bad.append((jax.tree_util.keystr(kp), n_nan, n_inf))
    return bad


def check_numerics(tree: Any, name: str = "tensor") -> None:
    """Raise ``FloatingPointError`` naming the offending leaves (eager /
    step-boundary use), mirroring the reference's enforce-on-NaN."""
    bad = find_nonfinite(tree)
    if bad:
        detail = ", ".join(f"{p} (nan={n}, inf={i})" for p, n, i in bad)
        raise FloatingPointError(f"NaN/Inf detected in {name}: {detail}")
