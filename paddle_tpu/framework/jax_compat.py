"""Version compatibility shims over the jax API surface.

The codebase targets the modern spelling (``jax.shard_map`` with the
``check_vma`` kwarg); older jax releases (< 0.5) ship it as
``jax.experimental.shard_map.shard_map`` with the kwarg named
``check_rep``. Import :func:`shard_map` from here instead of from jax so
every call site works on both.
"""
from __future__ import annotations

try:  # modern jax: top-level export
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # jax < 0.5: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg is detected from the SIGNATURE, not the
# import location: mid-band releases export jax.shard_map while still
# spelling the kwarg check_rep
import inspect as _inspect

_KWARG = ("check_vma" if "check_vma"
          in _inspect.signature(_shard_map).parameters else "check_rep")

__all__ = ["shard_map", "axis_size", "pcast",
           "make_array_from_process_local_data"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    if check_vma is not None:
        kw[_KWARG] = check_vma
    elif _KWARG == "check_rep":
        # code written for the VMA era relies on pcast to reconcile varying
        # types; the pre-VMA replication checker has no such escape hatch
        # and false-positives on those patterns, so default it off
        kw[_KWARG] = False
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


try:  # modern jax
    from jax.lax import axis_size  # type: ignore[attr-defined]
except ImportError:
    def axis_size(axis_name):
        """Size of a mapped mesh axis. On older jax, ``psum(1, axis)`` over
        a unit constant folds to the static axis size (a plain int), so it
        remains usable in shapes."""
        from jax import lax
        return lax.psum(1, axis_name)


try:  # modern jax: VMA cast between varying/invariant manual types
    from jax.lax import pcast  # type: ignore[attr-defined]
except ImportError:
    def pcast(t, axis_names=None, *, to=None):
        """Pre-VMA jax has no varying/invariant distinction inside
        shard_map — the cast is the identity."""
        return t


try:  # jax >= 0.4.26: per-host slice -> global sharded array, no
    # replicated staging copy on the way to the GSPMD layout
    from jax import (  # type: ignore[attr-defined]
        make_array_from_process_local_data,
    )
except ImportError:
    def make_array_from_process_local_data(sharding, local_data,
                                           global_shape=None):
        """Older jax: land the host batch through device_put — correct
        (single-process: local IS global) but via a replicated copy."""
        import jax

        return jax.device_put(local_data, sharding)
