"""Top-level API long tail: places, mode switches, tensor-array helpers.

Reference parity for the remaining ``paddle.*`` exports
(``python/paddle/__init__.py`` __all__): device Places
(``python/paddle/fluid/core.py`` wrappers over ``paddle/fluid/platform/place.h``),
static/dynamic mode switches (``python/paddle/fluid/framework.py``),
grad-mode toggles (``python/paddle/framework/``), ``paddle.batch``
(``python/paddle/batch.py``), LoDTensorArray ops
(``python/paddle/tensor/array.py``), and ``check_shape``
(``python/paddle/fluid/layers/utils.py:453``).

TPU-native collapses: a Place is a thin name tag resolved against
``jax.devices()`` (PJRT owns placement); LoDTensorArray is a Python list
(jax traces Python directly, so array_write/read need no graph ops);
static mode is a flag only — programs are always traced functions.
"""
from __future__ import annotations

import builtins
import warnings
from typing import List, Optional

import numpy as np

__all__ = [
    "CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "NPUPlace", "TPUPlace",
    "enable_static", "disable_static", "in_dynamic_mode",
    "is_grad_enabled", "set_grad_enabled", "LazyGuard", "batch",
    "check_shape", "create_parameter", "disable_signal_handler",
    "create_array", "array_write", "array_read", "array_length",
    "index_add_", "dtype",
]


# ------------------------------------------------------------------ places
class _Place:
    """Device tag; resolves lazily against jax.devices() (PJRT owns actual
    placement — reference ``platform::Place`` carries much more because it
    keys allocators; here it is identity only)."""

    _backend: Optional[str] = None  # None = default backend

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def jax_device(self):
        import jax

        devs = (jax.devices() if self._backend is None
                else jax.devices(self._backend))
        return devs[self.device_id]

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"


class CPUPlace(_Place):
    _backend = "cpu"

    def __init__(self):
        super().__init__(0)


class CUDAPlace(_Place):
    """On this stack the accelerator is the default jax backend (TPU);
    CUDAPlace(n) keeps ported scripts running unchanged."""


class TPUPlace(_Place):
    pass


class CUDAPinnedPlace(_Place):
    _backend = "cpu"  # pinned host memory: host-side on PJRT


class NPUPlace(_Place):
    pass


# ------------------------------------------------- static/dynamic switches
_static_mode = [False]


def enable_static():
    """Flag-level parity: programs here are ALWAYS traced functions
    compiled by XLA, so static mode changes nothing about execution —
    only what ``in_dynamic_mode()`` reports."""
    if not _static_mode[0]:
        warnings.warn(
            "paddle_tpu has one execution model (traced functions under "
            "XLA); enable_static() only flips the mode flag", stacklevel=2)
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_dynamic_mode() -> builtins.bool:
    return not _static_mode[0]


# --------------------------------------------------------------- grad mode
def is_grad_enabled() -> builtins.bool:
    """Whether the eager tape records (reference
    ``paddle.is_grad_enabled``). jax.grad closures are unaffected — they
    differentiate whatever they wrap."""
    from ..eager import _grad_enabled

    return _grad_enabled()


class set_grad_enabled:
    """Context manager / direct call, like paddle.set_grad_enabled."""

    def __init__(self, mode: builtins.bool):
        from ..eager import _grad_enabled, _state

        self.prev = _grad_enabled()
        _state.grad_enabled = builtins.bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        from ..eager import _state

        _state.grad_enabled = self.prev
        return False


class LazyGuard:
    """Reference ``paddle.LazyGuard`` defers parameter materialization to
    first forward to avoid host-memory spikes on huge models. Here
    parameters are jax arrays created on demand by the functional state
    (no per-parameter CUDA malloc at definition time), so the guard has
    nothing to defer; it is a documented no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# ------------------------------------------------------------- misc utils
def batch(reader, batch_size: int, drop_last: bool = False):
    """Classic ``paddle.batch``: wrap a sample reader into a batch reader."""

    def batched():
        buf: List = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def check_shape(shape) -> None:
    """Validate a shape argument (reference
    ``fluid/layers/utils.py:453``): ints (-1 allowed once per use for
    inferred dims) or a 1-D integer tensor."""
    import jax

    if isinstance(shape, (list, tuple)):
        for d in shape:
            if isinstance(d, (int, np.integer)):
                if d < -1:
                    raise ValueError(f"invalid dim {d} in shape {shape}")
            elif not isinstance(d, (jax.Array, np.ndarray)):
                raise TypeError(f"shape dims must be int/tensor, got "
                                f"{type(d).__name__}")
    elif isinstance(shape, (jax.Array, np.ndarray)):
        if np.asarray(shape).ndim != 1:
            raise ValueError("shape tensor must be 1-D")
    else:
        raise TypeError(f"shape must be list/tuple/tensor, got "
                        f"{type(shape).__name__}")


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone trainable parameter (reference
    ``paddle.create_parameter``): an eager Tensor with grad history
    enabled, initialized like ``nn.Layer.create_parameter``. ``name`` is
    accepted for API parity but unused — there is no global variable scope
    to register names into (jaxprs name nothing)."""
    from ..eager import Tensor
    from ..framework.dtype import convert_dtype
    from ..nn.initializer import (Constant, XavierUniform,
                                  _resolve_initializer)
    from ..nn.layer import take_rng_key

    # same resolution chain as nn.Layer.create_parameter: an installed
    # set_global_initializer outranks the built-in default here too
    init = _resolve_initializer(None, default_initializer, is_bias=is_bias)
    if init is None:
        init = Constant(0.0) if is_bias else XavierUniform()
    val = init(take_rng_key("params"), tuple(shape), convert_dtype(dtype))
    t = Tensor(val)
    t.stop_gradient = False
    return t


def disable_signal_handler() -> None:
    """Reference installs SIGSEGV/SIGBUS handlers for C++ stack capture and
    lets users disable them; this runtime installs none — no-op."""


# ----------------------------------------------- LoDTensorArray (as list)
def create_array(dtype: str = "float32", initialized_list=None) -> list:
    """LoDTensorArray analogue: a Python list (tracing handles it)."""
    return [] if initialized_list is None else list(initialized_list)


def array_write(x, i, array: Optional[list] = None) -> list:
    if array is None:
        array = []
    i = int(i)
    if i < len(array):
        array[i] = x
    elif i == len(array):
        array.append(x)
    else:
        raise IndexError(f"array_write index {i} beyond length {len(array)}")
    return array


def array_read(array: list, i):
    return array[int(i)]


def array_length(array: list):
    import jax.numpy as jnp

    return jnp.asarray(len(array), jnp.int32)


def index_add_(x, index, axis, value, name=None):
    """Inplace ``index_add``: mutates an eager Tensor's storage; on plain
    arrays returns the updated value (jax arrays are immutable). Obeys the
    tape's in-place invariant: mutating a grad-requiring tensor would make
    recorded vjps silently stale, so it raises like the other ``_`` ops."""
    from ..eager import Tensor, _grad_enabled
    from ..ops.search import index_add

    if isinstance(x, Tensor):
        if _grad_enabled() and not x.stop_gradient:
            raise RuntimeError(
                "index_add_ on a tensor that requires grad would break the "
                "recorded tape; use the functional index_add, detach() "
                "first, or run under no_grad()")
        x._data = index_add(x._data, index, axis, value)
        return x
    return index_add(x, index, axis, value)


class dtype:
    """``paddle.dtype`` callable: normalizes any dtype spec to numpy dtype
    (the runtime's canonical form)."""

    def __new__(cls, spec):
        from ..framework.dtype import convert_dtype

        return convert_dtype(spec)
