"""Self-healing training: numerics watchdog, auto-rollback, hang/preemption
supervision around a compiled train step.

Reference parity: the reference's fleet stack reacts to failures out-of-band
(elastic manager restarts, ``auto_checkpoint`` resume, per-op
``FLAGS_check_nan_inf`` scans). On TPU the interesting failures happen *in*
the compiled step — a NaN loss, a hung collective, a pod preemption — so
this module supervises the step itself:

- :class:`NumericsWatchdog` — consumes the LAZY ``(loss, ok, found_inf)``
  flags a ``TrainStep.watchdog_call`` returns and host-syncs them in
  batches of ``check_interval`` steps (PR 3's ``done_check_interval``
  pattern), so steady-state dispatch stays sync-free and recompile-free.
  An anomalous step was already *skipped in-graph* (the finite guard keeps
  the old state); the watchdog's job is bookkeeping and escalation:
  ``max_consecutive`` anomalies in a row escalate from skip-step to
  rollback. GradScaler inf-skips are recognised (``found_inf``) and NOT
  counted as anomalies.
- auto-rollback — :class:`TrainingSupervisor` restores the newest VALID
  ``AutoCheckpoint`` (crc-verified) and hands back the checkpoint's
  :class:`~paddle_tpu.io.cursor.DataCursor` so the caller replays the same
  data trajectory; ``skip_window`` additionally jumps the offending
  batches.
- :class:`HangWatchdog` — a daemon thread that fires when no step heartbeat
  lands within ``step_timeout`` (stuck H2D, hung collective); ``action=
  "exit"`` hard-exits with ``EXIT_HANG`` so ``distributed.launch`` restarts
  the worker from the last checkpoint.
- :class:`PreemptionHandler` — SIGTERM handler that requests a
  checkpoint-and-exit bounded by a ``resilience.Deadline`` grace window;
  the in-loop check raises :class:`TrainingPreempted` after the state (and
  cursor) is durably saved, and ``distributed.launch`` restarts such exits
  without charging ``--max_restarts``.

Fault sites: the loop is instrumented with ``train.step`` / ``train.ckpt``
/ ``train.data`` / ``train.bitflip`` fault points, so a seeded
:class:`~paddle_tpu.distributed.resilience.FaultPlan` can stall steps,
crash saves, or poison batches (``drop`` at ``train.data`` is translated
into ``step.inject_anomaly()`` — a NaN-poisoned loss; ``bitflip`` at
``train.bitflip`` flips one bit in one replica's physical tensor copies
via ``distributed.integrity.apply_bitflip`` — silent corruption only the
cross-replica fingerprint vote can see). ``tools/chaos_soak.py`` drives a
full kill/stall/NaN soak through these sites; ``tools/sdc_drill.py``
drives the silent-data-corruption escalation ladder.

Silent-data-corruption defense (``integrity_check_interval`` set): the
step emits lazy per-replica fingerprints, an
:class:`~paddle_tpu.distributed.integrity.IntegrityMonitor` votes on them
batched with the watchdog flush, and the supervisor escalates
suspect -> deterministic replay (existing rollback machinery; transient
faults are discarded with the replayed steps) -> conviction -> durable
quarantine record + :class:`~paddle_tpu.distributed.integrity.
HostEvictionRequested` so the launcher restarts on surviving capacity
through the elastic-mesh reshard path. Defaults off — the step programs
are bit-identical to a build without the feature.
"""
from __future__ import annotations

import os
import signal
import threading
import time
import warnings
from dataclasses import dataclass
from typing import Callable, List, Optional, Set, Tuple

from ..distributed.integrity import HostEvictionRequested  # noqa: F401
from ..distributed.resilience import (  # noqa: F401  (EXIT_* re-exported)
    Deadline, EXIT_EVICTED, EXIT_HANG, EXIT_PREEMPTED, InjectedBitflip,
    InjectedFault, fault_point)
from ..observability import flight as _flight
from ..observability import tracing as _tracing

__all__ = [
    "RecoveryPolicy", "TrainingSupervisor", "NumericsWatchdog",
    "HangWatchdog", "PreemptionHandler", "TrainingPreempted",
    "RollbackRequested", "HostEvictionRequested",
    "EXIT_PREEMPTED", "EXIT_HANG", "EXIT_EVICTED",
]


class TrainingPreempted(RuntimeError):
    """Raised at a step boundary after a SIGTERM/preemption request once the
    state has been checkpointed (or the grace deadline expired). The caller
    decides whether to re-raise, return, or ``sys.exit(EXIT_PREEMPTED)``."""

    def __init__(self, message: str, global_step: int, saved: bool):
        super().__init__(message)
        self.global_step = global_step
        self.saved = saved


class RollbackRequested(RuntimeError):
    """Control-flow signal: the watchdog escalated to rollback. The state
    has already been restored from the checkpoint; ``cursor`` (may be
    ``None`` when no checkpoint existed — continue in place) says where to
    resume the data stream and ``skip`` which ``(epoch, batch_index)``
    batches to jump."""

    def __init__(self, cursor, skip: Set[Tuple[int, int]]):
        super().__init__("numerics watchdog requested rollback")
        self.cursor = cursor
        self.skip = skip


@dataclass
class RecoveryPolicy:
    """Configuration for :class:`TrainingSupervisor` /
    ``Model.fit(recovery=...)``.

    - ``checkpoint_dir``: AutoCheckpoint root (``step_N`` dirs).
    - ``save_interval_steps``: snapshot every N optimizer steps.
    - ``check_interval``: watchdog host-sync batching (1 = every step).
    - ``max_consecutive``: K consecutive anomalous (skipped) steps escalate
      to rollback.
    - ``skip_window``: batches to jump past the first offending batch after
      a rollback (0 = replay everything and hope the anomaly was
      transient).
    - ``max_rollbacks``: give up (raise) after this many rollbacks.
    - ``step_timeout``: hang watchdog threshold in seconds (None = off).
    - ``hang_action``: ``"warn"`` logs and counts; ``"exit"`` hard-exits
      with ``EXIT_HANG`` for the launcher to restart.
    - ``preemption``: install the SIGTERM checkpoint-and-exit handler.
    - ``grace_seconds``: preemption grace budget (``resilience.Deadline``).
    - ``async_save``: overlap checkpoint IO with training (sync saves make
      kill-based tests deterministic).
    - ``integrity_check_interval``: silent-data-corruption defense —
      cross-replica fingerprint vote every N checked steps (``None`` =
      off, the default: step programs stay bit-identical to a build
      without the feature).
    - ``integrity_vote_axis``: mesh axis along which state must be
      bit-identical across replicas (leaves sharded over it — ZeRO
      shards — are excluded with coverage accounting).
    - ``integrity_forgive_after``: clean flushes after a replay before
      the armed suspect is forgiven as a transient fault.
    - ``integrity_ledger``: write/verify the per-save fingerprint record
      (``integrity.json``) next to ``metadata.json``.
    """

    checkpoint_dir: str
    save_interval_steps: int = 50
    keep_max: int = 3
    async_save: bool = True
    check_interval: int = 4
    max_consecutive: int = 2
    skip_window: int = 0
    max_rollbacks: int = 8
    step_timeout: Optional[float] = None
    hang_action: str = "warn"
    preemption: bool = True
    grace_seconds: float = 30.0
    integrity_check_interval: Optional[int] = None
    integrity_vote_axis: str = "dp"
    integrity_forgive_after: int = 2
    integrity_ledger: bool = True


class NumericsWatchdog:
    """Batches the lazy per-step numerics flags and decides escalation."""

    def __init__(self, check_interval: int = 4, max_consecutive: int = 2):
        self.check_interval = max(1, int(check_interval))
        self.max_consecutive = max(1, int(max_consecutive))
        self._pending: List[tuple] = []  # (epoch, batch_index, loss, ok, found)
        self.consecutive = 0
        self.anomalies = 0
        self.scaler_skips = 0
        self.first_bad: Optional[Tuple[int, int]] = None  # start of the run

    def observe(self, epoch: int, batch_index: int, loss, ok, found) -> None:
        """Record one step's flags WITHOUT forcing them to host."""
        self._pending.append((epoch, batch_index, loss, ok, found))

    @property
    def due(self) -> bool:
        return len(self._pending) >= self.check_interval

    def flush(self) -> List[Tuple[int, int, float]]:
        """Host-sync every pending flag; returns the newly-found anomalies
        as ``(epoch, batch_index, loss)``. Escalation state (``consecutive``
        / ``first_bad``) is updated as a side effect. The moment the streak
        reaches ``max_consecutive`` the scan stops — later flags in the
        window describe steps the rollback is about to replay anyway."""
        import jax

        from .. import profiler

        todo = [(e, bi, loss, ok, found)
                for e, bi, loss, ok, found in self._pending
                if ok is not None]   # accumulate-only calls: nothing to judge
        self._pending.clear()
        if not todo:
            return []
        # ONE device_get for the whole window — per-flag bool() would cost
        # up to 2*check_interval serialized host round-trips per flush,
        # defeating the batched-sync design
        # tpu-lint: disable=R1(THE batched watchdog sync point — one device_get per check_interval window, by design)
        fetched = jax.device_get([(loss, ok, found)
                                  for _, _, loss, ok, found in todo])
        out: List[Tuple[int, int, float]] = []
        for (epoch, bi, *_), (loss, ok, found) in zip(todo, fetched):
            if bool(found):          # GradScaler inf-skip: benign dynamics —
                self.scaler_skips += 1   # it also BREAKS an anomaly streak
                profiler.bump_counter("train.scaler_skip")
                self.consecutive = 0
                self.first_bad = None
                continue
            if bool(ok):
                self.consecutive = 0
                self.first_bad = None
                continue
            self.anomalies += 1
            profiler.bump_counter("train.anomaly")
            if self.consecutive == 0:
                self.first_bad = (epoch, bi)
            self.consecutive += 1
            out.append((epoch, bi, float(loss)))
            if self.consecutive >= self.max_consecutive:
                break
        return out

    @property
    def should_rollback(self) -> bool:
        return self.consecutive >= self.max_consecutive


class HangWatchdog:
    """Detects a train step exceeding ``step_timeout`` between heartbeats.

    The watcher runs on a daemon thread; :meth:`beat` is called at every
    step boundary. A stall fires ONCE per incident (re-armed by the next
    beat): ``on_hang(elapsed)`` then either a warning (``action="warn"``)
    or ``os._exit(EXIT_HANG)`` (``action="exit"``) — a hung XLA dispatch
    cannot be interrupted from Python, so escaping means dying hard and
    letting ``distributed.launch`` restart from the last checkpoint.
    """

    def __init__(self, step_timeout: float, action: str = "warn",
                 on_hang: Optional[Callable[[float], None]] = None):
        if action not in ("warn", "exit"):
            raise ValueError(f"hang action must be 'warn' or 'exit', got {action!r}")
        self.step_timeout = float(step_timeout)
        self.action = action
        self.on_hang = on_hang
        self.hangs_detected = 0
        self._last_beat = time.monotonic()
        self._fired = False
        self._paused = True
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HangWatchdog":
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="hang-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.step_timeout)

    def beat(self) -> None:
        """A step completed (or the loop is alive at a boundary)."""
        self._last_beat = time.monotonic()
        self._fired = False
        self._paused = False

    def pause(self) -> None:
        """Suspend detection across non-step phases (eval, shutdown)."""
        self._paused = True

    def _watch(self) -> None:
        from .. import profiler

        poll = max(0.05, min(self.step_timeout / 4.0, 1.0))
        while not self._stop.wait(poll):
            if self._paused or self._fired:
                continue
            elapsed = time.monotonic() - self._last_beat
            if elapsed <= self.step_timeout:
                continue
            self._fired = True
            self.hangs_detected += 1
            profiler.bump_counter("train.hang")
            # flight-record the incident BEFORE any exit path: a hard
            # os._exit leaves nothing else behind. The watcher thread has
            # no step correlation id of its own — the dump's span tail
            # carries the last step's.
            _flight.dump("hang", extra={"elapsed_s": round(elapsed, 3),
                                        "step_timeout_s": self.step_timeout,
                                        "action": self.action})
            msg = (f"train step exceeded step_timeout={self.step_timeout}s "
                   f"(no heartbeat for {elapsed:.1f}s) — stuck H2D or hung "
                   f"collective?")
            if self.on_hang is not None:
                try:
                    self.on_hang(elapsed)
                except Exception:
                    pass
            if self.action == "exit":
                print(f"[supervisor] {msg}; exiting {EXIT_HANG} for the "
                      f"launcher to restart", flush=True)
                os._exit(EXIT_HANG)
            warnings.warn(msg, RuntimeWarning)


class PreemptionHandler:
    """SIGTERM/preemption-notice handler (installed on the main thread).

    The signal only *requests* a stop: the training loop observes
    :attr:`requested` at the next step boundary, checkpoints within the
    remaining :attr:`deadline`, and raises :class:`TrainingPreempted`.
    Previously-installed handlers are restored on :meth:`uninstall`.
    """

    def __init__(self, grace_seconds: float = 30.0,
                 signals: Tuple[int, ...] = (signal.SIGTERM,)):
        self.grace_seconds = float(grace_seconds)
        self.signals = tuple(signals)
        self.requested = False
        self.deadline: Optional[Deadline] = None
        self._prev: dict = {}

    def install(self) -> "PreemptionHandler":
        try:
            for sig in self.signals:
                self._prev[sig] = signal.signal(sig, self._on_signal)
        except ValueError:
            # signal.signal only works on the main thread; a fit() driven
            # from a worker thread trains without preemption handling
            # rather than crashing before the first step
            self.uninstall()
            warnings.warn(
                "preemption handler unavailable off the main thread; "
                "SIGTERM checkpoint-and-exit is disabled for this run",
                RuntimeWarning)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._prev.clear()

    def _on_signal(self, signum, frame) -> None:
        # flags only: the handler interrupts the main thread mid-bytecode,
        # so taking any non-reentrant lock here (counters, IO) could
        # deadlock against the very frame it interrupted — accounting
        # happens at the step-boundary check instead
        if not self.requested:   # first notice stamps the grace budget
            self.requested = True
            self.deadline = Deadline(self.grace_seconds)


class TrainingSupervisor:
    """Ties watchdogs, AutoCheckpoint and the preemption handler around a
    compiled train step (``TrainStep`` / ``_HapiTrainStep`` /
    ``DistributedTrainStep`` — anything with ``watchdog_call``,
    ``inject_anomaly``, ``state_dict``/``set_state_dict``).

    Usage (``Model.fit(recovery=...)`` wraps exactly this)::

        sup = TrainingSupervisor(step, policy).start()
        cursor = sup.restore()            # None on a fresh run
        try:
            for epoch, i, batch in ...:   # resumed/fast-forwarded stream
                if sup.should_skip(epoch, i):
                    continue
                sup.before_batch()        # fault sites; stall/poison seams
                loss, ok, found = step.watchdog_call(batch)
                sup.after_batch(epoch, i, loss, ok, found)
        except RollbackRequested as rb:   # rewind data to rb.cursor
            ...
        except TrainingPreempted:         # checkpointed; exit/resume later
            ...
        finally:
            sup.stop()
    """

    def __init__(self, step, policy: RecoveryPolicy,
                 cursor_fn: Optional[Callable[[], "object"]] = None):
        from ..distributed.checkpoint import AutoCheckpoint

        self.step = step
        self.policy = policy
        self.checkpoint = AutoCheckpoint(
            policy.checkpoint_dir,
            save_interval_steps=max(1, int(policy.save_interval_steps)),
            keep_max=policy.keep_max, async_save=policy.async_save)
        self.watchdog = NumericsWatchdog(policy.check_interval,
                                         policy.max_consecutive)
        self.hang = (HangWatchdog(policy.step_timeout, policy.hang_action)
                     if policy.step_timeout else None)
        self.preempt = (PreemptionHandler(policy.grace_seconds)
                        if policy.preemption else None)
        self.integrity = None
        if policy.integrity_check_interval:
            enable = getattr(step, "enable_integrity", None)
            if enable is None:
                warnings.warn(
                    "integrity_check_interval is set but this step type "
                    "has no enable_integrity() (per-replica fingerprints "
                    "need a device mesh); silent-data-corruption checks "
                    "are disabled for this run", RuntimeWarning)
            else:
                from ..distributed.integrity import IntegrityMonitor

                enable(policy.integrity_vote_axis)
                self.integrity = IntegrityMonitor(
                    policy.integrity_check_interval,
                    forgive_after=policy.integrity_forgive_after)
        # cursor_fn supplies the CURRENT input-pipeline position (the NEXT
        # batch) whenever a checkpoint is cut mid-run
        self.cursor_fn = cursor_fn
        self.rollbacks = 0
        self.skipped_batches = 0
        self._skip: Set[Tuple[int, int]] = set()
        # events: the hapi layer routes these into callbacks
        self.on_anomaly: Optional[Callable] = None
        self.on_rollback: Optional[Callable] = None
        self.on_preemption: Optional[Callable] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "TrainingSupervisor":
        if self.preempt is not None:
            self.preempt.install()
        if self.hang is not None:
            self.hang.start()
        return self

    def stop(self) -> None:
        if self.hang is not None:
            self.hang.stop()
        if self.preempt is not None:
            self.preempt.uninstall()
        self.checkpoint.wait()
        # the last step's correlation id (stamped by before_batch) must
        # not leak past the supervised run: a later generate() on this
        # thread would inherit the stale train-step lane
        _tracing.set_current(None)

    def __enter__(self) -> "TrainingSupervisor":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # ------------------------------------------------------ state plumbing
    def _template(self, with_cursor: bool = True) -> dict:
        from ..io.cursor import DataCursor

        t = dict(self.step.state_dict())
        if with_cursor:
            t["data_cursor"] = DataCursor().as_state()
        return t

    def _shardings(self):
        fn = getattr(self.step, "state_shardings", None)
        return fn() if fn is not None else None

    def restore(self):
        """Restore the newest VALID checkpoint into the step (crc-verified;
        torn/corrupt candidates are skipped by ``latest_checkpoint``).
        Returns the recorded :class:`DataCursor`, ``None`` when there is no
        checkpoint or it predates cursors (old checkpoints still load; the
        data stream then restarts at epoch 0).

        Topology-agnostic: when the checkpoint was written on a DIFFERENT
        mesh (elastic shrink/grow — the step was rebuilt on surviving
        capacity via ``distributed.elastic_mesh.reshaped_mesh``), every
        leaf is re-sliced onto this step's shardings while loading —
        streaming, bounded host memory, never a full global array — and
        the resize is reported (``train.reshard`` counter). A candidate
        that fails to LOAD (corruption surfacing between validation and
        read, e.g. a rank's shards lost to a dying host) is skipped and
        the next newest complete checkpoint is tried; candidates that
        failed VALIDATION are remembered too, so each retry does not
        re-crc every shard of already-rejected newer checkpoints."""
        import jax

        from ..distributed.checkpoint import (_STEP_DIR,
                                              CheckpointCorruptError,
                                              latest_checkpoint, load_state)
        from ..distributed.integrity import ledger_problem, verify_ledger
        from ..io.cursor import DataCursor

        tried = []
        while True:
            path = latest_checkpoint(self.checkpoint.root, exclude=tried,
                                     on_invalid=tried.append)
            if path is None:
                return None
            # a checkpoint whose integrity ledger says the replicas had
            # already diverged at save time is poisoned regardless of its
            # crcs — reject it (with the suspect rank named) before
            # reading a byte of state
            prob = ledger_problem(path)
            if prob is not None:
                warnings.warn(
                    f"checkpoint rejected by integrity ledger: {prob}; "
                    f"falling back to the next newest complete checkpoint",
                    RuntimeWarning)
                tried.append(path)
                continue
            try:
                # "proactive": every recorded shard is crc-verified up
                # front, not just the slices this topology's devices ask
                # for — supervisor restores must not trust lazy reads
                flat = load_state(path, shardings=self._shardings(),
                                  verify="proactive")
                if self.integrity is not None:
                    prob = verify_ledger(path, flat)
                    if prob is not None:
                        raise CheckpointCorruptError(prob)
                # only a load that SUCCEEDED counts as a reshard — skipped
                # candidates must not bump the counter or log a resize
                self._report_reshard(path)
                break
            except CheckpointCorruptError as e:
                warnings.warn(
                    f"checkpoint {path} failed to load ({e}); falling back "
                    f"to the next newest complete checkpoint",
                    RuntimeWarning)
                tried.append(path)
        template = self._template(with_cursor=True)
        flat_t, treedef = _flatten_template(template)
        missing = [k for k in flat_t if k not in flat]
        cursor_missing = any(k.startswith("data_cursor/") for k in missing)
        hard_missing = [k for k in missing
                        if not k.startswith(("data_cursor/", "base_key",
                                             "scaler_state/"))]
        if hard_missing:
            raise KeyError(
                f"checkpoint {path} is missing required state leaves "
                f"{hard_missing[:5]} — was it written by a different model/"
                f"optimizer configuration?")
        ordered = [flat.get(k) for k in flat_t]
        state = jax.tree_util.tree_unflatten(treedef, ordered)
        cursor_state = state.pop("data_cursor", None)
        state = {k: v for k, v in state.items()
                 if not (v is None or (isinstance(v, dict)
                                       and any(x is None for x in v.values())))}
        self.step.set_state_dict(state)
        step_no = int(_STEP_DIR.match(os.path.basename(path)).group(1))
        print(f"[supervisor] restored {path} (step {step_no})", flush=True)
        if cursor_missing:
            return None
        return DataCursor.from_state(cursor_state)

    def _report_reshard(self, path: str) -> None:
        """Log + count a cross-topology restore (checkpoint mesh != the
        step's live mesh). Purely observational: the re-slice itself needs
        no planning input — per-shard offsets in the metadata drive it."""
        from .. import profiler
        from ..distributed.checkpoint import mesh_info

        info = mesh_info(path)
        mesh = getattr(self.step, "mesh", None)
        if not info or mesh is None or not info.get("axes"):
            return
        cur = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        if cur != info["axes"]:
            profiler.bump_counter("train.reshard")
            print(f"[supervisor] elastic reshard: checkpoint written on "
                  f"mesh {info['axes']} ({info.get('devices')} devices); "
                  f"restoring onto {cur} ({mesh.size} devices)", flush=True)

    def save_now(self, cursor=None) -> None:
        """Cut a checkpoint at the current step, recording the cursor."""
        if self.integrity is not None:
            # never cut a checkpoint over unverified state: drain the
            # fingerprint window first — a divergence raises (replay/
            # convict) BEFORE any poisoned bytes reach disk
            self._flush_watchdog()
        if self.hang is not None:
            self.hang.pause()   # a slow (sync) save is not a hung step
        fault_point("train.ckpt")
        state = dict(self.step.state_dict())
        cursor = cursor if cursor is not None else (
            self.cursor_fn() if self.cursor_fn is not None else None)
        if cursor is not None:
            state["data_cursor"] = cursor.as_state()
        extra_files = None
        if self.integrity is not None and self.policy.integrity_ledger:
            from ..distributed.integrity import (LEDGER_FILE,
                                                 build_ledger_bytes)

            extra_files = {LEDGER_FILE: build_ledger_bytes(
                state, int(self.step._count), self.integrity)}
        self.checkpoint.save(int(self.step._count), state,
                             extra_files=extra_files)

    def maybe_save(self, cursor=None) -> bool:
        if not self.checkpoint._due(int(self.step._count)):
            return False
        self.save_now(cursor)
        return True

    # ------------------------------------------------------------ the loop
    def should_skip(self, epoch: int, batch_index: int) -> bool:
        """True for batches inside a post-rollback ``skip_window``."""
        if (epoch, batch_index) in self._skip:
            from .. import profiler

            self._skip.discard((epoch, batch_index))
            self.skipped_batches += 1
            profiler.bump_counter("train.batch_skip")
            return True
        return False

    def before_batch(self) -> None:
        """Fault sites ahead of the dispatch: a ``delay`` rule at
        ``train.step`` stalls (exercising the hang watchdog), a ``crash``
        kills the process, and a ``drop`` at ``train.data`` poisons the
        upcoming batch through the step's NaN seam.

        Also the training side's correlation-id mint: each step boundary
        stamps the thread's tracing id, so spans and flight-recorder
        dumps (anomaly, rollback, preemption) attribute to the step that
        caused them."""
        _tracing.set_current(
            f"train-{os.getpid():x}-s{int(self.step._count)}")
        fault_point("train.step")
        try:
            fault_point("train.bitflip")
        except InjectedBitflip as f:
            # silent corruption: one bit in ONE replica's physical copies
            # of a parameter — the logical value is untouched and the
            # numerics watchdog stays blind; only the fingerprint vote
            # (integrity_check_interval) can catch it
            from ..distributed.integrity import apply_bitflip

            apply_bitflip(self.step, f)
        except InjectedFault:
            # a non-bitflip kind at this site (sweep matrix coverage):
            # degrade to the NaN poison seam like train.data
            self.step.inject_anomaly()
        try:
            fault_point("train.data")
        except InjectedFault:
            self.step.inject_anomaly()

    def after_batch(self, epoch: int, batch_index: int, loss, ok, found,
                    cursor=None) -> None:
        """Observe flags, heartbeat, checkpoint, honor preemption. May
        raise :class:`RollbackRequested` or :class:`TrainingPreempted`."""
        # beat FIRST: the step dispatched, so the hang window now covers
        # only the flush's device drain — where a stuck collective would
        # genuinely surface — and not step + flush stacked together
        if self.hang is not None:
            self.hang.beat()
        self.watchdog.observe(epoch, batch_index, loss, ok, found)
        if self.integrity is not None and ok is not None:
            fp = self.step.take_fingerprint()
            if fp is not None:
                self.integrity.observe(int(self.step._count), fp)
        if self.watchdog.due or (self.integrity is not None
                                 and self.integrity.due):
            self._flush_watchdog()
        if self.maybe_save(cursor) and self.hang is not None:
            # a (possibly synchronous) checkpoint save is not a hung step
            self.hang.beat()
        if self.preempt is not None and self.preempt.requested:
            self._handle_preemption(cursor)

    def finish_epoch(self) -> None:
        """Drain pending flags at an epoch boundary (and pause the hang
        watchdog across eval/checkpoint phases)."""
        if self.hang is not None:
            self.hang.pause()
        self._flush_watchdog()

    def _flush_watchdog(self) -> None:
        from ..profiler import RecordEvent

        with RecordEvent("watchdog_sync"):
            fresh = self.watchdog.flush()
        for epoch, bi, loss in fresh:
            warnings.warn(
                f"numerics watchdog: non-finite step at epoch {epoch} batch "
                f"{bi} (loss={loss}); update was skipped in-graph "
                f"({self.watchdog.consecutive} consecutive)", RuntimeWarning)
            _tracing.record_event("train:anomaly", epoch=epoch, batch=bi,
                                  loss=loss)
            _flight.note("train_anomaly", corr=_tracing.current(),
                         epoch=epoch, batch=bi, loss=loss)
            if self.on_anomaly is not None:
                self.on_anomaly({"epoch": epoch, "batch_index": bi,
                                 "loss": loss})
        if self.watchdog.should_rollback:
            self._rollback()
        if self.integrity is not None:
            with RecordEvent("integrity_sync"):
                verdict = self.integrity.flush()
            if verdict is not None:
                self._handle_integrity(verdict)

    def _rollback(self) -> None:
        from .. import profiler
        from ..profiler import RecordEvent

        if self.hang is not None:
            # restore from slow storage is not a hung step; the next
            # post-rollback beat() re-arms detection
            self.hang.pause()
        self.rollbacks += 1
        profiler.bump_counter("train.rollback")
        # crash artifact while the ring still holds the anomaly lead-up
        # (the restore below rewinds state; the telemetry must not rewind)
        _tracing.record_event("train:rollback", rollbacks=self.rollbacks)
        _flight.dump("rollback", corr=_tracing.current(),
                     extra={"rollbacks": self.rollbacks,
                            "first_bad": list(self.watchdog.first_bad)
                            if self.watchdog.first_bad else None,
                            "anomalies": self.watchdog.anomalies})
        if self.rollbacks > self.policy.max_rollbacks:
            raise FloatingPointError(
                f"numerics watchdog: {self.rollbacks} rollbacks exceeded "
                f"max_rollbacks={self.policy.max_rollbacks}; training is "
                f"not recovering (check data/lr)")
        first_bad = self.watchdog.first_bad
        skip: Set[Tuple[int, int]] = set()
        if first_bad is not None and self.policy.skip_window > 0:
            e0, b0 = first_bad
            skip = {(e0, b0 + j) for j in range(self.policy.skip_window)}
        with RecordEvent("rollback"):
            self.checkpoint.wait()   # an in-flight async save must land first
            cursor = self.restore()
        self.watchdog.consecutive = 0
        self.watchdog.first_bad = None
        if self.integrity is not None:
            # fingerprints of steps this rollback replays would re-report
            # pre-restore divergence — forget them
            self.integrity.drop_pending()
        self._skip |= skip
        print(f"[supervisor] rollback #{self.rollbacks}: replaying from "
              f"{'checkpoint' if cursor is not None else 'current position'}"
              f"{f', skipping {len(skip)} batch(es)' if skip else ''}",
              flush=True)
        if self.on_rollback is not None:
            self.on_rollback({"rollbacks": self.rollbacks,
                              "cursor": cursor, "skip": sorted(skip)})
        raise RollbackRequested(cursor, skip)

    # --------------------------------------------- the escalation ladder
    def _handle_integrity(self, verdict: dict) -> None:
        """suspect -> deterministic replay -> convict -> quarantine+evict.

        ``verdict`` comes from :meth:`IntegrityMonitor.flush`. A first
        divergence arms the suspect and replays deterministically from
        the last consistent checkpoint (a transient flip will not recur
        — the poisoned steps are simply discarded with the rollback); a
        suspect that diverges AGAIN after its replay is convicted and the
        host is evicted through the elastic machinery."""
        rank, step_no = verdict.get("rank"), verdict["step"]
        warnings.warn(
            f"integrity: cross-replica fingerprint divergence at step "
            f"{step_no} (suspect rank: {rank}); escalating to "
            f"{verdict['action']}", RuntimeWarning)
        _tracing.record_event("train:integrity_mismatch", step=step_no,
                              rank=rank)
        _flight.note("integrity_mismatch", corr=_tracing.current(),
                     step=step_no, rank=rank, action=verdict["action"])
        if verdict["action"] == "convict" and rank is not None:
            self._convict(verdict)
        else:
            self._integrity_replay(verdict)

    def _integrity_replay(self, verdict: dict) -> None:
        from .. import profiler
        from ..observability.registry import default_registry
        from ..profiler import RecordEvent

        default_registry().inc("integrity.replay")
        profiler.bump_counter("train.integrity_replay")
        if self.hang is not None:
            self.hang.pause()
        self.rollbacks += 1
        profiler.bump_counter("train.rollback")
        if self.rollbacks > self.policy.max_rollbacks:
            raise FloatingPointError(
                f"integrity: {self.rollbacks} rollbacks exceeded "
                f"max_rollbacks={self.policy.max_rollbacks}; replicas "
                f"keep diverging without an attributable culprit")
        with RecordEvent("integrity_replay"):
            self.checkpoint.wait()
            cursor = self.restore()
        # the replay is bit-deterministic: the per-step RNG is
        # fold_in(base_key, count) and the restored cursor replays the
        # same batches — a transient flip cannot recur, a sticky one
        # diverges again and the armed suspect is convicted next flush.
        # (With no checkpoint yet, restore() leaves state in place: the
        # corruption persists and the sticky path convicts — by design.)
        print(f"[supervisor] integrity replay #{self.rollbacks}: suspect "
              f"rank {verdict.get('rank')} diverged at step "
              f"{verdict['step']}; replaying from "
              f"{'checkpoint' if cursor is not None else 'current position'}",
              flush=True)
        if self.on_rollback is not None:
            self.on_rollback({"rollbacks": self.rollbacks, "cursor": cursor,
                              "skip": [], "integrity": dict(verdict)})
        raise RollbackRequested(cursor, set())

    def _convict(self, verdict: dict) -> None:
        from .. import profiler
        from ..distributed.integrity import record_conviction
        from ..observability.registry import default_registry

        rank, step_no = int(verdict["rank"]), int(verdict["step"])
        default_registry().inc("integrity.evicted")
        profiler.bump_counter("train.integrity_evicted")
        record = {"rank": rank, "step": step_no,
                  "fingerprints": verdict.get("fingerprints"),
                  "time": time.time(), "pid": os.getpid()}
        # durable BEFORE the dump/raise: the record is what the next
        # incarnation reads to boot on surviving capacity
        path = record_conviction(self.checkpoint.root, record)
        _flight.dump("integrity_conviction", corr=_tracing.current(),
                     extra=record)
        print(f"[supervisor] integrity conviction: rank {rank} diverged "
              f"again after a deterministic replay (sticky fault); "
              f"quarantine recorded at {path} — evicting via elastic "
              f"restart", flush=True)
        raise HostEvictionRequested(rank, step_no, path)

    def _handle_preemption(self, cursor=None) -> None:
        from .. import profiler
        from ..profiler import RecordEvent

        profiler.bump_counter("train.preemption")
        _flight.dump("preemption", corr=_tracing.current(),
                     extra={"global_step": int(self.step._count)})
        if self.hang is not None:
            self.hang.pause()
        saved = False
        deadline = self.preempt.deadline
        if deadline is None or not deadline.expired():
            with RecordEvent("preempt_ckpt"):
                self.save_now(cursor)
                self.checkpoint.wait()
            saved = True
        if self.on_preemption is not None:
            self.on_preemption({"global_step": int(self.step._count),
                                "saved": saved})
        detail = ("state checkpointed" if saved
                  else "grace deadline expired, state NOT saved")
        raise TrainingPreempted(
            f"preemption notice honored at step {self.step._count} "
            f"({detail})", int(self.step._count), saved)


def _flatten_template(tree):
    """Flat ``{slash/key: leaf}`` + treedef of a state template (the
    checkpoint module's key layout)."""
    from ..distributed.checkpoint import _flatten

    return _flatten(tree)
