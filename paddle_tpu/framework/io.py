"""Serialization: ``paddle.save`` / ``paddle.load`` analogues.

Reference: ``python/paddle/framework/io.py:637,879`` — pickled nested
state_dicts. Same wire idea here: pytrees with jax arrays converted to numpy,
pickled. Distributed/sharded checkpointing (orbax-backed, the ``dist_saver``
analogue) lives in ``paddle_tpu.distributed.checkpoint``.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def _to_numpy_tree(obj: Any):
    def conv(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree.map(conv, obj)


def _to_jax_tree(obj: Any):
    def conv(x):
        if isinstance(x, np.ndarray):
            return jnp.asarray(x)
        return x

    return jax.tree.map(conv, obj)


def save(obj: Any, path: str, protocol: int = 4):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_numpy_tree(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if return_numpy:
        return obj
    return _to_jax_tree(obj)
