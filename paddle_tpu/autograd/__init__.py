"""``paddle.autograd`` facade.

Reference parity: ``python/paddle/autograd/__init__.py`` — ``PyLayer`` /
``PyLayerContext`` (``py_layer.py``), ``saved_tensors_hooks``
(``saved_tensors_hooks.py``), and ``backward`` (``backward_mode.py``).

TPU-native shape: the eager tape (``paddle_tpu.eager``) provides the
engine; this module re-exports its user-extension points under the
reference's import path. Functional transforms (jvp/vjp/Hessian, the
reference's ``incubate/autograd``) live in :mod:`paddle_tpu.incubate`.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..eager import (PyLayer, PyLayerContext, no_grad,  # noqa: F401
                     saved_tensors_hooks)
from ..eager import grad  # noqa: F401  (partial grad, dygraph/base.py:468)

__all__ = ["PyLayer", "PyLayerContext", "saved_tensors_hooks", "backward",
           "no_grad", "grad"]


def backward(tensors: Sequence, grad_tensors: Optional[Sequence] = None,
             retain_graph: bool = False) -> None:
    """Run backward from several roots in ONE joint pass (reference
    ``python/paddle/autograd/backward_mode.py`` ``backward``): all seeds
    are planted before traversal, so a tensor reachable from several roots
    sees its fully accumulated gradient (hooks fire once, vjps run once) —
    not the partial per-root gradients a sequential emulation would give."""
    import jax.numpy as jnp

    from ..eager import Tensor, run_backward

    tensors = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    grad_tensors = list(grad_tensors)
    if len(grad_tensors) != len(tensors):
        raise ValueError("grad_tensors must match tensors in length")
    roots = []
    for t, g in zip(tensors, grad_tensors):
        if not isinstance(t, Tensor):
            raise TypeError("backward() roots must be eager Tensors")
        if t._node is None and t.stop_gradient:
            raise RuntimeError("backward() on a tensor with no grad history")
        seed = (jnp.ones_like(t._data) if g is None
                else jnp.asarray(getattr(g, "_data", g)))
        roots.append((t, seed))
    run_backward(roots, retain_graph=retain_graph)
