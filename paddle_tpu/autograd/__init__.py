"""``paddle.autograd`` facade.

Reference parity: ``python/paddle/autograd/__init__.py`` — ``PyLayer`` /
``PyLayerContext`` (``py_layer.py``), ``saved_tensors_hooks``
(``saved_tensors_hooks.py``), and ``backward`` (``backward_mode.py``).

TPU-native shape: the eager tape (``paddle_tpu.eager``) provides the
engine; this module re-exports its user-extension points under the
reference's import path. Functional transforms (jvp/vjp/Hessian, the
reference's ``incubate/autograd``) live in :mod:`paddle_tpu.incubate`.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..eager import (PyLayer, PyLayerContext, no_grad,  # noqa: F401
                     saved_tensors_hooks)

__all__ = ["PyLayer", "PyLayerContext", "saved_tensors_hooks", "backward",
           "no_grad"]


def backward(tensors: Sequence, grad_tensors: Optional[Sequence] = None,
             retain_graph: bool = False) -> None:
    """Run backward from several roots at once (reference
    ``python/paddle/autograd/backward_mode.py`` ``backward``): seeds each
    root with the matching ``grad_tensors`` entry (ones if None) and
    accumulates into leaf ``.grad``/layer stores."""
    from ..eager import Tensor

    tensors = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    grad_tensors = list(grad_tensors)
    if len(grad_tensors) != len(tensors):
        raise ValueError("grad_tensors must match tensors in length")
    for i, (t, g) in enumerate(zip(tensors, grad_tensors)):
        if not isinstance(t, Tensor):
            raise TypeError("backward() roots must be eager Tensors")
        # all but the last root retain the graph: later roots may share it
        keep = retain_graph or i < len(tensors) - 1
        t.backward(grad_tensor=g, retain_graph=keep)
