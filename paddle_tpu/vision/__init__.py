"""paddle_tpu.vision — models, transforms, datasets.

Reference parity: ``python/paddle/vision/`` (``models`` ResNet/VGG/
MobileNet/LeNet..., ``transforms`` functional + compose pipeline,
``datasets``, ``ops`` detection/region ops). Models keep the reference's NCHW layout so ported
checkpoints line up name-for-name (XLA lowers NCHW convs onto the MXU
directly — see ``paddle_tpu.models.resnet``).
"""
from . import datasets, models, ops, transforms

__all__ = ["models", "transforms", "datasets", "ops"]
