"""paddle_tpu.vision — models, transforms, datasets.

Reference parity: ``python/paddle/vision/`` (``models`` ResNet/VGG/
MobileNet/LeNet..., ``transforms`` functional + compose pipeline,
``datasets``, ``ops`` detection/region ops). Models keep the reference's NCHW layout so ported
checkpoints line up name-for-name (XLA lowers NCHW convs onto the MXU
directly — see ``paddle_tpu.models.resnet``).
"""
from . import datasets, models, ops, transforms

__all__ = ["models", "transforms", "datasets", "ops",
           "get_image_backend", "set_image_backend", "image_load"]

_image_backend = "pil"


def set_image_backend(backend: str) -> None:
    """Reference ``paddle.vision.set_image_backend``: choose the decoder
    for ``image_load``. 'pil' and 'cv2' accepted; 'cv2' requires opencv
    (not in this image — errors at load time, not here, matching the
    reference's lazy check)."""
    if backend not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {backend!r}")
    global _image_backend
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path: str, backend: str = None):
    """Load an image via the configured backend (reference
    ``paddle.vision.image_load``)."""
    backend = backend or _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {backend!r}")
    if backend == "cv2":
        import cv2  # noqa: F401  (not shipped in this image)

        return cv2.imread(path)
    from PIL import Image

    return Image.open(path)
