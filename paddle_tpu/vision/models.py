"""Vision model zoo beyond ResNet: LeNet, VGG, MobileNetV1/V2/V3(small).

Reference parity: ``python/paddle/vision/models/{lenet,vgg,mobilenetv1,
mobilenetv2,mobilenetv3}.py``. Same layer graphs and naming style; NCHW.
ResNet family lives in ``paddle_tpu.models.resnet`` (re-exported here).
"""
from __future__ import annotations

from typing import List, Optional

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

from ..models.resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                             resnet152, resnext50_32x4d, wide_resnet50_2)

__all__ = [
    "LeNet", "VGG", "vgg11", "vgg13", "vgg16", "vgg19", "MobileNetV1",
    "MobileNetV2", "MobileNetV3Small", "mobilenet_v1", "mobilenet_v2",
    "mobilenet_v3_small", "ResNet", "resnet18", "resnet34", "resnet50",
    "resnet101", "resnet152", "wide_resnet50_2", "resnext50_32x4d",
]


class LeNet(nn.Layer):
    """``paddle.vision.models.LeNet`` (28x28 single-channel input)."""

    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120), nn.Linear(120, 84),
                nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.fc(x)
        return x


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512,
          512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


def _vgg_features(cfg: List, batch_norm: bool) -> nn.Sequential:
    layers = []
    c_in = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(c_in, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            c_in = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    """``paddle.vision.models.VGG`` (global 7x7 pool + 3 FC head)."""

    def __init__(self, features: nn.Layer, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))
        self.num_classes = num_classes

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.classifier(x)
        return x


def _vgg(cfg: str, batch_norm=False, **kw):
    return VGG(_vgg_features(_VGG_CFGS[cfg], batch_norm), **kw)


def vgg11(pretrained=False, batch_norm=False, **kw):
    return _vgg("A", batch_norm, **kw)


def vgg13(pretrained=False, batch_norm=False, **kw):
    return _vgg("B", batch_norm, **kw)


def vgg16(pretrained=False, batch_norm=False, **kw):
    return _vgg("D", batch_norm, **kw)


def vgg19(pretrained=False, batch_norm=False, **kw):
    return _vgg("E", batch_norm, **kw)


class _ConvBNReLU(nn.Sequential):
    def __init__(self, c_in, c_out, k, stride=1, groups=1, act=nn.ReLU):
        pad = (k - 1) // 2
        layers = [nn.Conv2D(c_in, c_out, k, stride=stride, padding=pad,
                            groups=groups, bias_attr=False),
                  nn.BatchNorm2D(c_out)]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


class MobileNetV1(nn.Layer):
    """Depthwise-separable stack (``mobilenetv1.py``)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [  # (out, stride) for each depthwise separable block
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
            (1024, 1),
        ]
        layers = [_ConvBNReLU(3, c(32), 3, stride=2)]
        c_in = c(32)
        for out, stride in cfg:
            layers.append(_ConvBNReLU(c_in, c_in, 3, stride=stride,
                                      groups=c_in))     # depthwise
            layers.append(_ConvBNReLU(c_in, c(out), 1))  # pointwise
            c_in = c(out)
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.fc(x)
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, c_in, c_out, stride, expand_ratio):
        super().__init__()
        hidden = int(round(c_in * expand_ratio))
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(c_in, hidden, 1, act=nn.ReLU6))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden,
                        act=nn.ReLU6),
            nn.Conv2D(hidden, c_out, 1, bias_attr=False),
            nn.BatchNorm2D(c_out),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """``mobilenetv2.py`` inverted-residual network."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]

        def c(ch):
            return max(int(ch * scale), 8)

        layers = [_ConvBNReLU(3, c(32), 3, stride=2, act=nn.ReLU6)]
        c_in = c(32)
        for t, ch, n, s in cfg:
            for i in range(n):
                layers.append(InvertedResidual(c_in, c(ch),
                                               s if i == 0 else 1, t))
                c_in = c(ch)
        out_c = max(int(1280 * scale), 1280) if scale > 1.0 else 1280
        layers.append(_ConvBNReLU(c_in, out_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(out_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.classifier(x)
        return x


class _SEBlock(nn.Layer):
    def __init__(self, ch, reduction=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, ch // reduction, 1)
        self.fc2 = nn.Conv2D(ch // reduction, ch, 1)

    def forward(self, x):
        s = self.pool(x)
        s = F.relu(self.fc1(s))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, c_in, hidden, c_out, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if hidden != c_in:
            layers.append(_ConvBNReLU(c_in, hidden, 1, act=act))
        layers.append(_ConvBNReLU(hidden, hidden, k, stride=stride,
                                  groups=hidden, act=act))
        if use_se:
            layers.append(_SEBlock(hidden))
        layers += [nn.Conv2D(hidden, c_out, 1, bias_attr=False),
                   nn.BatchNorm2D(c_out)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV3Small(nn.Layer):
    """``mobilenetv3.py`` small variant (hardswish + SE blocks)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        HS, RE = nn.Hardswish, nn.ReLU
        cfg = [  # k, hidden, out, se, act, stride
            (3, 16, 16, True, RE, 2), (3, 72, 24, False, RE, 2),
            (3, 88, 24, False, RE, 1), (5, 96, 40, True, HS, 2),
            (5, 240, 40, True, HS, 1), (5, 240, 40, True, HS, 1),
            (5, 120, 48, True, HS, 1), (5, 144, 48, True, HS, 1),
            (5, 288, 96, True, HS, 2), (5, 576, 96, True, HS, 1),
            (5, 576, 96, True, HS, 1),
        ]

        def c(ch):
            return max(int(ch * scale), 8)

        layers = [_ConvBNReLU(3, c(16), 3, stride=2, act=HS)]
        c_in = c(16)
        for k, hidden, out, se, act, s in cfg:
            layers.append(_MBV3Block(c_in, c(hidden), c(out), k, s, se, act))
            c_in = c(out)
        layers.append(_ConvBNReLU(c_in, c(576), 1, act=HS))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(c(576), 1024), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.classifier(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


def mobilenet_v2(pretrained=False, scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Small(scale=scale, **kw)
