"""Vision model zoo beyond ResNet: LeNet, VGG, MobileNetV1/V2/V3(small).

Reference parity: ``python/paddle/vision/models/{lenet,vgg,mobilenetv1,
mobilenetv2,mobilenetv3}.py``. Same layer graphs and naming style; NCHW.
ResNet family lives in ``paddle_tpu.models.resnet`` (re-exported here).
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

from ..models.resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                             resnet152, resnext50_32x4d, resnext50_64x4d,
                             resnext101_32x4d, resnext101_64x4d,
                             resnext152_32x4d, resnext152_64x4d,
                             wide_resnet50_2, wide_resnet101_2)

__all__ = [
    "LeNet", "VGG", "vgg11", "vgg13", "vgg16", "vgg19", "MobileNetV1",
    "MobileNetV2", "MobileNetV3Small", "MobileNetV3Large", "mobilenet_v1",
    "mobilenet_v2", "mobilenet_v3_small", "mobilenet_v3_large", "ResNet",
    "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "wide_resnet50_2", "wide_resnet101_2", "resnext50_32x4d",
    "resnext50_64x4d", "resnext101_32x4d", "resnext101_64x4d",
    "resnext152_32x4d", "resnext152_64x4d", "AlexNet", "alexnet",
    "SqueezeNet", "squeezenet1_0", "squeezenet1_1", "ShuffleNetV2",
    "shufflenet_v2_x0_25", "shufflenet_v2_x0_33", "shufflenet_v2_x0_5",
    "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
    "shufflenet_v2_swish", "DenseNet", "densenet121", "densenet161",
    "densenet169", "densenet201", "densenet264", "GoogLeNet", "googlenet",
    "InceptionV3", "inception_v3",
]


def _finish(model, arch, pretrained):
    """Shared ``pretrained=True`` tail of every constructor: fetch the
    published paddle checkpoint for ``arch`` and load it (reference:
    each model file's ``get_weights_path_from_url`` + ``load_dict`` branch,
    e.g. ``python/paddle/vision/models/resnet.py:356-363``)."""
    if pretrained:
        from ..hapi.weights import load_pretrained

        load_pretrained(model, arch)
    return model


class LeNet(nn.Layer):
    """``paddle.vision.models.LeNet`` (28x28 single-channel input)."""

    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120), nn.Linear(120, 84),
                nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.fc(x)
        return x


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512,
          512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


def _vgg_features(cfg: List, batch_norm: bool) -> nn.Sequential:
    layers = []
    c_in = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(c_in, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            c_in = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    """``paddle.vision.models.VGG`` (global 7x7 pool + 3 FC head)."""

    def __init__(self, features: nn.Layer, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))
        self.num_classes = num_classes

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.classifier(x)
        return x


def _vgg(cfg: str, batch_norm=False, **kw):
    return VGG(_vgg_features(_VGG_CFGS[cfg], batch_norm), **kw)


def vgg11(pretrained=False, batch_norm=False, **kw):
    return _finish(_vgg("A", batch_norm, **kw), "vgg11", pretrained)


def vgg13(pretrained=False, batch_norm=False, **kw):
    return _finish(_vgg("B", batch_norm, **kw), "vgg13", pretrained)


def vgg16(pretrained=False, batch_norm=False, **kw):
    return _finish(_vgg("D", batch_norm, **kw), "vgg16", pretrained)


def vgg19(pretrained=False, batch_norm=False, **kw):
    return _finish(_vgg("E", batch_norm, **kw), "vgg19", pretrained)


class _ConvBNReLU(nn.Sequential):
    def __init__(self, c_in, c_out, k, stride=1, groups=1, act=nn.ReLU):
        pad = (k - 1) // 2
        layers = [nn.Conv2D(c_in, c_out, k, stride=stride, padding=pad,
                            groups=groups, bias_attr=False),
                  nn.BatchNorm2D(c_out)]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


class MobileNetV1(nn.Layer):
    """Depthwise-separable stack (``mobilenetv1.py``)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [  # (out, stride) for each depthwise separable block
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
            (1024, 1),
        ]
        layers = [_ConvBNReLU(3, c(32), 3, stride=2)]
        c_in = c(32)
        for out, stride in cfg:
            layers.append(_ConvBNReLU(c_in, c_in, 3, stride=stride,
                                      groups=c_in))     # depthwise
            layers.append(_ConvBNReLU(c_in, c(out), 1))  # pointwise
            c_in = c(out)
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.fc(x)
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, c_in, c_out, stride, expand_ratio):
        super().__init__()
        hidden = int(round(c_in * expand_ratio))
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(c_in, hidden, 1, act=nn.ReLU6))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden,
                        act=nn.ReLU6),
            nn.Conv2D(hidden, c_out, 1, bias_attr=False),
            nn.BatchNorm2D(c_out),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """``mobilenetv2.py`` inverted-residual network."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]

        def c(ch):
            return max(int(ch * scale), 8)

        layers = [_ConvBNReLU(3, c(32), 3, stride=2, act=nn.ReLU6)]
        c_in = c(32)
        for t, ch, n, s in cfg:
            for i in range(n):
                layers.append(InvertedResidual(c_in, c(ch),
                                               s if i == 0 else 1, t))
                c_in = c(ch)
        out_c = max(int(1280 * scale), 1280) if scale > 1.0 else 1280
        layers.append(_ConvBNReLU(c_in, out_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(out_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.classifier(x)
        return x


class _SEBlock(nn.Layer):
    def __init__(self, ch, reduction=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, ch // reduction, 1)
        self.fc2 = nn.Conv2D(ch // reduction, ch, 1)

    def forward(self, x):
        s = self.pool(x)
        s = F.relu(self.fc1(s))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, c_in, hidden, c_out, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if hidden != c_in:
            layers.append(_ConvBNReLU(c_in, hidden, 1, act=act))
        layers.append(_ConvBNReLU(hidden, hidden, k, stride=stride,
                                  groups=hidden, act=act))
        if use_se:
            layers.append(_SEBlock(hidden))
        layers += [nn.Conv2D(hidden, c_out, 1, bias_attr=False),
                   nn.BatchNorm2D(c_out)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV3Small(nn.Layer):
    """``mobilenetv3.py`` small variant (hardswish + SE blocks)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        HS, RE = nn.Hardswish, nn.ReLU
        cfg = [  # k, hidden, out, se, act, stride
            (3, 16, 16, True, RE, 2), (3, 72, 24, False, RE, 2),
            (3, 88, 24, False, RE, 1), (5, 96, 40, True, HS, 2),
            (5, 240, 40, True, HS, 1), (5, 240, 40, True, HS, 1),
            (5, 120, 48, True, HS, 1), (5, 144, 48, True, HS, 1),
            (5, 288, 96, True, HS, 2), (5, 576, 96, True, HS, 1),
            (5, 576, 96, True, HS, 1),
        ]

        def c(ch):
            return max(int(ch * scale), 8)

        layers = [_ConvBNReLU(3, c(16), 3, stride=2, act=HS)]
        c_in = c(16)
        for k, hidden, out, se, act, s in cfg:
            layers.append(_MBV3Block(c_in, c(hidden), c(out), k, s, se, act))
            c_in = c(out)
        layers.append(_ConvBNReLU(c_in, c(576), 1, act=HS))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(c(576), 1024), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.classifier(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return _finish(MobileNetV1(scale=scale, **kw),
                   f"mobilenetv1_{scale}", pretrained)


def mobilenet_v2(pretrained=False, scale=1.0, **kw):
    return _finish(MobileNetV2(scale=scale, **kw),
                   f"mobilenetv2_{scale}", pretrained)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    return _finish(MobileNetV3Small(scale=scale, **kw),
                   f"mobilenet_v3_small_x{scale}", pretrained)


# ------------------------------------------------- r4: remaining families
class MobileNetV3Large(nn.Layer):
    """``mobilenetv3.py`` large variant (same block algebra as Small)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        HS, RE = nn.Hardswish, nn.ReLU
        cfg = [  # k, hidden, out, se, act, stride
            (3, 16, 16, False, RE, 1), (3, 64, 24, False, RE, 2),
            (3, 72, 24, False, RE, 1), (5, 72, 40, True, RE, 2),
            (5, 120, 40, True, RE, 1), (5, 120, 40, True, RE, 1),
            (3, 240, 80, False, HS, 2), (3, 200, 80, False, HS, 1),
            (3, 184, 80, False, HS, 1), (3, 184, 80, False, HS, 1),
            (3, 480, 112, True, HS, 1), (3, 672, 112, True, HS, 1),
            (5, 672, 160, True, HS, 2), (5, 960, 160, True, HS, 1),
            (5, 960, 160, True, HS, 1),
        ]

        def c(ch):
            return max(int(ch * scale), 8)

        layers = [_ConvBNReLU(3, c(16), 3, stride=2, act=HS)]
        c_in = c(16)
        for k, hidden, out, se, act, s in cfg:
            layers.append(_MBV3Block(c_in, c(hidden), c(out), k, s, se, act))
            c_in = c(out)
        layers.append(_ConvBNReLU(c_in, c(960), 1, act=HS))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(c(960), 1280), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(1280, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.classifier(x)
        return x


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    return _finish(MobileNetV3Large(scale=scale, **kw),
                   f"mobilenet_v3_large_x{scale}", pretrained)


class AlexNet(nn.Layer):
    """``alexnet.py``: the 2012 5-conv/3-fc classifier."""

    def __init__(self, num_classes: int = 1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2))
        self.pool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Linear(256 * 36, 4096), nn.ReLU(),
                nn.Dropout(0.5), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        x = x.reshape((x.shape[0], -1))
        return self.classifier(x) if self.num_classes > 0 else x


def alexnet(pretrained=False, **kw):
    return _finish(AlexNet(**kw), "alexnet", pretrained)


class _Fire(nn.Layer):
    def __init__(self, c_in, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(c_in, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        s = F.relu(self.squeeze(x))
        return jnp.concatenate(
            [F.relu(self.expand1(s)), F.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    """``squeezenet.py``: Fire modules, versions "1.0"/"1.1"."""

    def __init__(self, version: str = "1.0", num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        if version not in ("1.0", "1.1"):
            raise ValueError(f"version must be '1.0' or '1.1', "
                             f"got {version!r}")
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.head = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.head(x)
        if self.with_pool:
            x = self.pool(x)
            if self.num_classes > 0:
                x = x.reshape((x.shape[0], -1))
        return x


def squeezenet1_0(pretrained=False, **kw):
    return _finish(SqueezeNet("1.0", **kw), "squeezenet1_0", pretrained)


def squeezenet1_1(pretrained=False, **kw):
    return _finish(SqueezeNet("1.1", **kw), "squeezenet1_1", pretrained)


class _ShuffleUnit(nn.Layer):
    """ShuffleNetV2 unit: channel split + shuffle (rides
    F.channel_shuffle)."""

    def __init__(self, c_in, c_out, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = c_out // 2
        self.act = F.swish if act == "swish" else F.relu
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(c_in, c_in, 3, stride=stride, padding=1,
                          groups=c_in, bias_attr=False),
                nn.BatchNorm2D(c_in),
                nn.Conv2D(c_in, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch))
            b2_in = c_in
        else:
            self.branch1 = None
            b2_in = c_in // 2
        # reference InvertedResidual: act after the FIRST pointwise conv
        # and after the LAST; the depthwise conv stays linear
        self.pw1 = nn.Sequential(
            nn.Conv2D(b2_in, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch))
        self.dw = nn.Sequential(
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch))
        self.pw2 = nn.Sequential(
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch))

    def _branch2(self, x):
        return self.act(self.pw2(self.dw(self.act(self.pw1(x)))))

    def forward(self, x):
        if self.stride > 1:
            out = jnp.concatenate(
                [self.act(self.branch1(x)), self._branch2(x)], axis=1)
        else:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = jnp.concatenate([x1, self._branch2(x2)], axis=1)
        return F.channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    """``shufflenetv2.py``: scale in {0.25,0.33,0.5,1.0,1.5,2.0}, optional
    swish activation."""

    _stage_out = {0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
                  0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
                  1.5: [24, 176, 352, 704, 1024],
                  2.0: [24, 244, 488, 976, 2048]}

    def __init__(self, scale: float = 1.0, act: str = "relu",
                 num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        outs = self._stage_out[scale]
        self.stem = nn.Sequential(
            nn.Conv2D(3, outs[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(outs[0]), nn.ReLU(), nn.MaxPool2D(3, stride=2,
                                                             padding=1))
        stages = []
        c_in = outs[0]
        for stage_i, repeat in enumerate((4, 8, 4)):
            c_out = outs[stage_i + 1]
            stages.append(_ShuffleUnit(c_in, c_out, 2, act))
            for _ in range(repeat - 1):
                stages.append(_ShuffleUnit(c_out, c_out, 1, act))
            c_in = c_out
        self.stages = nn.Sequential(*stages)
        self.final = nn.Sequential(
            nn.Conv2D(c_in, outs[-1], 1, bias_attr=False),
            nn.BatchNorm2D(outs[-1]), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(outs[-1], num_classes)

    def forward(self, x):
        x = self.final(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape((x.shape[0], -1)))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return _finish(ShuffleNetV2(0.25, **kw),
                   "shufflenet_v2_x0_25", pretrained)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return _finish(ShuffleNetV2(0.33, **kw),
                   "shufflenet_v2_x0_33", pretrained)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return _finish(ShuffleNetV2(0.5, **kw),
                   "shufflenet_v2_x0_5", pretrained)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return _finish(ShuffleNetV2(1.0, **kw),
                   "shufflenet_v2_x1_0", pretrained)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return _finish(ShuffleNetV2(1.5, **kw),
                   "shufflenet_v2_x1_5", pretrained)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return _finish(ShuffleNetV2(2.0, **kw),
                   "shufflenet_v2_x2_0", pretrained)


def shufflenet_v2_swish(pretrained=False, **kw):
    return _finish(ShuffleNetV2(1.0, act="swish", **kw),
                   "shufflenet_v2_swish", pretrained)


class _DenseLayer(nn.Layer):
    def __init__(self, c_in, growth, bn_size):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(c_in)
        self.conv1 = nn.Conv2D(c_in, bn_size * growth, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)

    def forward(self, x):
        out = self.conv1(F.relu(self.bn1(x)))
        out = self.conv2(F.relu(self.bn2(out)))
        return jnp.concatenate([x, out], axis=1)


class DenseNet(nn.Layer):
    """``densenet.py``: dense blocks with concat growth; layers in
    {121, 161, 169, 201, 264}."""

    _cfgs = {121: (32, (6, 12, 24, 16), 64),
             161: (48, (6, 12, 36, 24), 96),
             169: (32, (6, 12, 32, 32), 64),
             201: (32, (6, 12, 48, 32), 64),
             264: (32, (6, 12, 64, 48), 64)}

    def __init__(self, layers: int = 121, bn_size: int = 4,
                 num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        growth, blocks, init_c = self._cfgs[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        ch = init_c
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size))
                ch += growth
            if i != len(blocks) - 1:  # transition: halve channels + pool
                feats += [nn.BatchNorm2D(ch), nn.ReLU(),
                          nn.Conv2D(ch, ch // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, stride=2)]
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self._out_ch = ch
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.reshape((x.shape[0], -1)))
        return x


def densenet121(pretrained=False, **kw):
    return _finish(DenseNet(121, **kw), "densenet121", pretrained)


def densenet161(pretrained=False, **kw):
    return _finish(DenseNet(161, **kw), "densenet161", pretrained)


def densenet169(pretrained=False, **kw):
    return _finish(DenseNet(169, **kw), "densenet169", pretrained)


def densenet201(pretrained=False, **kw):
    return _finish(DenseNet(201, **kw), "densenet201", pretrained)


def densenet264(pretrained=False, **kw):
    return _finish(DenseNet(264, **kw), "densenet264", pretrained)


class _Inception(nn.Layer):
    """GoogLeNet inception module (1x1 / 3x3 / 5x5 / pool branches)."""

    def __init__(self, c_in, c1, r3, c3, r5, c5, cp):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(c_in, c1, 1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(c_in, r3, 1), nn.ReLU(),
                                nn.Conv2D(r3, c3, 3, padding=1), nn.ReLU())
        self.b5 = nn.Sequential(nn.Conv2D(c_in, r5, 1), nn.ReLU(),
                                nn.Conv2D(r5, c5, 5, padding=2), nn.ReLU())
        self.bp = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                nn.Conv2D(c_in, cp, 1), nn.ReLU())

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b3(x), self.b5(x), self.bp(x)], axis=1)


class GoogLeNet(nn.Layer):
    """``googlenet.py`` (inception v1). ``forward`` returns the main
    logits (the reference also returns two aux heads during training;
    deep supervision belongs to the recipe, main head carries serving)."""

    def __init__(self, num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.blocks = nn.Sequential(
            _Inception(192, 64, 96, 128, 16, 32, 32),
            _Inception(256, 128, 128, 192, 32, 96, 64),
            nn.MaxPool2D(3, stride=2, padding=1),
            _Inception(480, 192, 96, 208, 16, 48, 64),
            _Inception(512, 160, 112, 224, 24, 64, 64),
            _Inception(512, 128, 128, 256, 24, 64, 64),
            _Inception(512, 112, 144, 288, 32, 64, 64),
            _Inception(528, 256, 160, 320, 32, 128, 128),
            nn.MaxPool2D(3, stride=2, padding=1),
            _Inception(832, 256, 160, 320, 32, 128, 128),
            _Inception(832, 384, 192, 384, 48, 128, 128))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.head = nn.Sequential(nn.Dropout(0.4),
                                      nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.head(x.reshape((x.shape[0], -1)))
        return x


def googlenet(pretrained=False, **kw):
    return _finish(GoogLeNet(**kw), "googlenet", pretrained)


class _BasicConv(nn.Sequential):
    def __init__(self, ci, co, k, s=1, p=0):
        super().__init__(
            nn.Conv2D(ci, co, k, stride=s, padding=p, bias_attr=False),
            nn.BatchNorm2D(co), nn.ReLU())


class _InceptionA(nn.Layer):
    """35x35 cell: 1x1 / 5x5 / double-3x3 / pool -> 224 + pool_ch."""

    def __init__(self, c_in, pool_ch):
        super().__init__()
        self.b1 = _BasicConv(c_in, 64, 1)
        self.b5 = nn.Sequential(_BasicConv(c_in, 48, 1),
                                _BasicConv(48, 64, 5, p=2))
        self.b3d = nn.Sequential(_BasicConv(c_in, 64, 1),
                                 _BasicConv(64, 96, 3, p=1),
                                 _BasicConv(96, 96, 3, p=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _BasicConv(c_in, pool_ch, 1))

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b5(x), self.b3d(x), self.bp(x)], axis=1)


class _ReductionA(nn.Layer):
    """35 -> 17: stride-2 3x3 / stride-2 double-3x3 / maxpool."""

    def __init__(self, c_in):
        super().__init__()
        self.b3 = _BasicConv(c_in, 384, 3, s=2)
        self.b3d = nn.Sequential(_BasicConv(c_in, 64, 1),
                                 _BasicConv(64, 96, 3, p=1),
                                 _BasicConv(96, 96, 3, s=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return jnp.concatenate(
            [self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _InceptionB(nn.Layer):
    """17x17 cell with 1x7/7x1 factorized branches -> 768."""

    def __init__(self, c_in, mid):
        super().__init__()
        self.b1 = _BasicConv(c_in, 192, 1)
        self.b7 = nn.Sequential(
            _BasicConv(c_in, mid, 1),
            _BasicConv(mid, mid, (1, 7), p=(0, 3)),
            _BasicConv(mid, 192, (7, 1), p=(3, 0)))
        self.b7d = nn.Sequential(
            _BasicConv(c_in, mid, 1),
            _BasicConv(mid, mid, (7, 1), p=(3, 0)),
            _BasicConv(mid, mid, (1, 7), p=(0, 3)),
            _BasicConv(mid, mid, (7, 1), p=(3, 0)),
            _BasicConv(mid, 192, (1, 7), p=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _BasicConv(c_in, 192, 1))

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], axis=1)


class _ReductionB(nn.Layer):
    """17 -> 8: 1280 out."""

    def __init__(self, c_in):
        super().__init__()
        self.b3 = nn.Sequential(_BasicConv(c_in, 192, 1),
                                _BasicConv(192, 320, 3, s=2))
        self.b7 = nn.Sequential(
            _BasicConv(c_in, 192, 1),
            _BasicConv(192, 192, (1, 7), p=(0, 3)),
            _BasicConv(192, 192, (7, 1), p=(3, 0)),
            _BasicConv(192, 192, 3, s=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return jnp.concatenate(
            [self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    """8x8 cell with expanded 1x3/3x1 splits -> 2048."""

    def __init__(self, c_in):
        super().__init__()
        self.b1 = _BasicConv(c_in, 320, 1)
        self.b3_stem = _BasicConv(c_in, 384, 1)
        self.b3_a = _BasicConv(384, 384, (1, 3), p=(0, 1))
        self.b3_b = _BasicConv(384, 384, (3, 1), p=(1, 0))
        self.b3d_stem = nn.Sequential(_BasicConv(c_in, 448, 1),
                                      _BasicConv(448, 384, 3, p=1))
        self.b3d_a = _BasicConv(384, 384, (1, 3), p=(0, 1))
        self.b3d_b = _BasicConv(384, 384, (3, 1), p=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _BasicConv(c_in, 192, 1))

    def forward(self, x):
        s3 = self.b3_stem(x)
        s3d = self.b3d_stem(x)
        return jnp.concatenate(
            [self.b1(x), self.b3_a(s3), self.b3_b(s3),
             self.b3d_a(s3d), self.b3d_b(s3d), self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    """``inceptionv3.py``: the full v3 block plan — 3x InceptionA (5x5 +
    double-3x3 branches), ReductionA, 4x InceptionB (7x7 factorized as
    1x7/7x1), ReductionB, 2x InceptionC (expanded 1x3/3x1 splits), 2048
    final channels. Aux head omitted (training-recipe deep supervision;
    the serving graph is the main head)."""

    def __init__(self, num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 32, 3, s=2), _BasicConv(32, 32, 3),
            _BasicConv(32, 64, 3, p=1), nn.MaxPool2D(3, stride=2),
            _BasicConv(64, 80, 1), _BasicConv(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32),    # -> 256
            _InceptionA(256, 64),    # -> 288
            _InceptionA(288, 64),    # -> 288
            _ReductionA(288),        # -> 768
            _InceptionB(768, 128),   # -> 768
            _InceptionB(768, 160),
            _InceptionB(768, 160),
            _InceptionB(768, 192),
            _ReductionB(768),        # -> 1280
            _InceptionC(1280),       # -> 2048
            _InceptionC(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Sequential(nn.Dropout(0.5),
                                    nn.Linear(2048, num_classes))

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape((x.shape[0], -1)))
        return x


def inception_v3(pretrained=False, **kw):
    return _finish(InceptionV3(**kw), "inception_v3", pretrained)
