"""Detection / region ops.

Reference parity: ``python/paddle/vision/ops.py`` (nms, box_coder,
yolo_box, prior_box, roi_align, roi_pool, psroi_pool, deform_conv2d,
read_file/decode_jpeg). TPU-native notes: the box math is pure jnp (XLA
fuses it); the region poolers are gather+interpolation formulations (no
scatter-heavy CUDA kernels to port); nms returns a dynamic-length index
set, so it computes through jnp and materializes eagerly — inside jit use
the fixed-shape ``nms_mask`` flavor.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["nms", "nms_mask", "box_coder", "yolo_box", "prior_box",
           "roi_align", "roi_pool", "psroi_pool", "deform_conv2d",
           "read_file", "decode_jpeg", "sequence_mask"]


def _pairwise_iou(boxes):
    """IoU matrix for [N, 4] (x1, y1, x2, y2) boxes."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms_mask(boxes, scores, iou_threshold: float = 0.3):
    """Fixed-shape NMS: boolean keep-mask in SCORE order is computed with a
    ``fori_loop`` greedy sweep — jit-safe (use this inside compiled code)."""
    boxes = jnp.asarray(boxes, jnp.float32)
    scores = jnp.asarray(scores, jnp.float32)
    order = jnp.argsort(-scores)
    iou = _pairwise_iou(boxes[order])
    n = boxes.shape[0]

    def body(i, keep):
        # suppressed if overlapping any higher-scoring KEPT box
        over = (iou[:, i] > iou_threshold) & keep & (jnp.arange(n) < i)
        return keep.at[i].set(~jnp.any(over))

    keep_sorted = lax.fori_loop(0, n, body, jnp.ones(n, bool))
    # back to original indexing
    keep = jnp.zeros(n, bool).at[order].set(keep_sorted)
    return keep


def nms(boxes, iou_threshold: float = 0.3, scores=None, category_idxs=None,
        categories=None, top_k: Optional[int] = None, name=None):
    """Greedy NMS returning kept indices sorted by descending score
    (reference ``nms``). Dynamic-length output -> eager; supports the
    reference's categorical batched mode (suppression only within a
    category) and ``top_k``."""
    boxes = jnp.asarray(boxes, jnp.float32)
    n = boxes.shape[0]
    if scores is None:
        scores = jnp.arange(n, 0, -1, dtype=jnp.float32)  # input order
    scores = jnp.asarray(scores, jnp.float32)
    if category_idxs is not None:
        # offset boxes per category so cross-category IoU is 0 (the
        # standard batched-nms trick)
        cat = jnp.asarray(category_idxs)
        offset = (cat.astype(jnp.float32) *
                  (jnp.max(boxes) - jnp.min(boxes) + 1.0))[:, None]
        keep = nms_mask(boxes + offset, scores, iou_threshold)
    else:
        keep = nms_mask(boxes, scores, iou_threshold)
    idx = np.where(np.asarray(keep))[0]
    idx = idx[np.argsort(-np.asarray(scores)[idx], kind="stable")]
    if top_k is not None:
        idx = idx[:top_k]
    return jnp.asarray(idx)  # default int dtype (x64 is globally off)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size", box_normalized: bool = True,
              axis: int = 0, name=None):
    """Encode/decode boxes against priors (reference ``box_coder``)."""
    pb = jnp.asarray(prior_box, jnp.float32)
    tb = jnp.asarray(target_box, jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pb_w = pb[:, 2] - pb[:, 0] + norm
    pb_h = pb[:, 3] - pb[:, 1] + norm
    pb_x = pb[:, 0] + pb_w * 0.5
    pb_y = pb[:, 1] + pb_h * 0.5
    if prior_box_var is None:
        var = jnp.ones((1, 4), jnp.float32)
    else:
        var = jnp.asarray(prior_box_var, jnp.float32)
        if var.ndim == 1:
            var = var[None, :]
    if code_type == "encode_center_size":
        tb_w = tb[:, 2] - tb[:, 0] + norm
        tb_h = tb[:, 3] - tb[:, 1] + norm
        tb_x = tb[:, 0] + tb_w * 0.5
        tb_y = tb[:, 1] + tb_h * 0.5
        # [M priors, N targets] broadcast: reference encodes every target
        # against every prior -> [N, M, 4]
        out = jnp.stack([
            (tb_x[:, None] - pb_x[None, :]) / pb_w[None, :],
            (tb_y[:, None] - pb_y[None, :]) / pb_h[None, :],
            jnp.log(jnp.abs(tb_w[:, None] / pb_w[None, :])),
            jnp.log(jnp.abs(tb_h[:, None] / pb_h[None, :])),
        ], axis=-1)
        return out / var[None, :, :]
    if code_type == "decode_center_size":
        # tb: [N, M, 4] codes; priors broadcast along `axis`
        exp = (None, slice(None)) if axis == 0 else (slice(None), None)
        pbx, pby = pb_x[exp], pb_y[exp]
        pbw, pbh = pb_w[exp], pb_h[exp]
        v = var[exp[0], exp[1], :] if var.shape[0] > 1 else var[None, :, :]
        tx = tb[..., 0] * v[..., 0] * pbw + pbx
        ty = tb[..., 1] * v[..., 1] * pbh + pby
        tw = jnp.exp(v[..., 2] * tb[..., 2]) * pbw
        th = jnp.exp(v[..., 3] * tb[..., 3]) * pbh
        return jnp.stack([tx - tw / 2, ty - th / 2,
                          tx + tw / 2 - norm, ty + th / 2 - norm], axis=-1)
    raise ValueError(f"unknown code_type {code_type!r}")


def yolo_box(x, img_size, anchors: Sequence[int], class_num: int,
             conf_thresh: float, downsample_ratio: int, clip_bbox: bool = True,
             scale_x_y: float = 1.0, iou_aware: bool = False,
             iou_aware_factor: float = 0.5, name=None):
    """Decode a YOLOv3 head into boxes + scores (reference ``yolo_box``).
    x: [N, C, H, W] with C = num_anchors * (5 + class_num)."""
    if iou_aware:
        raise NotImplementedError(
            "yolo_box iou_aware=False only (PP-YOLO's iou-aware channel "
            "layout is not implemented)")
    x = jnp.asarray(x, jnp.float32)
    n, c, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[:, None]
    bx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) / 2 + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) / 2 + grid_y) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    conf = jnp.where(conf < conf_thresh, 0.0, conf)
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    img = jnp.asarray(img_size, jnp.float32).reshape(n, 2)  # [h, w]
    ih, iw = img[:, 0], img[:, 1]
    x1 = (bx - bw / 2) * iw[:, None, None, None]
    y1 = (by - bh / 2) * ih[:, None, None, None]
    x2 = (bx + bw / 2) * iw[:, None, None, None]
    y2 = (by + bh / 2) * ih[:, None, None, None]
    if clip_bbox:
        x1 = jnp.clip(x1, 0, iw[:, None, None, None] - 1)
        y1 = jnp.clip(y1, 0, ih[:, None, None, None] - 1)
        x2 = jnp.clip(x2, 0, iw[:, None, None, None] - 1)
        y2 = jnp.clip(y2, 0, ih[:, None, None, None] - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
    scores = jnp.moveaxis(probs, 2, -1).reshape(n, -1, class_num)
    return boxes, scores


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip: bool = False,
              clip: bool = False, steps=(0.0, 0.0), offset: float = 0.5,
              min_max_aspect_ratios_order: bool = False, name=None):
    """SSD prior (anchor) boxes per feature-map cell (reference
    ``prior_box``)."""
    feat_h, feat_w = jnp.asarray(input).shape[2:]
    img_h, img_w = jnp.asarray(image).shape[2:]
    step_w = steps[0] or img_w / feat_w
    step_h = steps[1] or img_h / feat_h
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    whs = []
    for ms in min_sizes:
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((float(np.sqrt(ms * mx)),) * 2)
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((float(np.sqrt(ms * mx)),) * 2)
    whs = jnp.asarray(whs, jnp.float32)  # [P, 2]
    cx = (jnp.arange(feat_w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(feat_h, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    boxes = jnp.stack([
        (cxg[..., None] - whs[None, None, :, 0] / 2) / img_w,
        (cyg[..., None] - whs[None, None, :, 1] / 2) / img_h,
        (cxg[..., None] + whs[None, None, :, 0] / 2) / img_w,
        (cyg[..., None] + whs[None, None, :, 1] / 2) / img_h,
    ], axis=-1)  # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           boxes.shape)
    return boxes, var


def _bilinear(x, ys, xs):
    """Sample x [C, H, W] at float coords (ys, xs) [...]: bilinear, zero
    padded outside."""
    c, h, w = x.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0

    def at(yi, xi):
        valid = (yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)
        yi = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        return x[:, yi, xi] * valid.astype(x.dtype)

    return (at(y0, x0) * (1 - wy1) * (1 - wx1) +
            at(y0, x0 + 1) * (1 - wy1) * wx1 +
            at(y0 + 1, x0) * wy1 * (1 - wx1) +
            at(y0 + 1, x0 + 1) * wy1 * wx1)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True, name=None):
    """RoIAlign (reference ``roi_align``): bilinear grid sampling + average
    over samples per bin. x: [N, C, H, W]; boxes: [R, 4]; boxes_num: [N]."""
    x = jnp.asarray(x, jnp.float32)
    boxes = jnp.asarray(boxes, jnp.float32)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    sr = sampling_ratio if sampling_ratio > 0 else 2
    off = 0.5 if aligned else 0.0
    # roi -> batch index mapping from boxes_num
    batch_idx = jnp.repeat(jnp.arange(len(np.asarray(boxes_num))),
                           np.asarray(boxes_num))

    def one_roi(b, box):
        x1, y1, x2, y2 = box * spatial_scale - off
        rh = jnp.maximum((y2 - y1) / ph, 1e-6)
        rw = jnp.maximum((x2 - x1) / pw, 1e-6)
        # sample grid: sr x sr points per bin, centers at (k + 0.5)/sr
        iy = (jnp.arange(sr, dtype=jnp.float32) + 0.5) / sr
        ys = y1 + (jnp.arange(ph, dtype=jnp.float32)[:, None] + iy[None, :]) * rh
        xs = x1 + (jnp.arange(pw, dtype=jnp.float32)[:, None] + iy[None, :]) * rw
        grid_y = jnp.broadcast_to(ys[:, None, :, None], (ph, pw, sr, sr))
        grid_x = jnp.broadcast_to(xs[None, :, None, :], (ph, pw, sr, sr))
        vals = _bilinear(x[b], grid_y.reshape(-1), grid_x.reshape(-1))
        vals = vals.reshape(x.shape[1], ph, pw, sr * sr)
        return vals.mean(-1)

    return jax.vmap(one_roi)(batch_idx, boxes)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
             name=None, max_samples_per_bin: int = 8):
    """RoIPool (reference ``roi_pool``): dense-sampled max per quantized
    bin (sampling formulation — no data-dependent bin extents, so it
    jit-compiles; matches the kernel up to sampling density)."""
    x = jnp.asarray(x, jnp.float32)
    boxes = jnp.asarray(boxes, jnp.float32)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    # Samples per bin edge scale with the worst-case bin extent for an RoI
    # covering the whole feature map (H/ph cells tall): spacing <= 1 cell
    # hits every integer cell of such a bin, making the max exact. The
    # budget is CAPPED (max_samples_per_bin per edge, default 8) because
    # the gather materializes R*C*ph*pw*sr_y*sr_x samples — an uncapped
    # whole-map budget on a large map would explode memory for every RoI,
    # however small. Bins wider than the cap are approximated at cap
    # density; raise max_samples_per_bin when RoIs near the full map size
    # need exact maxes.
    cap = int(max_samples_per_bin)
    sr_y = max(4, min(cap, -(-x.shape[2] // ph)))
    sr_x = max(4, min(cap, -(-x.shape[3] // pw)))
    batch_idx = jnp.repeat(jnp.arange(len(np.asarray(boxes_num))),
                           np.asarray(boxes_num))

    def one_roi(b, box):
        x1, y1, x2, y2 = jnp.round(box * spatial_scale)
        # clip to the map BEFORE computing bin extents: out-of-bounds boxes
        # would otherwise make bins wider than the sample budget assumes
        # (spacing > 1 cell skips in-bounds rows) — and the reference's
        # quantized kernel clamps bin coordinates into the map anyway
        x1 = jnp.clip(x1, 0, x.shape[3] - 1)
        x2 = jnp.clip(x2, 0, x.shape[3] - 1)
        y1 = jnp.clip(y1, 0, x.shape[2] - 1)
        y2 = jnp.clip(y2, 0, x.shape[2] - 1)
        rh = jnp.maximum(y2 - y1 + 1, 1.0) / ph
        rw = jnp.maximum(x2 - x1 + 1, 1.0) / pw
        iy = jnp.arange(sr_y, dtype=jnp.float32) / sr_y
        ix = jnp.arange(sr_x, dtype=jnp.float32) / sr_x
        ys = y1 + (jnp.arange(ph, dtype=jnp.float32)[:, None] + iy[None, :]) * rh
        xs = x1 + (jnp.arange(pw, dtype=jnp.float32)[:, None] + ix[None, :]) * rw
        gy = jnp.broadcast_to(ys[:, None, :, None], (ph, pw, sr_y, sr_x))
        gx = jnp.broadcast_to(xs[None, :, None, :], (ph, pw, sr_y, sr_x))
        # nearest-sample max over the bin
        yi = jnp.clip(jnp.floor(gy), 0, x.shape[2] - 1).astype(jnp.int32)
        xi = jnp.clip(jnp.floor(gx), 0, x.shape[3] - 1).astype(jnp.int32)
        vals = x[b][:, yi.reshape(-1), xi.reshape(-1)]
        return vals.reshape(x.shape[1], ph, pw, sr_y * sr_x).max(-1)

    return jax.vmap(one_roi)(batch_idx, boxes)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
               name=None):
    """Position-sensitive RoI pooling (reference ``psroi_pool``): channel
    block (i, j) feeds output bin (i, j); average pooling per bin."""
    x = jnp.asarray(x, jnp.float32)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    c = x.shape[1]
    if c % (ph * pw):
        raise ValueError(f"channels {c} must divide output {ph}x{pw}")
    co = c // (ph * pw)
    aligned = roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                        sampling_ratio=2, aligned=False)  # [R, C, ph, pw]
    r = aligned.shape[0]
    # channel layout: [cout, ph, pw] blocks — bin (i, j) takes its block
    blocks = aligned.reshape(r, co, ph, pw, ph, pw)
    ii = jnp.arange(ph)
    jj = jnp.arange(pw)
    return blocks[:, :, ii[:, None], jj[None, :], ii[:, None], jj[None, :]]


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference ``deform_conv2d``): gather-sample
    the input at offset positions, then a dense matmul — the gather+MXU
    formulation of the CUDA kernel. x: [N, Cin, H, W]; offset:
    [N, 2*dg*kh*kw, Ho, Wo]; mask (v2): [N, dg*kh*kw, Ho, Wo]."""
    x = jnp.asarray(x, jnp.float32)
    offset = jnp.asarray(offset, jnp.float32)
    weight = jnp.asarray(weight, jnp.float32)
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("groups/deformable_groups > 1")
    n, cin, h, w = x.shape
    cout, _, kh, kw = weight.shape
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    padh, padw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    ho = (h + 2 * padh - (dh * (kh - 1) + 1)) // sh + 1
    wo = (w + 2 * padw - (dw * (kw - 1) + 1)) // sw + 1
    base_y = (jnp.arange(ho) * sh - padh)[:, None, None] + \
        (jnp.arange(kh) * dh)[None, :, None]              # [Ho, kh, 1]
    base_x = (jnp.arange(wo) * sw - padw)[:, None, None] + \
        (jnp.arange(kw) * dw)[None, :, None]              # [Wo, kw, 1]
    off = offset.reshape(n, kh, kw, 2, ho, wo)
    oy = off[:, :, :, 0]  # [N, kh, kw, Ho, Wo]
    ox = off[:, :, :, 1]
    # absolute sample coords [N, kh, kw, Ho, Wo]
    ys = oy + base_y.transpose(1, 2, 0).reshape(1, kh, 1, ho, 1)
    xs = ox + base_x.transpose(1, 2, 0).reshape(1, 1, kw, 1, wo)
    if mask is not None:
        m = jnp.asarray(mask, jnp.float32).reshape(n, kh, kw, ho, wo)
    else:
        m = jnp.ones((n, kh, kw, ho, wo), jnp.float32)

    def sample_img(img, ys_i, xs_i, m_i):
        vals = _bilinear(img, ys_i.reshape(-1), xs_i.reshape(-1))
        return vals.reshape(cin, kh, kw, ho, wo) * m_i[None]

    cols = jax.vmap(sample_img)(x, ys, xs, m)  # [N, Cin, kh, kw, Ho, Wo]
    cols = cols.reshape(n, cin * kh * kw, ho * wo)
    wmat = weight.reshape(cout, cin * kh * kw)
    out = jnp.einsum("ok,nkp->nop", wmat, cols).reshape(n, cout, ho, wo)
    if bias is not None:
        out = out + jnp.asarray(bias, jnp.float32)[None, :, None, None]
    return out


def read_file(filename: str, name=None):
    """Raw file bytes as a uint8 tensor (reference ``read_file``)."""
    with open(filename, "rb") as f:
        return jnp.asarray(np.frombuffer(f.read(), np.uint8))


def decode_jpeg(x, mode: str = "unchanged", name=None):
    """Decode JPEG bytes to [C, H, W] uint8 via PIL (the host-side decode
    the reference does with nvjpeg/CPU)."""
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(np.asarray(x).tobytes()))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return jnp.asarray(arr)


def sequence_mask(lengths, maxlen: Optional[int] = None, dtype="bool",
                  name=None):
    """[..., maxlen] mask of positions < length (reference
    ``paddle.nn.functional.sequence_mask`` — the sequence-op family's
    surviving member; LoD sequence ops collapse into masking on TPU)."""
    from ..framework.dtype import convert_dtype

    lengths = jnp.asarray(lengths)
    if maxlen is None:
        maxlen = int(jnp.max(lengths))
    mask = jnp.arange(maxlen)[None, :] < lengths[..., None]
    return mask.astype(convert_dtype(dtype))
