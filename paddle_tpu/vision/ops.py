"""Detection / region ops.

Reference parity: ``python/paddle/vision/ops.py`` (nms, box_coder,
yolo_box, prior_box, roi_align, roi_pool, psroi_pool, deform_conv2d,
read_file/decode_jpeg). TPU-native notes: the box math is pure jnp (XLA
fuses it); the region poolers are gather+interpolation formulations (no
scatter-heavy CUDA kernels to port); nms returns a dynamic-length index
set, so it computes through jnp and materializes eagerly — inside jit use
the fixed-shape ``nms_mask`` flavor.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["nms", "nms_mask", "box_coder", "yolo_box", "prior_box",
           "roi_align", "roi_pool", "psroi_pool", "deform_conv2d",
           "read_file", "decode_jpeg", "sequence_mask", "matrix_nms",
           "distribute_fpn_proposals", "generate_proposals", "yolo_loss"]


def _pairwise_iou(boxes):
    """IoU matrix for [N, 4] (x1, y1, x2, y2) boxes."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms_mask(boxes, scores, iou_threshold: float = 0.3):
    """Fixed-shape NMS: boolean keep-mask in SCORE order is computed with a
    ``fori_loop`` greedy sweep — jit-safe (use this inside compiled code)."""
    boxes = jnp.asarray(boxes, jnp.float32)
    scores = jnp.asarray(scores, jnp.float32)
    order = jnp.argsort(-scores)
    iou = _pairwise_iou(boxes[order])
    n = boxes.shape[0]

    def body(i, keep):
        # suppressed if overlapping any higher-scoring KEPT box
        over = (iou[:, i] > iou_threshold) & keep & (jnp.arange(n) < i)
        return keep.at[i].set(~jnp.any(over))

    keep_sorted = lax.fori_loop(0, n, body, jnp.ones(n, bool))
    # back to original indexing
    keep = jnp.zeros(n, bool).at[order].set(keep_sorted)
    return keep


def nms(boxes, iou_threshold: float = 0.3, scores=None, category_idxs=None,
        categories=None, top_k: Optional[int] = None, name=None):
    """Greedy NMS returning kept indices sorted by descending score
    (reference ``nms``). Dynamic-length output -> eager; supports the
    reference's categorical batched mode (suppression only within a
    category) and ``top_k``."""
    boxes = jnp.asarray(boxes, jnp.float32)
    n = boxes.shape[0]
    if scores is None:
        scores = jnp.arange(n, 0, -1, dtype=jnp.float32)  # input order
    scores = jnp.asarray(scores, jnp.float32)
    if category_idxs is not None:
        # offset boxes per category so cross-category IoU is 0 (the
        # standard batched-nms trick)
        cat = jnp.asarray(category_idxs)
        offset = (cat.astype(jnp.float32) *
                  (jnp.max(boxes) - jnp.min(boxes) + 1.0))[:, None]
        keep = nms_mask(boxes + offset, scores, iou_threshold)
    else:
        keep = nms_mask(boxes, scores, iou_threshold)
    idx = np.where(np.asarray(keep))[0]
    idx = idx[np.argsort(-np.asarray(scores)[idx], kind="stable")]
    if top_k is not None:
        idx = idx[:top_k]
    return jnp.asarray(idx)  # default int dtype (x64 is globally off)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size", box_normalized: bool = True,
              axis: int = 0, name=None):
    """Encode/decode boxes against priors (reference ``box_coder``)."""
    pb = jnp.asarray(prior_box, jnp.float32)
    tb = jnp.asarray(target_box, jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pb_w = pb[:, 2] - pb[:, 0] + norm
    pb_h = pb[:, 3] - pb[:, 1] + norm
    pb_x = pb[:, 0] + pb_w * 0.5
    pb_y = pb[:, 1] + pb_h * 0.5
    if prior_box_var is None:
        var = jnp.ones((1, 4), jnp.float32)
    else:
        var = jnp.asarray(prior_box_var, jnp.float32)
        if var.ndim == 1:
            var = var[None, :]
    if code_type == "encode_center_size":
        tb_w = tb[:, 2] - tb[:, 0] + norm
        tb_h = tb[:, 3] - tb[:, 1] + norm
        tb_x = tb[:, 0] + tb_w * 0.5
        tb_y = tb[:, 1] + tb_h * 0.5
        # [M priors, N targets] broadcast: reference encodes every target
        # against every prior -> [N, M, 4]
        out = jnp.stack([
            (tb_x[:, None] - pb_x[None, :]) / pb_w[None, :],
            (tb_y[:, None] - pb_y[None, :]) / pb_h[None, :],
            jnp.log(jnp.abs(tb_w[:, None] / pb_w[None, :])),
            jnp.log(jnp.abs(tb_h[:, None] / pb_h[None, :])),
        ], axis=-1)
        return out / var[None, :, :]
    if code_type == "decode_center_size":
        # tb: [N, M, 4] codes; priors broadcast along `axis`
        exp = (None, slice(None)) if axis == 0 else (slice(None), None)
        pbx, pby = pb_x[exp], pb_y[exp]
        pbw, pbh = pb_w[exp], pb_h[exp]
        v = var[exp[0], exp[1], :] if var.shape[0] > 1 else var[None, :, :]
        tx = tb[..., 0] * v[..., 0] * pbw + pbx
        ty = tb[..., 1] * v[..., 1] * pbh + pby
        tw = jnp.exp(v[..., 2] * tb[..., 2]) * pbw
        th = jnp.exp(v[..., 3] * tb[..., 3]) * pbh
        return jnp.stack([tx - tw / 2, ty - th / 2,
                          tx + tw / 2 - norm, ty + th / 2 - norm], axis=-1)
    raise ValueError(f"unknown code_type {code_type!r}")


def yolo_box(x, img_size, anchors: Sequence[int], class_num: int,
             conf_thresh: float, downsample_ratio: int, clip_bbox: bool = True,
             scale_x_y: float = 1.0, iou_aware: bool = False,
             iou_aware_factor: float = 0.5, name=None):
    """Decode a YOLOv3 head into boxes + scores (reference ``yolo_box``).
    x: [N, C, H, W] with C = num_anchors * (5 + class_num)."""
    if iou_aware:
        raise NotImplementedError(
            "yolo_box iou_aware=False only (PP-YOLO's iou-aware channel "
            "layout is not implemented)")
    x = jnp.asarray(x, jnp.float32)
    n, c, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[:, None]
    bx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) / 2 + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) / 2 + grid_y) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    conf = jnp.where(conf < conf_thresh, 0.0, conf)
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    img = jnp.asarray(img_size, jnp.float32).reshape(n, 2)  # [h, w]
    ih, iw = img[:, 0], img[:, 1]
    x1 = (bx - bw / 2) * iw[:, None, None, None]
    y1 = (by - bh / 2) * ih[:, None, None, None]
    x2 = (bx + bw / 2) * iw[:, None, None, None]
    y2 = (by + bh / 2) * ih[:, None, None, None]
    if clip_bbox:
        x1 = jnp.clip(x1, 0, iw[:, None, None, None] - 1)
        y1 = jnp.clip(y1, 0, ih[:, None, None, None] - 1)
        x2 = jnp.clip(x2, 0, iw[:, None, None, None] - 1)
        y2 = jnp.clip(y2, 0, ih[:, None, None, None] - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
    scores = jnp.moveaxis(probs, 2, -1).reshape(n, -1, class_num)
    return boxes, scores


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip: bool = False,
              clip: bool = False, steps=(0.0, 0.0), offset: float = 0.5,
              min_max_aspect_ratios_order: bool = False, name=None):
    """SSD prior (anchor) boxes per feature-map cell (reference
    ``prior_box``)."""
    feat_h, feat_w = jnp.asarray(input).shape[2:]
    img_h, img_w = jnp.asarray(image).shape[2:]
    step_w = steps[0] or img_w / feat_w
    step_h = steps[1] or img_h / feat_h
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    whs = []
    for ms in min_sizes:
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((float(np.sqrt(ms * mx)),) * 2)
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((float(np.sqrt(ms * mx)),) * 2)
    whs = jnp.asarray(whs, jnp.float32)  # [P, 2]
    cx = (jnp.arange(feat_w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(feat_h, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    boxes = jnp.stack([
        (cxg[..., None] - whs[None, None, :, 0] / 2) / img_w,
        (cyg[..., None] - whs[None, None, :, 1] / 2) / img_h,
        (cxg[..., None] + whs[None, None, :, 0] / 2) / img_w,
        (cyg[..., None] + whs[None, None, :, 1] / 2) / img_h,
    ], axis=-1)  # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           boxes.shape)
    return boxes, var


def _bilinear(x, ys, xs):
    """Sample x [C, H, W] at float coords (ys, xs) [...]: bilinear, zero
    padded outside."""
    c, h, w = x.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0

    def at(yi, xi):
        valid = (yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)
        yi = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        return x[:, yi, xi] * valid.astype(x.dtype)

    return (at(y0, x0) * (1 - wy1) * (1 - wx1) +
            at(y0, x0 + 1) * (1 - wy1) * wx1 +
            at(y0 + 1, x0) * wy1 * (1 - wx1) +
            at(y0 + 1, x0 + 1) * wy1 * wx1)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True, name=None):
    """RoIAlign (reference ``roi_align``): bilinear grid sampling + average
    over samples per bin. x: [N, C, H, W]; boxes: [R, 4]; boxes_num: [N]."""
    x = jnp.asarray(x, jnp.float32)
    boxes = jnp.asarray(boxes, jnp.float32)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    sr = sampling_ratio if sampling_ratio > 0 else 2
    off = 0.5 if aligned else 0.0
    # roi -> batch index mapping from boxes_num
    batch_idx = jnp.repeat(jnp.arange(len(np.asarray(boxes_num))),
                           np.asarray(boxes_num))

    def one_roi(b, box):
        x1, y1, x2, y2 = box * spatial_scale - off
        rh = jnp.maximum((y2 - y1) / ph, 1e-6)
        rw = jnp.maximum((x2 - x1) / pw, 1e-6)
        # sample grid: sr x sr points per bin, centers at (k + 0.5)/sr
        iy = (jnp.arange(sr, dtype=jnp.float32) + 0.5) / sr
        ys = y1 + (jnp.arange(ph, dtype=jnp.float32)[:, None] + iy[None, :]) * rh
        xs = x1 + (jnp.arange(pw, dtype=jnp.float32)[:, None] + iy[None, :]) * rw
        grid_y = jnp.broadcast_to(ys[:, None, :, None], (ph, pw, sr, sr))
        grid_x = jnp.broadcast_to(xs[None, :, None, :], (ph, pw, sr, sr))
        vals = _bilinear(x[b], grid_y.reshape(-1), grid_x.reshape(-1))
        vals = vals.reshape(x.shape[1], ph, pw, sr * sr)
        return vals.mean(-1)

    return jax.vmap(one_roi)(batch_idx, boxes)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
             name=None, max_samples_per_bin: int = 8):
    """RoIPool (reference ``roi_pool``): dense-sampled max per quantized
    bin (sampling formulation — no data-dependent bin extents, so it
    jit-compiles; matches the kernel up to sampling density)."""
    x = jnp.asarray(x, jnp.float32)
    boxes = jnp.asarray(boxes, jnp.float32)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    # Samples per bin edge scale with the worst-case bin extent for an RoI
    # covering the whole feature map (H/ph cells tall): spacing <= 1 cell
    # hits every integer cell of such a bin, making the max exact. The
    # budget is CAPPED (max_samples_per_bin per edge, default 8) because
    # the gather materializes R*C*ph*pw*sr_y*sr_x samples — an uncapped
    # whole-map budget on a large map would explode memory for every RoI,
    # however small. Bins wider than the cap are approximated at cap
    # density; raise max_samples_per_bin when RoIs near the full map size
    # need exact maxes.
    cap = int(max_samples_per_bin)
    sr_y = max(4, min(cap, -(-x.shape[2] // ph)))
    sr_x = max(4, min(cap, -(-x.shape[3] // pw)))
    batch_idx = jnp.repeat(jnp.arange(len(np.asarray(boxes_num))),
                           np.asarray(boxes_num))

    def one_roi(b, box):
        x1, y1, x2, y2 = jnp.round(box * spatial_scale)
        # clip to the map BEFORE computing bin extents: out-of-bounds boxes
        # would otherwise make bins wider than the sample budget assumes
        # (spacing > 1 cell skips in-bounds rows) — and the reference's
        # quantized kernel clamps bin coordinates into the map anyway
        x1 = jnp.clip(x1, 0, x.shape[3] - 1)
        x2 = jnp.clip(x2, 0, x.shape[3] - 1)
        y1 = jnp.clip(y1, 0, x.shape[2] - 1)
        y2 = jnp.clip(y2, 0, x.shape[2] - 1)
        rh = jnp.maximum(y2 - y1 + 1, 1.0) / ph
        rw = jnp.maximum(x2 - x1 + 1, 1.0) / pw
        iy = jnp.arange(sr_y, dtype=jnp.float32) / sr_y
        ix = jnp.arange(sr_x, dtype=jnp.float32) / sr_x
        ys = y1 + (jnp.arange(ph, dtype=jnp.float32)[:, None] + iy[None, :]) * rh
        xs = x1 + (jnp.arange(pw, dtype=jnp.float32)[:, None] + ix[None, :]) * rw
        gy = jnp.broadcast_to(ys[:, None, :, None], (ph, pw, sr_y, sr_x))
        gx = jnp.broadcast_to(xs[None, :, None, :], (ph, pw, sr_y, sr_x))
        # nearest-sample max over the bin
        yi = jnp.clip(jnp.floor(gy), 0, x.shape[2] - 1).astype(jnp.int32)
        xi = jnp.clip(jnp.floor(gx), 0, x.shape[3] - 1).astype(jnp.int32)
        vals = x[b][:, yi.reshape(-1), xi.reshape(-1)]
        return vals.reshape(x.shape[1], ph, pw, sr_y * sr_x).max(-1)

    return jax.vmap(one_roi)(batch_idx, boxes)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
               name=None):
    """Position-sensitive RoI pooling (reference ``psroi_pool``): channel
    block (i, j) feeds output bin (i, j); average pooling per bin."""
    x = jnp.asarray(x, jnp.float32)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    c = x.shape[1]
    if c % (ph * pw):
        raise ValueError(f"channels {c} must divide output {ph}x{pw}")
    co = c // (ph * pw)
    aligned = roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                        sampling_ratio=2, aligned=False)  # [R, C, ph, pw]
    r = aligned.shape[0]
    # channel layout: [cout, ph, pw] blocks — bin (i, j) takes its block
    blocks = aligned.reshape(r, co, ph, pw, ph, pw)
    ii = jnp.arange(ph)
    jj = jnp.arange(pw)
    return blocks[:, :, ii[:, None], jj[None, :], ii[:, None], jj[None, :]]


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference ``deform_conv2d``): gather-sample
    the input at offset positions, then a dense matmul — the gather+MXU
    formulation of the CUDA kernel. x: [N, Cin, H, W]; offset:
    [N, 2*dg*kh*kw, Ho, Wo]; mask (v2): [N, dg*kh*kw, Ho, Wo]."""
    x = jnp.asarray(x, jnp.float32)
    offset = jnp.asarray(offset, jnp.float32)
    weight = jnp.asarray(weight, jnp.float32)
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("groups/deformable_groups > 1")
    n, cin, h, w = x.shape
    cout, _, kh, kw = weight.shape
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    padh, padw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    ho = (h + 2 * padh - (dh * (kh - 1) + 1)) // sh + 1
    wo = (w + 2 * padw - (dw * (kw - 1) + 1)) // sw + 1
    base_y = (jnp.arange(ho) * sh - padh)[:, None, None] + \
        (jnp.arange(kh) * dh)[None, :, None]              # [Ho, kh, 1]
    base_x = (jnp.arange(wo) * sw - padw)[:, None, None] + \
        (jnp.arange(kw) * dw)[None, :, None]              # [Wo, kw, 1]
    off = offset.reshape(n, kh, kw, 2, ho, wo)
    oy = off[:, :, :, 0]  # [N, kh, kw, Ho, Wo]
    ox = off[:, :, :, 1]
    # absolute sample coords [N, kh, kw, Ho, Wo]
    ys = oy + base_y.transpose(1, 2, 0).reshape(1, kh, 1, ho, 1)
    xs = ox + base_x.transpose(1, 2, 0).reshape(1, 1, kw, 1, wo)
    if mask is not None:
        m = jnp.asarray(mask, jnp.float32).reshape(n, kh, kw, ho, wo)
    else:
        m = jnp.ones((n, kh, kw, ho, wo), jnp.float32)

    def sample_img(img, ys_i, xs_i, m_i):
        vals = _bilinear(img, ys_i.reshape(-1), xs_i.reshape(-1))
        return vals.reshape(cin, kh, kw, ho, wo) * m_i[None]

    cols = jax.vmap(sample_img)(x, ys, xs, m)  # [N, Cin, kh, kw, Ho, Wo]
    cols = cols.reshape(n, cin * kh * kw, ho * wo)
    wmat = weight.reshape(cout, cin * kh * kw)
    out = jnp.einsum("ok,nkp->nop", wmat, cols).reshape(n, cout, ho, wo)
    if bias is not None:
        out = out + jnp.asarray(bias, jnp.float32)[None, :, None, None]
    return out


def read_file(filename: str, name=None):
    """Raw file bytes as a uint8 tensor (reference ``read_file``)."""
    with open(filename, "rb") as f:
        return jnp.asarray(np.frombuffer(f.read(), np.uint8))


def decode_jpeg(x, mode: str = "unchanged", name=None):
    """Decode JPEG bytes to [C, H, W] uint8 via PIL (the host-side decode
    the reference does with nvjpeg/CPU)."""
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(np.asarray(x).tobytes()))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return jnp.asarray(arr)


def sequence_mask(lengths, maxlen: Optional[int] = None, dtype="bool",
                  name=None):
    """[..., maxlen] mask of positions < length (reference
    ``paddle.nn.functional.sequence_mask`` — the sequence-op family's
    surviving member; LoD sequence ops collapse into masking on TPU)."""
    from ..framework.dtype import convert_dtype

    lengths = jnp.asarray(lengths)
    if maxlen is None:
        maxlen = int(jnp.max(lengths))
    mask = jnp.arange(maxlen)[None, :] < lengths[..., None]
    return mask.astype(convert_dtype(dtype))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN levels by scale (reference
    ``distribute_fpn_proposals``, python/paddle/vision/ops.py:1288):
    level = floor(refer_level + log2(sqrt(area) / refer_scale)) clipped to
    [min_level, max_level]. Dynamic-length per-level outputs -> eager.

    Returns ``(multi_rois, restore_ind, rois_num_per_level)`` where
    ``restore_ind`` re-concatenates level outputs back to input order.
    """
    rois = np.asarray(fpn_rois, np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 0.0))
    level = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    level = np.clip(level, min_level, max_level).astype(np.int64)
    multi_rois, per_level, order = [], [], []
    for lv in range(min_level, max_level + 1):
        idx = np.where(level == lv)[0]
        multi_rois.append(jnp.asarray(rois[idx]))
        per_level.append(idx.size)
        order.append(idx)
    order = np.concatenate(order) if order else np.empty(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.size)
    return multi_rois, jnp.asarray(restore), jnp.asarray(per_level)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference ``matrix_nms``, vision/ops.py:2428; SOLOv2):
    instead of hard suppression, each box's score decays by the IoU it has
    with every higher-scored box of its class, normalized by how much THAT
    box was itself overlapped — one IoU matrix, no sequential loop.

    bboxes: [N, M, 4]; scores: [N, C, M]. Returns (out [R, 6], rois_num
    and/or index per the flags); out rows are [label, score, x1, y1, x2, y2].
    """
    # whole routine in host numpy: this is an inherently eager op (dynamic
    # output length) and per-class device round-trips would dominate
    bboxes = np.asarray(bboxes, np.float32)
    scores = np.asarray(scores, np.float32)
    n, c, m = scores.shape
    outs, idxs, counts = [], [], []

    def np_iou(box):
        area = (np.maximum(box[:, 2] - box[:, 0], 0)
                * np.maximum(box[:, 3] - box[:, 1], 0))
        lt = np.maximum(box[:, None, :2], box[None, :, :2])
        rb = np.minimum(box[:, None, 2:], box[None, :, 2:])
        wh = np.maximum(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / np.maximum(area[:, None] + area[None, :] - inter,
                                  1e-10)

    for b in range(n):
        rows = []
        ridx = []
        for cls in range(c):
            if cls == background_label:
                continue
            sc = scores[b, cls]
            keep = np.where(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            # top nms_top_k by score
            order = keep[np.argsort(-sc[keep], kind="stable")]
            order = order[:nms_top_k]
            box = bboxes[b][order]
            s = sc[order]
            iou = np_iou(box)
            k = order.size
            tri = np.tril(iou, k=-1)  # iou with higher-scored (earlier) boxes
            max_iou_of_higher = np.max(tri, axis=1)  # per box
            # decay_ij = f(iou_ij) / f(max overlap of the suppressor j)
            if use_gaussian:
                decay = np.exp(-(tri ** 2 - max_iou_of_higher[None, :] ** 2)
                               / gaussian_sigma)
            else:
                decay = (1 - tri) / (1 - max_iou_of_higher[None, :] + 1e-10)
            decay = np.where(np.tril(np.ones((k, k), bool), k=-1),
                             decay, 1.0)
            factor = np.min(decay, axis=1)
            new_s = s * factor
            rows.append(np.column_stack([
                np.full(k, cls, np.float32), new_s, box]))
            ridx.append(order)
        if rows:
            allr = np.concatenate(rows)
            alli = np.concatenate(ridx)
            sel = np.where(allr[:, 1] > post_threshold)[0]
            sel = sel[np.argsort(-allr[sel, 1], kind="stable")][:keep_top_k]
            outs.append(allr[sel])
            idxs.append(alli[sel] + b * m)
            counts.append(sel.size)
        else:
            outs.append(np.zeros((0, 6), np.float32))
            idxs.append(np.zeros(0, np.int64))
            counts.append(0)
    out = jnp.asarray(np.concatenate(outs))
    result = [out]
    if return_index:
        result.append(jnp.asarray(np.concatenate(idxs)))
    if return_rois_num:
        result.append(jnp.asarray(np.asarray(counts, np.int32)))
    return tuple(result) if len(result) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (reference ``generate_proposals``,
    vision/ops.py:2239): decode anchor deltas, clip to the image, drop tiny
    boxes, take top pre_nms_top_n by score, NMS, keep post_nms_top_n.

    scores: [N, A, H, W]; bbox_deltas: [N, 4*A, H, W]; anchors/variances:
    [H*W*A, 4]. Returns (rpn_rois [R, 4], rpn_roi_probs [R, 1][, rois_num]).
    """
    scores = np.asarray(scores, np.float32)
    deltas = np.asarray(bbox_deltas, np.float32)
    anchors = np.asarray(anchors, np.float32).reshape(-1, 4)
    variances = np.asarray(variances, np.float32).reshape(-1, 4)
    img_size = np.asarray(img_size, np.float32).reshape(-1, 2)
    n, a, h, w = scores.shape
    off = 1.0 if pixel_offset else 0.0
    all_rois, all_probs, counts = [], [], []
    for b in range(n):
        sc = scores[b].transpose(1, 2, 0).reshape(-1)       # [H*W*A]
        dl = deltas[b].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-sc, kind="stable")[:pre_nms_top_n]
        sc, dl = sc[order], dl[order]
        an, var = anchors[order], variances[order]
        # decode (encode_center_size inverse, the RPN convention)
        aw = an[:, 2] - an[:, 0] + off
        ah = an[:, 3] - an[:, 1] + off
        ax = an[:, 0] + aw * 0.5
        ay = an[:, 1] + ah * 0.5
        cx = var[:, 0] * dl[:, 0] * aw + ax
        cy = var[:, 1] * dl[:, 1] * ah + ay
        bw = np.exp(np.minimum(var[:, 2] * dl[:, 2], 10.0)) * aw
        bh = np.exp(np.minimum(var[:, 3] * dl[:, 3], 10.0)) * ah
        box = np.column_stack([cx - bw / 2, cy - bh / 2,
                               cx + bw / 2 - off, cy + bh / 2 - off])
        ih, iw = img_size[b]
        box[:, 0::2] = np.clip(box[:, 0::2], 0, iw - off)
        box[:, 1::2] = np.clip(box[:, 1::2], 0, ih - off)
        ok = ((box[:, 2] - box[:, 0] + off >= min_size) &
              (box[:, 3] - box[:, 1] + off >= min_size))
        box, sc = box[ok], sc[ok]
        if box.shape[0]:
            keep = np.asarray(nms_mask(box, sc, nms_thresh))
            sel = np.where(keep)[0]
            sel = sel[np.argsort(-sc[sel], kind="stable")][:post_nms_top_n]
            box, sc = box[sel], sc[sel]
        all_rois.append(box)
        all_probs.append(sc[:, None])
        counts.append(box.shape[0])
    rois = jnp.asarray(np.concatenate(all_rois))
    probs = jnp.asarray(np.concatenate(all_probs))
    if return_rois_num:
        return rois, probs, jnp.asarray(np.asarray(counts, np.int32))
    return rois, probs


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference ``yolo_loss``): responsible-anchor matching
    by best whole-image IoU, objectness BCE with an ignore band, box
    regression (xy BCE + wh L1, scaled by 2 - w*h), and class BCE.

    x: [N, A*(5+C), H, W]; gt_box: [N, B, 4] (cx, cy, w, h, normalized to
    the image); gt_label: [N, B]. Returns per-image loss [N].
    Vectorized jnp throughout — one fused XLA program, no loops over boxes.
    """
    x = jnp.asarray(x, jnp.float32)
    gt_box = jnp.asarray(gt_box, jnp.float32)
    gt_label = jnp.asarray(gt_label, jnp.int32)
    n, c, h, w = x.shape
    na = len(anchor_mask)
    all_an = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    an = all_an[jnp.asarray(anchor_mask)]
    input_size = downsample_ratio * h
    x = x.reshape(n, na, 5 + class_num, h, w)
    pred_xy = jax.nn.sigmoid(x[:, :, 0:2]) * scale_x_y - (scale_x_y - 1) / 2
    pred_wh = x[:, :, 2:4]
    pred_obj = x[:, :, 4]
    pred_cls = x[:, :, 5:]
    nb = gt_box.shape[1]
    valid = (gt_box[:, :, 2] > 0) & (gt_box[:, :, 3] > 0)  # [N, B]

    # responsible anchor: best IoU of gt wh vs ALL anchors (shape-only IoU)
    gwh = gt_box[:, :, 2:4] * input_size  # pixels
    inter = (jnp.minimum(gwh[:, :, None, 0], all_an[None, None, :, 0]) *
             jnp.minimum(gwh[:, :, None, 1], all_an[None, None, :, 1]))
    union = (gwh[:, :, 0] * gwh[:, :, 1])[:, :, None] + \
        (all_an[:, 0] * all_an[:, 1])[None, None, :] - inter
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)  # [N, B]
    # map to the mask slot (or -1 if this level is not responsible)
    mask_arr = jnp.asarray(anchor_mask)
    slot = jnp.argmax(best_anchor[..., None] == mask_arr[None, None, :], -1)
    responsible = valid & jnp.any(
        best_anchor[..., None] == mask_arr[None, None, :], -1)

    gi = jnp.clip((gt_box[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[:, :, 1] * h).astype(jnp.int32), 0, h - 1)
    # padding / non-responsible rows must scatter NOWHERE: route them to an
    # out-of-bounds cell so mode="drop" discards the write (otherwise they
    # all land on [b, 0, 0, 0] and clobber a real target there)
    tx = gt_box[:, :, 0] * w - gi
    ty = gt_box[:, :, 1] * h - gj
    tw = jnp.log(jnp.maximum(gwh[:, :, 0] / jnp.maximum(an[slot][:, :, 0],
                                                        1e-8), 1e-8))
    th = jnp.log(jnp.maximum(gwh[:, :, 1] / jnp.maximum(an[slot][:, :, 1],
                                                        1e-8), 1e-8))
    box_scale = 2.0 - gt_box[:, :, 2] * gt_box[:, :, 3]
    score = (jnp.asarray(gt_score, jnp.float32) if gt_score is not None
             else jnp.ones((n, nb), jnp.float32))

    # scatter gt targets onto the grid; non-responsible rows get an OOB
    # row index so mode="drop" discards them entirely
    gj_s = jnp.where(responsible, gj, h)
    bidx = jnp.broadcast_to(jnp.arange(n)[:, None], (n, nb))

    def scatter(weight, vals, default):
        tgt = jnp.full((n, na, h, w), default, jnp.float32)
        wgt = jnp.zeros((n, na, h, w), jnp.float32)
        tgt = tgt.at[bidx, slot, gj_s, gi].set(vals, mode="drop")
        wgt = wgt.at[bidx, slot, gj_s, gi].set(score * weight, mode="drop")
        return tgt, wgt

    one = jnp.ones((n, nb), jnp.float32)
    txg, wxy = scatter(box_scale, tx, 0.0)
    tyg, _ = scatter(box_scale, ty, 0.0)
    twg, _ = scatter(box_scale, tw, 0.0)
    thg, _ = scatter(box_scale, th, 0.0)
    tobj, wobj = scatter(one, one, 0.0)
    has_obj = wobj > 0

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    # xy/wh regression on responsible cells only
    loss_xy = wxy * (bce(x[:, :, 0], txg) + bce(x[:, :, 1], tyg))
    loss_wh = wxy * (jnp.abs(pred_wh[:, :, 0] - twg) +
                     jnp.abs(pred_wh[:, :, 1] - thg))

    # objectness: positives get BCE to 1; negatives whose best pred-gt IoU
    # exceeds ignore_thresh are ignored (the reference's ignore band)
    grid_x = (jnp.arange(w, dtype=jnp.float32)[None, :] + pred_xy[:, :, 0]) / w
    grid_y = (jnp.arange(h, dtype=jnp.float32)[:, None] + pred_xy[:, :, 1]) / h
    pw_ = jnp.exp(jnp.clip(pred_wh[:, :, 0], -10, 10)) * \
        an[None, :, 0, None, None] / input_size
    ph_ = jnp.exp(jnp.clip(pred_wh[:, :, 1], -10, 10)) * \
        an[None, :, 1, None, None] / input_size
    px1, py1 = grid_x - pw_ / 2, grid_y - ph_ / 2
    px2, py2 = grid_x + pw_ / 2, grid_y + ph_ / 2
    gx1 = gt_box[:, :, 0] - gt_box[:, :, 2] / 2
    gy1 = gt_box[:, :, 1] - gt_box[:, :, 3] / 2
    gx2 = gt_box[:, :, 0] + gt_box[:, :, 2] / 2
    gy2 = gt_box[:, :, 1] + gt_box[:, :, 3] / 2
    # IoU of every pred cell vs every gt: [N, A, H, W, B]
    ix1 = jnp.maximum(px1[..., None], gx1[:, None, None, None, :])
    iy1 = jnp.maximum(py1[..., None], gy1[:, None, None, None, :])
    ix2 = jnp.minimum(px2[..., None], gx2[:, None, None, None, :])
    iy2 = jnp.minimum(py2[..., None], gy2[:, None, None, None, :])
    iw_ = jnp.maximum(ix2 - ix1, 0)
    ih_ = jnp.maximum(iy2 - iy1, 0)
    inter_p = iw_ * ih_
    area_p = (px2 - px1) * (py2 - py1)
    area_g = ((gx2 - gx1) * (gy2 - gy1))[:, None, None, None, :]
    iou_p = inter_p / jnp.maximum(area_p[..., None] + area_g - inter_p, 1e-10)
    iou_p = jnp.where(valid[:, None, None, None, :], iou_p, 0.0)
    best_iou = jnp.max(iou_p, -1)
    noobj_mask = (~has_obj) & (best_iou < ignore_thresh)
    loss_obj = jnp.where(has_obj, wobj * bce(pred_obj, 1.0), 0.0) + \
        jnp.where(noobj_mask, bce(pred_obj, 0.0), 0.0)

    # classification on responsible cells
    smooth = 1.0 / class_num if (use_label_smooth and class_num > 1) else 0.0
    onehot = jax.nn.one_hot(gt_label, class_num)
    onehot = onehot * (1.0 - smooth) + smooth * (1.0 / class_num)
    tcls = jnp.zeros((n, na, h, w, class_num), jnp.float32)
    tcls = tcls.at[bidx, slot, gj_s, gi].set(onehot, mode="drop")
    loss_cls = has_obj[..., None] * bce(jnp.moveaxis(pred_cls, 2, -1), tcls)

    per_img = (loss_xy.sum((1, 2, 3)) + loss_wh.sum((1, 2, 3)) +
               loss_obj.sum((1, 2, 3)) + loss_cls.sum((1, 2, 3, 4)))
    return per_img


# --------------------------------------------- r4: layer-class wrappers
class RoIAlign:
    """Layer form of :func:`roi_align` (reference ``paddle.vision.ops.RoIAlign``)."""

    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool:
    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def _make_deform_conv2d():
    # Layer import deferred: vision.ops is imported by modules that load
    # before nn is fully initialized
    from ..nn.layer import Layer
    from ..nn.layers.conv import Conv2D

    class DeformConv2D(Layer):
        """Layer form of :func:`deform_conv2d`: a real nn.Layer, so its
        kernel parameters register with parameters()/state_dict and reach
        the optimizer (reference ``paddle.vision.ops.DeformConv2D``)."""

        def __init__(self, in_channels, out_channels, kernel_size,
                     stride=1, padding=0, dilation=1, deformable_groups=1,
                     groups=1, weight_attr=None, bias_attr=None):
            super().__init__()
            # borrow Conv2D's parameter init/naming (registered sublayer)
            self.conv = Conv2D(in_channels, out_channels, kernel_size,
                               stride=stride, padding=padding,
                               dilation=dilation, groups=groups,
                               weight_attr=weight_attr,
                               bias_attr=bias_attr)
            self.stride, self.padding = stride, padding
            self.dilation, self.groups = dilation, groups
            self.deformable_groups = deformable_groups

        @property
        def weight(self):
            return self.conv.weight

        @property
        def bias(self):
            return self.conv.bias

        def forward(self, x, offset, mask=None):
            return deform_conv2d(x, offset, self.conv.weight,
                                 self.conv.bias, self.stride, self.padding,
                                 self.dilation, self.deformable_groups,
                                 self.groups, mask)

    return DeformConv2D


DeformConv2D = _make_deform_conv2d()


__all__ += ["RoIAlign", "RoIPool", "PSRoIPool", "DeformConv2D"]
