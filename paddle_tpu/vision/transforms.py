"""Image transforms — functional ops + composable pipeline.

Reference parity: ``python/paddle/vision/transforms/`` (``transforms.py``
Compose/Resize/CenterCrop/RandomCrop/RandomHorizontalFlip/Normalize/
ToTensor..., ``functional.py``). TPU-native: transforms are host-side numpy
(they run in DataLoader workers feeding the device, like the reference's
CPU pipeline); arrays are HWC uint8/float in, CHW float out of ``ToTensor``.
"""
from __future__ import annotations

import numbers
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "Compose", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Normalize", "ToTensor",
    "Transpose", "BrightnessTransform", "Pad", "resize", "center_crop",
    "crop", "hflip", "vflip", "normalize", "to_tensor", "pad",
]


def _as_hwc(img) -> np.ndarray:
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def _pair(size) -> Tuple[int, int]:
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


# ------------------------------------------------------------- functional
def resize(img, size, interpolation: str = "bilinear") -> np.ndarray:
    """Resize HWC image. int size = short side (aspect preserved), like the
    reference."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, numbers.Number):
        if h < w:
            oh, ow = int(size), max(1, int(size * w / h))
        else:
            oh, ow = max(1, int(size * h / w)), int(size)
    else:
        oh, ow = _pair(size)
    if (oh, ow) == (h, w):
        return img
    dtype = img.dtype
    x = img.astype(np.float32)
    if interpolation == "nearest":
        ri = (np.arange(oh) * h / oh).astype(np.int64).clip(0, h - 1)
        ci = (np.arange(ow) * w / ow).astype(np.int64).clip(0, w - 1)
        out = x[ri][:, ci]
    else:  # bilinear, align_corners=False convention
        ry = (np.arange(oh) + 0.5) * h / oh - 0.5
        rx = (np.arange(ow) + 0.5) * w / ow - 0.5
        y0 = np.floor(ry).astype(np.int64)
        x0 = np.floor(rx).astype(np.int64)
        wy = (ry - y0)[:, None, None]
        wx = (rx - x0)[None, :, None]
        y0c = y0.clip(0, h - 1)
        y1c = (y0 + 1).clip(0, h - 1)
        x0c = x0.clip(0, w - 1)
        x1c = (x0 + 1).clip(0, w - 1)
        out = ((1 - wy) * (1 - wx) * x[y0c][:, x0c]
               + (1 - wy) * wx * x[y0c][:, x1c]
               + wy * (1 - wx) * x[y1c][:, x0c]
               + wy * wx * x[y1c][:, x1c])
    if np.issubdtype(dtype, np.integer):
        out = np.round(out).clip(np.iinfo(dtype).min,
                                 np.iinfo(dtype).max).astype(dtype)
    else:
        out = out.astype(dtype)
    return out


def crop(img, top: int, left: int, height: int, width: int) -> np.ndarray:
    img = _as_hwc(img)
    return img[top:top + height, left:left + width]


def center_crop(img, output_size) -> np.ndarray:
    img = _as_hwc(img)
    th, tw = _pair(output_size)
    h, w = img.shape[:2]
    top = max(0, (h - th) // 2)
    left = max(0, (w - tw) // 2)
    return crop(img, top, left, th, tw)


def hflip(img) -> np.ndarray:
    return _as_hwc(img)[:, ::-1]


def vflip(img) -> np.ndarray:
    return _as_hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode: str = "constant") -> np.ndarray:
    img = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pt = pr = pb = int(padding)
    elif len(padding) == 2:
        pl = pr = int(padding[0])
        pt = pb = int(padding[1])
    else:
        pl, pt, pr, pb = (int(p) for p in padding)
    pads = [(pt, pb), (pl, pr), (0, 0)]
    if padding_mode == "constant":
        return np.pad(img, pads, mode="constant", constant_values=fill)
    return np.pad(img, pads, mode=padding_mode)


def normalize(img, mean, std, data_format: str = "CHW") -> np.ndarray:
    img = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def to_tensor(img, data_format: str = "CHW") -> np.ndarray:
    """HWC [0,255] uint8 (or float) -> CHW float32 [0,1]."""
    img = _as_hwc(img)
    out = img.astype(np.float32)
    if np.issubdtype(np.asarray(img).dtype, np.integer):
        out = out / 255.0
    if data_format == "CHW":
        out = out.transpose(2, 0, 1)
    return out


# ---------------------------------------------------------------- classes
class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class Resize:
    def __init__(self, size, interpolation: str = "bilinear"):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop:
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed: bool = False,
                 fill=0):
        self.size = _pair(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill

    def __call__(self, img):
        img = _as_hwc(img)
        if self.padding is not None:
            img = pad(img, self.padding, self.fill)
        th, tw = self.size
        h, w = img.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            # pad() tuple order is (left, top, right, bottom)
            img = pad(img, (0, 0, max(0, tw - w), max(0, th - h)), self.fill)
            h, w = img.shape[:2]
        top = np.random.randint(0, h - th + 1)
        left = np.random.randint(0, w - tw + 1)
        return crop(img, top, left, th, tw)


class RandomHorizontalFlip:
    def __init__(self, prob: float = 0.5):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if np.random.rand() < self.prob else _as_hwc(img)


class RandomVerticalFlip:
    def __init__(self, prob: float = 0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if np.random.rand() < self.prob else _as_hwc(img)


class Normalize:
    def __init__(self, mean, std, data_format: str = "CHW"):
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class ToTensor:
    def __init__(self, data_format: str = "CHW"):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return _as_hwc(img).transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode: str = "constant"):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform:
    def __init__(self, value: float):
        self.value = float(value)

    def __call__(self, img):
        img = _as_hwc(img)
        factor = 1.0 + np.random.uniform(-self.value, self.value)
        dtype = img.dtype
        out = img.astype(np.float32) * factor
        if np.issubdtype(dtype, np.integer):
            out = out.clip(0, 255)
        return out.astype(dtype)
