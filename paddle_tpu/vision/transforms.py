"""Image transforms — functional ops + composable pipeline.

Reference parity: ``python/paddle/vision/transforms/`` (``transforms.py``
Compose/Resize/CenterCrop/RandomCrop/RandomHorizontalFlip/Normalize/
ToTensor..., ``functional.py``). TPU-native: transforms are host-side numpy
(they run in DataLoader workers feeding the device, like the reference's
CPU pipeline); arrays are HWC uint8/float in, CHW float out of ``ToTensor``.
"""
from __future__ import annotations

import numbers
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "Compose", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Normalize", "ToTensor",
    "Transpose", "BrightnessTransform", "Pad", "resize", "center_crop",
    "crop", "hflip", "vflip", "normalize", "to_tensor", "pad",
]


def _as_hwc(img) -> np.ndarray:
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def _pair(size) -> Tuple[int, int]:
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


# ------------------------------------------------------------- functional
def resize(img, size, interpolation: str = "bilinear") -> np.ndarray:
    """Resize HWC image. int size = short side (aspect preserved), like the
    reference."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, numbers.Number):
        if h < w:
            oh, ow = int(size), max(1, int(size * w / h))
        else:
            oh, ow = max(1, int(size * h / w)), int(size)
    else:
        oh, ow = _pair(size)
    if (oh, ow) == (h, w):
        return img
    dtype = img.dtype
    x = img.astype(np.float32)
    if interpolation == "nearest":
        ri = (np.arange(oh) * h / oh).astype(np.int64).clip(0, h - 1)
        ci = (np.arange(ow) * w / ow).astype(np.int64).clip(0, w - 1)
        out = x[ri][:, ci]
    else:  # bilinear, align_corners=False convention
        ry = (np.arange(oh) + 0.5) * h / oh - 0.5
        rx = (np.arange(ow) + 0.5) * w / ow - 0.5
        y0 = np.floor(ry).astype(np.int64)
        x0 = np.floor(rx).astype(np.int64)
        wy = (ry - y0)[:, None, None]
        wx = (rx - x0)[None, :, None]
        y0c = y0.clip(0, h - 1)
        y1c = (y0 + 1).clip(0, h - 1)
        x0c = x0.clip(0, w - 1)
        x1c = (x0 + 1).clip(0, w - 1)
        out = ((1 - wy) * (1 - wx) * x[y0c][:, x0c]
               + (1 - wy) * wx * x[y0c][:, x1c]
               + wy * (1 - wx) * x[y1c][:, x0c]
               + wy * wx * x[y1c][:, x1c])
    if np.issubdtype(dtype, np.integer):
        out = np.round(out).clip(np.iinfo(dtype).min,
                                 np.iinfo(dtype).max).astype(dtype)
    else:
        out = out.astype(dtype)
    return out


def crop(img, top: int, left: int, height: int, width: int) -> np.ndarray:
    img = _as_hwc(img)
    return img[top:top + height, left:left + width]


def center_crop(img, output_size) -> np.ndarray:
    img = _as_hwc(img)
    th, tw = _pair(output_size)
    h, w = img.shape[:2]
    top = max(0, (h - th) // 2)
    left = max(0, (w - tw) // 2)
    return crop(img, top, left, th, tw)


def hflip(img) -> np.ndarray:
    return _as_hwc(img)[:, ::-1]


def vflip(img) -> np.ndarray:
    return _as_hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode: str = "constant") -> np.ndarray:
    img = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pt = pr = pb = int(padding)
    elif len(padding) == 2:
        pl = pr = int(padding[0])
        pt = pb = int(padding[1])
    else:
        pl, pt, pr, pb = (int(p) for p in padding)
    pads = [(pt, pb), (pl, pr), (0, 0)]
    if padding_mode == "constant":
        return np.pad(img, pads, mode="constant", constant_values=fill)
    return np.pad(img, pads, mode=padding_mode)


def normalize(img, mean, std, data_format: str = "CHW") -> np.ndarray:
    img = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def to_tensor(img, data_format: str = "CHW") -> np.ndarray:
    """HWC [0,255] uint8 (or float) -> CHW float32 [0,1]."""
    img = _as_hwc(img)
    out = img.astype(np.float32)
    if np.issubdtype(np.asarray(img).dtype, np.integer):
        out = out / 255.0
    if data_format == "CHW":
        out = out.transpose(2, 0, 1)
    return out


# ---------------------------------------------------------------- classes
class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class Resize:
    def __init__(self, size, interpolation: str = "bilinear"):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop:
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed: bool = False,
                 fill=0):
        self.size = _pair(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill

    def __call__(self, img):
        img = _as_hwc(img)
        if self.padding is not None:
            img = pad(img, self.padding, self.fill)
        th, tw = self.size
        h, w = img.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            # pad() tuple order is (left, top, right, bottom)
            img = pad(img, (0, 0, max(0, tw - w), max(0, th - h)), self.fill)
            h, w = img.shape[:2]
        top = np.random.randint(0, h - th + 1)
        left = np.random.randint(0, w - tw + 1)
        return crop(img, top, left, th, tw)


class RandomHorizontalFlip:
    def __init__(self, prob: float = 0.5):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if np.random.rand() < self.prob else _as_hwc(img)


class RandomVerticalFlip:
    def __init__(self, prob: float = 0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if np.random.rand() < self.prob else _as_hwc(img)


class Normalize:
    def __init__(self, mean, std, data_format: str = "CHW"):
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class ToTensor:
    def __init__(self, data_format: str = "CHW"):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return _as_hwc(img).transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode: str = "constant"):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform:
    def __init__(self, value: float):
        self.value = float(value)

    def __call__(self, img):
        img = _as_hwc(img)
        factor = 1.0 + np.random.uniform(-self.value, self.value)
        dtype = img.dtype
        out = img.astype(np.float32) * factor
        if np.issubdtype(dtype, np.integer):
            out = out.clip(0, 255)
        return out.astype(dtype)


# -------------------------------------------------- r4: remaining surface
def _clip_like(out, dtype):
    if np.issubdtype(dtype, np.integer):
        info = np.iinfo(dtype)
        out = np.round(out).clip(info.min, info.max)
    return out.astype(dtype)


def adjust_brightness(img, brightness_factor: float):
    img = _as_hwc(img)
    return _clip_like(img.astype(np.float32) * brightness_factor, img.dtype)


def adjust_contrast(img, contrast_factor: float):
    img = _as_hwc(img)
    f = img.astype(np.float32)
    gray_mean = f.mean() if img.shape[-1] == 1 else \
        (f @ np.asarray([0.299, 0.587, 0.114], np.float32)).mean()
    out = gray_mean + contrast_factor * (f - gray_mean)
    return _clip_like(out, img.dtype)


def to_grayscale(img, num_output_channels: int = 1):
    img = _as_hwc(img)
    f = img.astype(np.float32)
    g = f @ np.asarray([0.299, 0.587, 0.114], np.float32) \
        if img.shape[-1] == 3 else f[..., 0]
    g = g[..., None]
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=-1)
    return _clip_like(g, img.dtype)


def _rgb_to_hsv(f):
    mx, mn = f.max(-1), f.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    h = np.where(mx == r, (g - b) / diff % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4))
    h = h / 6.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    return h, s, mx


def _hsv_to_rgb(h, s, v):
    h6 = h * 6.0
    i = np.floor(h6) % 6
    f = h6 - np.floor(h6)
    p, q, t = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
    conds = [(i == k)[..., None] for k in range(6)]
    out = np.select(
        conds,
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return out


def adjust_hue(img, hue_factor: float):
    """hue_factor in [-0.5, 0.5] (reference adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    img = _as_hwc(img)
    scale = 255.0 if np.issubdtype(img.dtype, np.integer) else 1.0
    f = img.astype(np.float32) / scale
    h, s, v = _rgb_to_hsv(f)
    out = _hsv_to_rgb((h + hue_factor) % 1.0, s, v) * scale
    return _clip_like(out, img.dtype)


def adjust_saturation(img, saturation_factor: float):
    img = _as_hwc(img)
    gray = to_grayscale(img, 3).astype(np.float32)
    out = gray + saturation_factor * (img.astype(np.float32) - gray)
    return _clip_like(out, img.dtype)


def erase(img, i: int, j: int, h: int, w: int, v, inplace: bool = False):
    """Fill the [i:i+h, j:j+w] patch with ``v`` (reference ``erase``)."""
    img = _as_hwc(img)
    out = img if inplace else img.copy()
    out[i:i + h, j:j + w] = np.asarray(v, dtype=img.dtype)
    return out


def _inverse_warp(img, inv_matrix, fill=0, interpolation="bilinear",
                  out_hw=None):
    """Sample img (HWC) through a 3x3 INVERSE homography
    (bilinear/nearest); ``out_hw`` sets the output canvas (expand)."""
    img = _as_hwc(img)
    H, W = img.shape[:2]
    Ho, Wo = out_hw or (H, W)
    ys, xs = np.meshgrid(np.arange(Ho), np.arange(Wo), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1).astype(np.float32)
    src = inv_matrix @ coords
    sx = src[0] / src[2]
    sy = src[1] / src[2]

    def gather(yy, xx):
        inb = (xx >= 0) & (xx < W) & (yy >= 0) & (yy < H)
        val = img[yy.clip(0, H - 1), xx.clip(0, W - 1)].astype(np.float32)
        val[~inb] = fill
        return val

    if interpolation == "nearest":
        # exact source texels: label/mask-safe (no class blending)
        out = gather(np.round(sy).astype(np.int64),
                     np.round(sx).astype(np.int64))
        return _clip_like(out.reshape((Ho, Wo, img.shape[2])), img.dtype)
    x0 = np.floor(sx).astype(np.int64)
    y0 = np.floor(sy).astype(np.int64)
    wx = (sx - x0)[:, None]
    wy = (sy - y0)[:, None]
    out = (gather(y0, x0) * (1 - wx) * (1 - wy)
           + gather(y0, x0 + 1) * wx * (1 - wy)
           + gather(y0 + 1, x0) * (1 - wx) * wy
           + gather(y0 + 1, x0 + 1) * wx * wy)
    return _clip_like(out.reshape((Ho, Wo, img.shape[2])), img.dtype)


def _affine_forward(angle, translate, scale, shear, center):
    """Forward map: T(center+translate) @ R @ Shear @ Scale @
    T(-center) — shear is a real x/y skew (tangent terms), not folded
    into the rotation."""
    a = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    cx, cy = center
    tx, ty = translate
    rot = np.asarray([[np.cos(a), -np.sin(a), 0],
                      [np.sin(a), np.cos(a), 0], [0, 0, 1]], np.float32)
    sh = np.asarray([[1.0, np.tan(sx), 0], [np.tan(sy), 1.0, 0],
                     [0, 0, 1]], np.float32)
    scl = np.diag([scale, scale, 1.0]).astype(np.float32)

    def trans(x, y):
        m = np.eye(3, dtype=np.float32)
        m[0, 2], m[1, 2] = x, y
        return m

    return trans(cx + tx, cy + ty) @ rot @ sh @ scl @ trans(-cx, -cy)


def _affine_inverse(angle, translate, scale, shear, center):
    return np.linalg.inv(
        _affine_forward(angle, translate, scale, shear, center))


def rotate(img, angle, interpolation="bilinear", expand=False, center=None,
           fill=0):
    img = _as_hwc(img)
    H, W = img.shape[:2]
    c = center or ((W - 1) / 2.0, (H - 1) / 2.0)
    out_hw = None
    fwd = _affine_forward(-angle, (0, 0), 1.0, (0, 0), c)
    if expand:
        a = np.deg2rad(angle)
        Wo = int(np.ceil(abs(W * np.cos(a)) + abs(H * np.sin(a))))
        Ho = int(np.ceil(abs(H * np.cos(a)) + abs(W * np.sin(a))))
        # recenter so the rotated content lands on the enlarged canvas
        fwd = _affine_forward(-angle, ((Wo - W) / 2.0, (Ho - H) / 2.0),
                              1.0, (0, 0), c)
        out_hw = (Ho, Wo)
    return _inverse_warp(img, np.linalg.inv(fwd), fill, interpolation,
                         out_hw)


def affine(img, angle, translate, scale, shear, interpolation="bilinear",
           fill=0, center=None):
    img = _as_hwc(img)
    H, W = img.shape[:2]
    if not isinstance(shear, (tuple, list)):
        shear = (shear, 0.0)
    c = center or ((W - 1) / 2.0, (H - 1) / 2.0)
    return _inverse_warp(
        img, _affine_inverse(-angle, tuple(translate), scale, shear, c),
        fill, interpolation)


def perspective(img, startpoints, endpoints, interpolation="bilinear",
                fill=0):
    """Warp so ``startpoints`` map onto ``endpoints`` (reference
    ``perspective``); solves the 8-dof homography."""
    a, b = [], []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        a.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
        b += [ex, ey]
    h = np.linalg.solve(np.asarray(a, np.float32),
                        np.asarray(b, np.float32))
    fwd = np.append(h, 1.0).reshape(3, 3)
    return _inverse_warp(_as_hwc(img), np.linalg.inv(fwd), fill,
                         interpolation)


class BaseTransform:
    """Reference ``BaseTransform``: subclasses implement ``_apply_image``
    (and optionally ``_apply_*`` for other keys)."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, image):
        raise NotImplementedError

    def _dispatch(self, key, value):
        fn = getattr(self, f"_apply_{key}", None)
        if fn is not None:
            return fn(value)
        return value  # entries with no _apply_<key> pass through untouched

    def __call__(self, inputs):
        if isinstance(inputs, (list, tuple)):
            out = [self._dispatch(k, v) for k, v in zip(self.keys, inputs)]
            out += list(inputs[len(self.keys):])  # extras pass through
            return type(inputs)(out)
        return self._apply_image(inputs)


class ContrastTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        return adjust_contrast(
            img, 1.0 + np.random.uniform(-self.value, self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        return adjust_saturation(
            img, 1.0 + np.random.uniform(-self.value, self.value))


class HueTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        for i in np.random.permutation(len(self.transforms)):
            img = self.transforms[int(i)](img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels: int = 1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="bilinear", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if not isinstance(degrees, (tuple, list)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.center, self.fill = center, fill

    def _apply_image(self, img):
        return rotate(img, np.random.uniform(*self.degrees),
                      center=self.center, fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="bilinear", fill=0, center=None, keys=None):
        super().__init__(keys)
        if not isinstance(degrees, (tuple, list)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees, self.translate = degrees, translate
        self.scale, self.shear = scale, shear
        self.fill, self.center = fill, center

    def _apply_image(self, img):
        img = _as_hwc(img)
        H, W = img.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * W
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * H
        scale = np.random.uniform(*self.scale) if self.scale else 1.0
        if self.shear is None:
            shear = (0.0, 0.0)
        elif len(self.shear) == 4:  # (min_x, max_x, min_y, max_y)
            shear = (np.random.uniform(self.shear[0], self.shear[1]),
                     np.random.uniform(self.shear[2], self.shear[3]))
        else:
            shear = (np.random.uniform(*self.shear), 0.0)
        return affine(img, angle, (tx, ty), scale, shear, fill=self.fill,
                      center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob: float = 0.5, distortion_scale: float = 0.5,
                 interpolation="bilinear", fill=0, keys=None):
        super().__init__(keys)
        self.prob, self.distortion = prob, distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        img = _as_hwc(img)
        if np.random.random() >= self.prob:
            return img
        H, W = img.shape[:2]
        d = self.distortion
        dx, dy = int(W * d / 2), int(H * d / 2)

        def jitter(x, y, sx, sy):
            return (x + sx * np.random.randint(0, dx + 1),
                    y + sy * np.random.randint(0, dy + 1))

        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        end = [jitter(0, 0, 1, 1), jitter(W - 1, 0, -1, 1),
               jitter(W - 1, H - 1, -1, -1), jitter(0, H - 1, 1, -1)]
        return perspective(img, start, end, fill=self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob: float = 0.5, scale=(0.02, 0.33),
                 ratio=(0.3, 3.3), value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        img = _as_hwc(img)
        if np.random.random() >= self.prob:
            return img
        H, W = img.shape[:2]
        for _ in range(10):
            area = H * W * np.random.uniform(*self.scale)
            ratio = np.exp(np.random.uniform(*np.log(self.ratio)))
            h = int(round(np.sqrt(area * ratio)))
            w = int(round(np.sqrt(area / ratio)))
            if h < H and w < W:
                i = np.random.randint(0, H - h + 1)
                j = np.random.randint(0, W - w + 1)
                return erase(img, i, j, h, w, self.value, self.inplace)
        return img


class RandomResizedCrop(BaseTransform):
    """Random area/aspect crop resized to ``size`` (the ImageNet aug)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = _pair(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        H, W = img.shape[:2]
        for _ in range(10):
            area = H * W * np.random.uniform(*self.scale)
            ratio = np.exp(np.random.uniform(*np.log(self.ratio)))
            w = int(round(np.sqrt(area * ratio)))
            h = int(round(np.sqrt(area / ratio)))
            if 0 < h <= H and 0 < w <= W:
                top = np.random.randint(0, H - h + 1)
                left = np.random.randint(0, W - w + 1)
                return resize(crop(img, top, left, h, w), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(H, W)), self.size,
                      self.interpolation)


__all__ += ["BaseTransform", "ColorJitter", "ContrastTransform",
            "SaturationTransform", "HueTransform", "Grayscale",
            "RandomAffine", "RandomErasing", "RandomPerspective",
            "RandomResizedCrop", "RandomRotation", "adjust_brightness",
            "adjust_contrast", "adjust_hue", "adjust_saturation", "affine",
            "erase", "perspective", "rotate", "to_grayscale"]
