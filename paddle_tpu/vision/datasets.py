"""Vision datasets.

Reference parity: ``python/paddle/vision/datasets/`` (MNIST/Cifar/
ImageFolder/DatasetFolder/Flowers). Zero-egress environment: the
downloadable datasets accept a local ``data_file``/``data_dir`` and raise a
clear error when absent (no network fetch); ``FakeData`` provides the
synthetic stand-in the reference uses in CI.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..io.dataset import Dataset

__all__ = ["FakeData", "MNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder"]

_IMG_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".npy")


class FakeData(Dataset):
    """Deterministic synthetic images (reference CI stand-in)."""

    def __init__(self, num_samples: int = 128,
                 image_shape: Sequence[int] = (3, 32, 32),
                 num_classes: int = 10, transform: Optional[Callable] = None,
                 seed: int = 0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        img = rng.integers(0, 256, self.image_shape, np.uint8)
        label = np.int64(rng.integers(self.num_classes))
        if self.transform:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    """IDX-format reader (``vision/datasets/mnist.py``); pass local
    ``image_path``/``label_path`` (.gz or raw idx)."""

    def __init__(self, image_path: str, label_path: str, mode: str = "train",
                 transform: Optional[Callable] = None,
                 backend: str = "cv2"):
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)
        assert len(self.images) == len(self.labels)

    @staticmethod
    def _open(path: str):
        if path.endswith(".gz"):
            return gzip.open(path, "rb")
        return open(path, "rb")

    def _read_images(self, path: str) -> np.ndarray:
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad MNIST image magic {magic} in {path}")
            data = np.frombuffer(f.read(n * rows * cols), np.uint8)
            return data.reshape(n, rows, cols)

    def _read_labels(self, path: str) -> np.ndarray:
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad MNIST label magic {magic} in {path}")
            return np.frombuffer(f.read(n), np.uint8).astype(np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar10(Dataset):
    """Reads the python-pickle CIFAR tarball from a local ``data_file``
    (``vision/datasets/cifar.py`` minus the downloader)."""

    _TRAIN_MEMBERS = [f"data_batch_{i}" for i in range(1, 6)]
    _TEST_MEMBERS = ["test_batch"]
    _LABEL_KEY = b"labels"

    def __init__(self, data_file: str, mode: str = "train",
                 transform: Optional[Callable] = None):
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{data_file} not found; download the CIFAR python tarball "
                f"out-of-band (no network access here)")
        members = (self._TRAIN_MEMBERS if mode == "train"
                   else self._TEST_MEMBERS)
        images, labels = [], []
        with tarfile.open(data_file) as tar:
            for m in tar.getmembers():
                base = os.path.basename(m.name)
                if base in members:
                    d = pickle.load(tar.extractfile(m), encoding="bytes")
                    images.append(d[b"data"].reshape(-1, 3, 32, 32))
                    labels.extend(d[self._LABEL_KEY])
        self.images = np.concatenate(images)
        self.labels = np.asarray(labels, np.int64)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].transpose(1, 2, 0)  # HWC uint8
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar100(Cifar10):
    _TRAIN_MEMBERS = ["train"]
    _TEST_MEMBERS = ["test"]
    _LABEL_KEY = b"fine_labels"


class DatasetFolder(Dataset):
    """class-per-subdir layout (``vision/datasets/folder.py``); .npy or
    image files (image decoding needs an out-of-band loader arg)."""

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions: Sequence[str] = _IMG_EXTS,
                 transform: Optional[Callable] = None):
        self.root = root
        self.loader = loader or self._default_loader
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class subdirectories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: List[Tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path: str):
        if path.endswith(".npy"):
            return np.load(path)
        raise ValueError(
            f"no builtin decoder for {path}; pass loader= (e.g. PIL/cv2)")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, np.int64(label)


class ImageFolder(Dataset):
    """Unlabeled flat/recursive image list (reference ``ImageFolder``)."""

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions: Sequence[str] = _IMG_EXTS,
                 transform: Optional[Callable] = None):
        self.loader = loader or DatasetFolder._default_loader
        self.transform = transform
        self.samples: List[str] = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                if fname.lower().endswith(tuple(extensions)):
                    self.samples.append(os.path.join(dirpath, fname))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform:
            img = self.transform(img)
        return (img,)


class FashionMNIST(MNIST):
    """Same IDX wire format as MNIST (reference ``fashion_mnist.py``);
    point image_path/label_path at the Fashion-MNIST archives."""


class Flowers(Dataset):
    """Flowers-102 (reference ``flowers.py``): local extracted archive —
    ``data_file`` is the image directory (image_%05d.jpg), ``label_file``
    the imagelabels .mat, ``setid_file`` the split ids .mat."""

    def __init__(self, data_file: str, label_file: str, setid_file: str,
                 mode: str = "train", transform: Optional[Callable] = None,
                 backend: str = "pil"):
        import scipy.io as sio

        self.transform = transform
        self.data_dir = data_file
        labels = sio.loadmat(label_file)["labels"].reshape(-1)
        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.ids = setid[key].reshape(-1)
        self.labels = labels

    def __len__(self):
        return len(self.ids)

    def __getitem__(self, idx):
        import os

        from PIL import Image

        img_id = int(self.ids[idx])
        path = os.path.join(self.data_dir, f"image_{img_id:05d}.jpg")
        img = np.asarray(Image.open(path))
        if self.transform:
            img = self.transform(img)
        return img, np.int64(self.labels[img_id - 1] - 1)


class VOC2012(Dataset):
    """VOC2012 segmentation pairs (reference ``voc2012.py``): point
    ``data_file`` at the extracted VOCdevkit/VOC2012 directory."""

    def __init__(self, data_file: str, mode: str = "train",
                 transform: Optional[Callable] = None,
                 backend: str = "pil"):
        import os

        self.transform = transform
        self.root = data_file
        split = {"train": "train", "valid": "val", "test": "val",
                 "trainval": "trainval"}[mode]
        list_path = os.path.join(self.root, "ImageSets", "Segmentation",
                                 f"{split}.txt")
        with open(list_path) as f:
            self.names = [ln.strip() for ln in f if ln.strip()]

    def __len__(self):
        return len(self.names)

    def __getitem__(self, idx):
        import os

        from PIL import Image

        name = self.names[idx]
        img = np.asarray(Image.open(
            os.path.join(self.root, "JPEGImages", f"{name}.jpg")))
        seg = np.asarray(Image.open(
            os.path.join(self.root, "SegmentationClass", f"{name}.png")))
        if self.transform:
            img = self.transform(img)
        return img, seg


__all__ += ["FashionMNIST", "Flowers", "VOC2012"]
