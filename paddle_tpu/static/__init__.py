"""paddle_tpu.static — the static-graph API surface, collapsed.

Reference parity: ``python/paddle/static/`` (Program/Executor over the
C++ ``ProgramDesc`` + ``InterpreterCore``). SURVEY §7 stance: the static
graph IS the traced function here — ``to_static`` captures it, ``jit``
compiles it, ``jit.save`` serializes it as StableHLO. This module keeps
the names ported scripts reach for:

- the pieces with a direct collapsed equivalent work:
  ``InputSpec``, ``save_inference_model`` / ``load_inference_model``
  (jit.save/load + Predictor), ``default_main_program`` (a no-op token),
  ``name_scope`` / ``program_guard`` (no-op contexts — naming/graph
  scoping has no analogue in jaxprs);
- the op-append machinery (``Program.block().append_op`` style) CANNOT
  be emulated without the whole fluid op system, so ``Program`` /
  ``Executor.run`` raise a clear migration error instead of failing
  somewhere deep.
"""
from __future__ import annotations

import contextlib
from typing import Optional, Sequence

from ..hapi.model import InputSpec  # noqa: F401  (paddle.static.InputSpec)

__all__ = ["InputSpec", "Program", "Executor", "default_main_program",
           "default_startup_program", "program_guard", "name_scope",
           "save_inference_model", "load_inference_model", "data",
           "CompiledProgram"]

_MIGRATE = (
    "paddle_tpu has ONE execution model: python functions traced by jax "
    "and compiled by XLA. Port static-graph code by writing the forward "
    "as a function/Layer and using paddle_tpu.jit.to_static (control "
    "flow converts automatically), TrainStep (training), or "
    "paddle_tpu.inference (serving). Program/Executor op-append "
    "emulation is deliberately not provided."
)


class Program:
    """Placeholder token: exists so `default_main_program()` comparisons
    and `program_guard` blocks parse; any op-level use raises."""

    def global_block(self):
        raise NotImplementedError(_MIGRATE)

    def block(self, *a, **kw):
        raise NotImplementedError(_MIGRATE)

    def clone(self, for_test: bool = False):
        return self


_main = Program()
_startup = Program()


def default_main_program() -> Program:
    return _main


def default_startup_program() -> Program:
    return _startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    yield


@contextlib.contextmanager
def name_scope(prefix: Optional[str] = None):
    yield


def data(name: str, shape: Sequence[int], dtype: str = "float32",
         lod_level: int = 0):
    """``paddle.static.data`` -> an InputSpec (the collapsed 'placeholder':
    feed it to ``to_static``/``jit.save`` input_spec)."""
    return InputSpec(list(shape), dtype=dtype, name=name)


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, *a, **kw):
        raise NotImplementedError(_MIGRATE)


class CompiledProgram:
    def __init__(self, *a, **kw):
        raise NotImplementedError(_MIGRATE)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars,
                         executor=None, **kwargs):
    """Collapsed ``save_inference_model``: ``fetch_vars`` is the Layer (or
    ``to_static`` wrapper) whose forward produces the outputs, and
    ``feed_vars`` its InputSpecs; the artifact is the same
    StableHLO+params pair ``paddle_tpu.jit.save`` writes and the
    Predictor/C API serve."""
    from ..jit import save as jit_save

    if not isinstance(feed_vars, (list, tuple)):
        feed_vars = [feed_vars]
    return jit_save(fetch_vars, path_prefix, input_spec=list(feed_vars))


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns a callable loaded program (TranslatedLayer) — the collapsed
    (program, feed_names, fetch_names) triple."""
    from ..jit import load as jit_load

    return jit_load(path_prefix)
