"""paddle_tpu.lora — multi-tenant low-rank adaptation.

Per-tenant fine-tuned models WITHOUT per-tenant replicas: adapters train
against a frozen base (``Model.fit(lora=LoraConfig(...))`` — optimizer
state scales with the rank, not the model), persist as tiny crash-safe
checkpoints (``save_adapter``/``load_adapter`` with a ``lora_adapter``
metadata record pinning the base-model fingerprint), stack into a
device-resident page buffer (:class:`AdapterStore`, LRU rows, load/evict
= buffer update), and serve batched — every slot of the continuous-
batching engine gathers its own ``(A, B)`` pages in-program, so ONE
compiled decode program serves every tenant plus the base model (page
row 0 = the zero adapter). See README "Multi-tenant LoRA serving".

    from paddle_tpu.lora import LoraConfig, AdapterStore, apply_lora

    apply_lora(lm, LoraConfig(rank=8))
    Model(lm).fit(train_data, lora=LoraConfig(rank=8))   # adapter-only fit
    save_adapter("adapters/tenant-a", lm)

    store = AdapterStore(lm, max_loaded=32)
    store.load("tenant-a", "adapters/tenant-a")
    srv = InferenceServer(lm, slots=8, adapter_store=store).start()
    srv.submit(prompt, adapter_id="tenant-a")
"""
from .layers import (LoraConfig, adapter_rows, applied_config,  # noqa: F401
                     apply_lora, base_fingerprint, clear_adapter,
                     is_lora_param, lora_paths, lora_state, set_adapter)
from .store import (ADAPTER_FORMAT, AdapterError,  # noqa: F401
                    AdapterFormatError, AdapterStore, adapter_metadata,
                    load_adapter, normalize_adapter_id, save_adapter)

__all__ = [
    "LoraConfig", "apply_lora", "applied_config", "lora_paths",
    "lora_state", "set_adapter", "clear_adapter", "is_lora_param",
    "base_fingerprint", "adapter_rows", "AdapterStore", "AdapterError",
    "AdapterFormatError", "ADAPTER_FORMAT", "save_adapter", "load_adapter",
    "adapter_metadata", "normalize_adapter_id",
]
